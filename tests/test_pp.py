"""Pipeline parallelism: the GPipe fill-drain schedule over the
differentiable Isend/Irecv/Wait transport must reproduce the sequential
(single-process) composition exactly — loss AND per-stage parameter
gradients, which arrive over the reverse pipeline (§2.5 PP row;
reverse-flow discipline reference csrc/extension.cpp:1159-1218)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm
from mpi4torch_tpu.parallel import pipeline_step

NR = 4
N_MB, B, D = 3, 2, 6


def make_stages(seed=0):
    rng = np.random.default_rng(seed)
    stages = [{
        "w": jnp.asarray(rng.standard_normal((D, D)) / np.sqrt(D)),
        "b": jnp.asarray(rng.standard_normal(D) * 0.1),
    } for _ in range(NR)]
    mbs = [jnp.asarray(rng.standard_normal((B, D))) for _ in range(N_MB)]
    return stages, mbs


def apply_stage(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def loss_fn(y, i):
    return (i + 1.0) * jnp.sum(y ** 2)


def sequential_oracle(stages, mbs):
    def total(stages):
        s = 0.0
        for i, mb in enumerate(mbs):
            x = mb
            for p in stages:
                x = apply_stage(p, x)
            s = s + loss_fn(x, i)
        return s
    val = total(stages)
    grads = jax.grad(total)(stages)
    return np.asarray(val), grads


class TestPipeline:
    def test_loss_and_grads_match_sequential(self):
        stages, mbs = make_stages()
        val_d, g_d = sequential_oracle(stages, mbs)

        def body():
            r = int(comm.rank)
            loss, g = pipeline_step(
                comm, apply_stage, stages[r], mbs, loss_fn,
                recv_like=jnp.zeros((B, D)))
            return np.asarray(loss), jax.tree.map(np.asarray, g)

        outs = mpi.run_ranks(body, NR)
        for r in range(NR):
            loss, g = outs[r]
            np.testing.assert_allclose(loss, val_d, rtol=1e-12,
                                       err_msg=f"rank {r} loss")
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    g[k], np.asarray(g_d[r][k]), rtol=1e-9, atol=1e-12,
                    err_msg=f"stage {r} grad {k}")

    @pytest.mark.parametrize("nranks", [2, 5])
    def test_other_world_sizes(self, nranks):
        rng = np.random.default_rng(nranks)
        stages = [{
            "w": jnp.asarray(rng.standard_normal((D, D)) / np.sqrt(D)),
            "b": jnp.zeros(D),
        } for _ in range(nranks)]
        mbs = [jnp.asarray(rng.standard_normal((B, D))) for _ in range(2)]

        def total(stages):
            s = 0.0
            for i, mb in enumerate(mbs):
                x = mb
                for p in stages:
                    x = apply_stage(p, x)
                s = s + loss_fn(x, i)
            return s

        val_d = np.asarray(total(stages))
        g_d = jax.grad(total)(stages)

        def body():
            r = int(comm.rank)
            loss, g = pipeline_step(
                comm, apply_stage, stages[r], mbs, loss_fn,
                recv_like=jnp.zeros((B, D)))
            return np.asarray(loss), jax.tree.map(np.asarray, g)

        outs = mpi.run_ranks(body, nranks)
        for r in range(nranks):
            loss, g = outs[r]
            np.testing.assert_allclose(loss, val_d, rtol=1e-12)
            np.testing.assert_allclose(g["w"], np.asarray(g_d[r]["w"]),
                                       rtol=1e-9, atol=1e-12)

    def test_size_one_pipeline_is_sequential(self):
        stages, mbs = make_stages(3)
        val_d, g_d = sequential_oracle(stages[:1], mbs)

        def body():
            loss, g = pipeline_step(comm, apply_stage, stages[0], mbs,
                                    loss_fn)
            return np.asarray(loss), jax.tree.map(np.asarray, g)

        outs = mpi.run_ranks(body, 1)
        np.testing.assert_allclose(outs[0][0], val_d, rtol=1e-12)
        np.testing.assert_allclose(outs[0][1]["w"], np.asarray(g_d[0]["w"]),
                                   rtol=1e-10)

    def test_missing_recv_like_raises(self):
        stages, mbs = make_stages()
        with pytest.raises(ValueError, match="recv_like"):
            def body():
                return pipeline_step(comm, apply_stage,
                                     stages[int(comm.rank)], mbs, loss_fn)
            mpi.run_ranks(body, 2)

    def test_pipelined_training_converges(self):
        # A few SGD steps through the pipeline reduce the loss — the
        # end-to-end "PP training works" smoke test.
        stages, mbs = make_stages(9)

        def body():
            r = int(comm.rank)
            p = stages[r]
            losses = []
            for _ in range(5):
                loss, g = pipeline_step(
                    comm, apply_stage, p, mbs, loss_fn,
                    recv_like=jnp.zeros((B, D)))
                p = jax.tree.map(lambda a, b: a - 0.05 * b, p, g)
                losses.append(float(loss))
            return losses

        outs = mpi.run_ranks(body, NR)
        for losses in outs:
            assert losses[-1] < losses[0]


class Test1F1B:
    """The 1F1B schedule must reproduce the sequential oracle exactly
    (same per-stage grads as GPipe) while bounding in-flight activation
    stashes at min(size - rank, n_mb) instead of n_mb."""

    @pytest.mark.parametrize("nranks,n_mb", [(2, 4), (4, 6), (5, 5)])
    def test_loss_and_grads_match_sequential(self, nranks, n_mb):
        from mpi4torch_tpu.parallel import pipeline_step_1f1b

        rng = np.random.default_rng(nranks * 10 + n_mb)
        stages = [{
            "w": jnp.asarray(rng.standard_normal((D, D)) / np.sqrt(D)),
            "b": jnp.asarray(rng.standard_normal(D) * 0.1),
        } for _ in range(nranks)]
        mbs = [jnp.asarray(rng.standard_normal((B, D)))
               for _ in range(n_mb)]

        def total(stages):
            s = 0.0
            for i, mb in enumerate(mbs):
                x = mb
                for p in stages:
                    x = apply_stage(p, x)
                s = s + loss_fn(x, i)
            return s

        val_d = np.asarray(total(stages))
        g_d = jax.grad(total)(stages)

        def body():
            r = int(comm.rank)
            loss, g = pipeline_step_1f1b(
                comm, apply_stage, stages[r], mbs, loss_fn,
                recv_like=jnp.zeros((B, D)))
            return np.asarray(loss), jax.tree.map(np.asarray, g)

        outs = mpi.run_ranks(body, nranks)
        for r in range(nranks):
            loss, g = outs[r]
            np.testing.assert_allclose(loss, val_d, rtol=1e-12,
                                       err_msg=f"rank {r} loss")
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    g[k], np.asarray(g_d[r][k]), rtol=1e-9, atol=1e-12,
                    err_msg=f"stage {r} grad {k}")

    @pytest.mark.parametrize("size,n_mb", [(2, 4), (4, 8), (8, 3), (3, 1)])
    def test_schedule_properties(self, size, n_mb):
        from mpi4torch_tpu.parallel import schedule_1f1b

        for rank in range(size):
            ops = schedule_1f1b(rank, size, n_mb)
            # Every microbatch exactly one F and one B, in order.
            assert [i for op, i in ops if op == "F"] == list(range(n_mb))
            assert [i for op, i in ops if op == "B"] == list(range(n_mb))
            # B(i) follows F(i).
            pos = {(op, i): t for t, (op, i) in enumerate(ops)}
            for i in range(n_mb):
                assert pos[("B", i)] > pos[("F", i)]
            # The 1F1B bound: in-flight stashes never exceed
            # min(size - rank, n_mb).
            live = peak = 0
            for op, i in ops:
                live += 1 if op == "F" else -1
                peak = max(peak, live)
            assert peak <= min(size - rank, n_mb), (rank, peak)

    def test_shape_changing_stages(self):
        # Stages that change the activation width: the backward cotangent
        # for each rank is shaped like ITS OWN output (stashed out_aval),
        # not like recv_like — a widening/narrowing pipeline catches any
        # mix-up.  Widths: 6 -> 10 -> 4.
        from mpi4torch_tpu.parallel import pipeline_step_1f1b

        widths = [6, 10, 4]
        rng = np.random.default_rng(3)
        stages = [{"w": jnp.asarray(
            rng.standard_normal((widths[i], widths[i + 1]))
            / np.sqrt(widths[i]))} for i in range(2)]
        mbs = [jnp.asarray(rng.standard_normal((B, widths[0])))
               for _ in range(4)]

        def apply(p, x):
            return jnp.tanh(x @ p["w"])

        def total(stages):
            s = 0.0
            for i, mb in enumerate(mbs):
                x = mb
                for p in stages:
                    x = apply(p, x)
                s = s + loss_fn(x, i)
            return s

        val_d = np.asarray(total(stages))
        g_d = jax.grad(total)(stages)

        def body():
            r = int(comm.rank)
            loss, g = pipeline_step_1f1b(
                comm, apply, stages[r], mbs, loss_fn,
                recv_like=jnp.zeros((B, widths[r])))
            return np.asarray(loss), jax.tree.map(np.asarray, g)

        outs = mpi.run_ranks(body, 2)
        for r in range(2):
            loss, g = outs[r]
            np.testing.assert_allclose(loss, val_d, rtol=1e-12)
            np.testing.assert_allclose(g["w"], np.asarray(g_d[r]["w"]),
                                       rtol=1e-9, atol=1e-12)

    def test_size_one_is_sequential(self):
        from mpi4torch_tpu.parallel import pipeline_step_1f1b

        stages, mbs = make_stages(7)
        val_d, g_d = sequential_oracle(stages[:1], mbs)

        def body():
            loss, g = pipeline_step_1f1b(comm, apply_stage, stages[0],
                                         mbs, loss_fn)
            return np.asarray(loss), jax.tree.map(np.asarray, g)

        outs = mpi.run_ranks(body, 1)
        np.testing.assert_allclose(outs[0][0], val_d, rtol=1e-12)
        np.testing.assert_allclose(outs[0][1]["w"], np.asarray(g_d[0]["w"]),
                                   rtol=1e-10)


class TestPipelineSPMD:
    def test_scan_body_hlo_census(self):
        # The scan formulation must keep the compiled program O(1) in
        # n_mb and size: exactly ONE collective-permute (the ring hop)
        # in the whole lowered module, regardless of microbatch count —
        # an unrolled schedule would lower n_mb + size - 1 of them.
        from mpi4torch_tpu.parallel import pipeline_spmd, shard_axis

        for n_mb in (3, 9):
            stages, _ = make_stages(5)
            mbs = [jnp.zeros((B, D)) for _ in range(n_mb)]
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)

            def fn(stacked):
                local = jax.tree.map(
                    lambda a: shard_axis(comm, a, 0)[0], stacked)
                return pipeline_spmd(comm, apply_stage, local, mbs,
                                     loss_fn)

            # Lower via run_spmd's public path: jit of the shard_map'd fn.
            call = mpi.run_spmd(fn, nranks=NR)
            lowered = jax.jit(lambda s: call(s)).lower(stacked)
            hlo = lowered.as_text()
            n_cp = hlo.count("collective-permute(")
            if n_cp == 0:   # dialect variations
                n_cp = hlo.count("collective_permute")
            assert n_cp == 1, f"n_mb={n_mb}: {n_cp} collective permutes"

    def test_spmd_pipeline_matches_sequential(self):
        from mpi4torch_tpu.parallel import pipeline_spmd, shard_axis

        stages, mbs = make_stages(21)
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)

        def total_seq(stacked):
            s = 0.0
            for i, mb in enumerate(mbs):
                x = mb
                for r in range(NR):
                    p = jax.tree.map(lambda a: a[r], stacked)
                    x = apply_stage(p, x)
                s = s + loss_fn(x, i)
            return s

        val_d = np.asarray(total_seq(stacked))
        g_d = jax.tree.map(np.asarray, jax.grad(total_seq)(stacked))

        def fn(stacked):
            local = jax.tree.map(
                lambda a: shard_axis(comm, a, 0)[0], stacked)
            return pipeline_spmd(comm, apply_stage, local, mbs, loss_fn)

        out = mpi.run_spmd(fn, nranks=NR)(stacked)
        for r in range(NR):
            np.testing.assert_allclose(np.asarray(out[r]), val_d,
                                       rtol=1e-12)
        # out stacks NR identical losses; summing scales grads by NR.
        g = jax.grad(lambda s: mpi.run_spmd(fn, nranks=NR)(s).sum())(stacked)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(g[k]), NR * g_d[k], rtol=1e-9, atol=1e-12,
                err_msg=f"stacked grad {k}")


class TestInterleaved:
    """Interleaved virtual stages (Megatron-style): rank r owns chunks
    {r, size+r, 2*size+r, ...} of v*size global stages; loss and grads
    must equal the sequential oracle exactly."""

    @pytest.mark.parametrize("nranks,v,n_mb", [(2, 2, 3), (4, 2, 4),
                                               (2, 3, 2)])
    def test_loss_and_grads_match_sequential(self, nranks, v, n_mb):
        from mpi4torch_tpu.parallel import pipeline_step_interleaved

        n_stages = nranks * v
        rng = np.random.default_rng(nranks * 100 + v * 10 + n_mb)
        stages = [{
            "w": jnp.asarray(rng.standard_normal((D, D)) / np.sqrt(D)),
            "b": jnp.asarray(rng.standard_normal(D) * 0.1),
        } for _ in range(n_stages)]
        mbs = [jnp.asarray(rng.standard_normal((B, D)))
               for _ in range(n_mb)]
        val_d, g_d = sequential_oracle(stages, mbs)

        def body():
            r = int(comm.rank)
            # rank r's chunks are global stages r, size + r, ...
            mine = [stages[c * nranks + r] for c in range(v)]
            loss, g = pipeline_step_interleaved(
                comm, apply_stage, mine, mbs, loss_fn,
                recv_like=jnp.zeros((B, D)))
            return np.asarray(loss), jax.tree.map(np.asarray, g)

        outs = mpi.run_ranks(body, nranks)
        for r in range(nranks):
            loss, g = outs[r]
            np.testing.assert_allclose(loss, val_d, rtol=1e-12,
                                       err_msg=f"rank {r} loss")
            for c in range(v):
                for k in ("w", "b"):
                    np.testing.assert_allclose(
                        g[c][k], np.asarray(g_d[c * nranks + r][k]),
                        rtol=1e-9, atol=1e-12,
                        err_msg=f"rank {r} chunk {c} grad {k}")

    def test_size_one_is_sequential(self):
        from mpi4torch_tpu.parallel import pipeline_step_interleaved

        rng = np.random.default_rng(5)
        stages = [{
            "w": jnp.asarray(rng.standard_normal((D, D)) / np.sqrt(D)),
            "b": jnp.asarray(rng.standard_normal(D) * 0.1),
        } for _ in range(3)]
        mbs = [jnp.asarray(rng.standard_normal((B, D))) for _ in range(2)]
        val_d, g_d = sequential_oracle(stages, mbs)

        def body():
            loss, g = pipeline_step_interleaved(
                comm, apply_stage, stages, mbs, loss_fn)
            return np.asarray(loss), jax.tree.map(np.asarray, g)

        loss, g = mpi.run_ranks(body, 1)[0]
        np.testing.assert_allclose(loss, val_d, rtol=1e-12)
        for c in range(3):
            for k in ("w", "b"):
                np.testing.assert_allclose(g[c][k],
                                           np.asarray(g_d[c][k]),
                                           rtol=1e-9, atol=1e-12)

    def test_missing_recv_like_raises(self):
        from mpi4torch_tpu.parallel import pipeline_step_interleaved

        def body():
            with pytest.raises(ValueError, match="recv_like"):
                pipeline_step_interleaved(
                    comm, apply_stage, [{"w": jnp.eye(D)}],
                    [jnp.zeros((B, D))], loss_fn)
            return True

        assert all(mpi.run_ranks(body, 2))

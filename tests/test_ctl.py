"""Online self-tuning controller (ISSUE 19, mpi4torch_tpu.ctl).

The control loop in layers: the EWMA goodput estimator over synthetic
CommEvent streams (tier attribution == the census rule, cursor, codec
invariance), the two-watermark drift monitor (the no-flap hysteresis
property), the decision ledger, the config knobs
(validation/snapshot/fingerprint), the registry-sync guard, and the
REAL closed loop — a brownout driven through an epoch-fenced consensus
to the q8 winner and back, bitwise against the explicit-q8 oracle and
the pre-episode exact result, on the (8,) and (2,2,2) stacks over the
thread AND process transports.  ``make ctl-smoke`` runs the standalone
lane over the same surface.
"""

import json

import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import config, ctl, obs, tune
from mpi4torch_tpu.analyze.registry import ctl_problems
from mpi4torch_tpu.compress import get_codec
from mpi4torch_tpu.ctl.__main__ import (closed_loop_episode,
                                        synthetic_event,
                                        synthetic_round)
from mpi4torch_tpu.ctl.controller import SelfTuningController
from mpi4torch_tpu.elastic.membership import StaleEpochError

NR = 8
TIERS = (2, 2, 2)


@pytest.fixture(autouse=True)
def _isolated_state(tmp_path, monkeypatch):
    monkeypatch.setenv("MPI4TORCH_TPU_TUNE_CACHE",
                       str(tmp_path / "tune_cache.json"))
    from mpi4torch_tpu.csched import synth as S
    snap = config.snapshot_process_state()
    tune.clear()
    S.clear_installed()
    yield
    config.apply_process_state(snap)
    config.set_fault_plan(None)
    tune.clear()
    S.clear_installed()


# ---------------------------------------------------------------------------
# Estimator
# ---------------------------------------------------------------------------

class TestEstimator:
    def test_ewma_halflife_math(self):
        e = ctl.Ewma(1.0)                      # alpha = 1/2
        assert e.update(4.0) == 4.0            # first sample adopted
        assert e.update(2.0) == pytest.approx(3.0)
        e4 = ctl.Ewma(4.0)
        e4.update(1.0)
        for _ in range(4):                     # one half-life of samples
            e4.update(0.0)
        assert e4.value == pytest.approx(0.5)

    def test_tier_attribution_is_the_census_rule(self):
        est = ctl.BandwidthEstimator(TIERS, halflife=1.0)
        # group of 2 -> innermost tier, 4 -> middle, whole-world (and
        # None) -> top: csched.tier_of_group on the measured stream.
        est.ingest([synthetic_event(0, 0, 1e6, group_size=2),
                    synthetic_event(1, 0, 2e6, group_size=4),
                    synthetic_event(2, 0, 3e6, group_size=None),
                    synthetic_event(3, 0, 3e6, group_size=8)])
        assert est.tier_estimates() == pytest.approx((1e6, 2e6, 3e6))
        assert est.tier_samples() == (1, 1, 2)

    def test_cursor_never_double_counts(self):
        est = ctl.BandwidthEstimator(TIERS, halflife=1.0)
        events = synthetic_round(0, 1e6)
        assert est.ingest(events) == NR
        assert est.ingest(events) == 0         # same seqs: no-op
        assert est.ingest(events + [synthetic_event(NR, 0, 5e5)]) == 1
        assert est.tier_estimates()[-1] == pytest.approx(7.5e5)

    def test_filters(self):
        est = ctl.BandwidthEstimator(TIERS, halflife=1.0)
        n = est.ingest([
            synthetic_event(0, 0, 9e9, bookkeeping=True),
            synthetic_event(1, 0, 9e9, status="Timeout"),
            synthetic_event(2, 0, 9e9, channel="p2p_send"),
            synthetic_event(3, 0, 9e9, nbytes=0),
        ])
        assert n == 0
        assert est.tier_estimates() == (None, None, None)

    def test_goodput_is_codec_invariant(self):
        # A q8 event's encoded bytes scale back to LOGICAL bytes by
        # the codec's own wire accounting, so the estimate reads the
        # same bandwidth whether the wire is exact or compressed.
        wire = get_codec("q8").wire_bytes((4096,), "float32")
        factor = (4096 * 4) / wire
        ev = synthetic_event(0, 0, 1e6, nbytes=wire, codec="q8")
        assert ctl.goodput_bytes(ev) == pytest.approx(wire * factor)
        exact = synthetic_event(1, 0, 1e6)
        assert ctl.goodput_bytes(exact) == exact.payload_bytes
        # Unregistered codec: degrade to encoded bytes, never raise.
        odd = synthetic_event(2, 0, 1e6, codec="no-such-codec")
        assert ctl.goodput_bytes(odd) == odd.payload_bytes

    def test_per_link_estimates(self):
        est = ctl.BandwidthEstimator(TIERS, halflife=1.0)
        est.ingest(synthetic_round(0, 1e6))
        links = est.link_estimates()
        assert sorted(links) == list(range(NR))
        assert all(v == pytest.approx(1e6) for v in links.values())


# ---------------------------------------------------------------------------
# Drift monitor
# ---------------------------------------------------------------------------

class TestDriftMonitor:
    def _calibrated(self, low=0.5, high=0.8, patience=2):
        est = ctl.BandwidthEstimator(TIERS, halflife=1.0)
        mon = ctl.DriftMonitor(len(TIERS), low=low, high=high,
                               patience=patience)
        est.ingest(synthetic_round(0, 1e6))
        mon.calibrate(est)
        return est, mon

    def test_no_flap_inside_the_band(self):
        est, mon = self._calibrated()
        seq = NR
        for i in range(16):   # oscillate INSIDE the hysteresis band
            est.ingest(synthetic_round(seq, 0.55e6 if i % 2
                                       else 0.75e6))
            seq += NR
            rep = mon.check(est)
            assert rep.changed == {}
        assert mon.states == ("ok", "ok", "ok")

    def test_patience_gates_both_directions(self):
        est, mon = self._calibrated()
        est.ingest(synthetic_round(NR, 0.1e6))
        assert mon.check(est).changed == {}       # 1st sag: patience
        est.ingest(synthetic_round(2 * NR, 0.1e6))
        rep = mon.check(est)
        assert rep.changed == {2: "degraded"}     # 2nd consecutive
        assert rep.degraded == (2,) and not rep.ok
        est.ingest(synthetic_round(3 * NR, 1e6))
        assert mon.check(est).changed == {}       # 1st recovery
        est.ingest(synthetic_round(4 * NR, 1e6))
        assert mon.check(est).changed == {2: "ok"}

    def test_single_excursion_resets(self):
        est, mon = self._calibrated()
        est.ingest(synthetic_round(NR, 0.1e6))
        mon.check(est)
        est.ingest(synthetic_round(2 * NR, 1e6))   # back in band
        mon.check(est)
        est.ingest(synthetic_round(3 * NR, 0.1e6))
        assert mon.check(est).changed == {}        # counter was reset

    def test_uncalibrated_tier_self_calibrates(self):
        est = ctl.BandwidthEstimator(TIERS, halflife=1.0)
        mon = ctl.DriftMonitor(len(TIERS))
        mon.calibrate(est)                         # all-None baseline
        est.ingest([synthetic_event(0, 0, 1e6, group_size=2)])
        rep = mon.check(est)
        assert rep.ratios[0] == pytest.approx(1.0)  # first value IS
        assert mon.baseline[0] == pytest.approx(1e6)  # the baseline

    def test_as_reconcile_shape(self):
        est, mon = self._calibrated()
        rep = mon.check(est)
        doc = rep.as_reconcile()
        assert doc["ok"] and set(doc["matches"]) == {"tier0", "tier1",
                                                     "tier2"}
        assert doc["measured"] == list(rep.estimates)

    def test_live_bandwidths_mixes_sag_into_declared(self):
        est, mon = self._calibrated(patience=1)
        est.ingest(synthetic_round(NR, 0.5e6))
        rep = mon.check(est)
        live = ctl.live_bandwidths(rep, (4.0, 2.0, 1.0))
        assert live[:2] == (4.0, 2.0)              # unsampled: declared
        assert live[2] == pytest.approx(0.5, abs=0.01)  # sagged: scaled
        uniform = ctl.live_bandwidths(rep, None)
        assert uniform[2] == pytest.approx(0.5, abs=0.01)

    def test_threshold_validation(self):
        with pytest.raises(ValueError):
            ctl.DriftMonitor(3, low=0.8, high=0.5)
        with pytest.raises(ValueError):
            ctl.DriftMonitor(3, patience=0)
        with pytest.raises(ValueError):
            ctl.DriftMonitor(0)


# ---------------------------------------------------------------------------
# Config knobs
# ---------------------------------------------------------------------------

class TestConfigKnobs:
    def test_defaults_off(self):
        assert config.ctl_enabled() is False

    def test_validated_setters(self):
        with pytest.raises(ValueError):
            config.set_ctl_halflife(0.0)
        with pytest.raises(ValueError):
            config.set_ctl_drift_thresholds(0.8, 0.5)
        with pytest.raises(ValueError):
            config.set_ctl_drift_thresholds(0.0, 0.5)
        with pytest.raises(ValueError):
            config.set_ctl_drift_patience(0)
        with pytest.raises(ValueError):
            config.set_ctl_min_switch_epochs(-1)
        with pytest.raises(ValueError):
            config.set_ctl_codec_crossover(0.0)
        with pytest.raises(ValueError):
            config.set_ctl_codec_crossover(1.5)

    def test_snapshot_round_trips_ctl_knobs(self):
        config.set_ctl_enabled(True)
        config.set_ctl_halflife(2.5)
        config.set_ctl_drift_thresholds(0.2, 0.6)
        config.set_ctl_drift_patience(3)
        config.set_ctl_min_switch_epochs(4)
        config.set_ctl_codec_crossover(0.1)
        snap = config.snapshot_process_state()
        for k in ("ctl_enabled", "ctl_halflife", "ctl_drift_thresholds",
                  "ctl_drift_patience", "ctl_min_switch_epochs",
                  "ctl_codec_crossover"):
            assert k in snap
        config.set_ctl_enabled(False)
        config.set_ctl_halflife(4.0)
        config.set_ctl_drift_thresholds(0.5, 0.8)
        config.apply_process_state(snap)
        assert config.ctl_enabled() is True
        assert config.ctl_halflife() == 2.5
        assert config.ctl_drift_thresholds() == (0.2, 0.6)
        assert config.ctl_drift_patience() == 3
        assert config.ctl_min_switch_epochs() == 4
        assert config.ctl_codec_crossover() == 0.1

    def test_fingerprint_covers_ctl_knobs(self):
        fp = config.thresholds_fingerprint()
        config.set_ctl_halflife(9.0)
        fp2 = config.thresholds_fingerprint()
        assert fp != fp2
        config.set_ctl_drift_thresholds(0.11, 0.91)
        assert config.thresholds_fingerprint() != fp2
        # The mode_a tracer flag stays the LAST element (tests/test_obs
        # reads fingerprint[-1]) — ctl entries must sit before it.
        assert config.thresholds_fingerprint()[-1] is False


# ---------------------------------------------------------------------------
# Ledger + registry guard
# ---------------------------------------------------------------------------

class TestLedger:
    def test_record_validates_and_counts(self):
        led = ctl.DecisionLedger()
        d = led.record(3, "crossover", tier=2, ratio=0.1,
                       old={"winner": "a", "weighted_cost": 4.0},
                       new={"winner": "b", "codec": "synth_q8",
                            "weighted_cost": 1.0})
        assert d.epoch == 3 and d.trigger == "crossover"
        assert len(led) == 1 and led.triggers() == ["crossover"]
        with pytest.raises(ValueError):
            led.record(4, "vibes")

    def test_json_and_table(self, tmp_path):
        led = ctl.DecisionLedger()
        led.record(1, "drift", tier=0, ratio=0.42,
                   old={"winner": "synth:aa", "weighted_cost": 8.0},
                   new={"winner": "synth:bb", "codec": "synth",
                        "weighted_cost": 2.0})
        led.record(2, "recovery", new={"restored": ["compression"]})
        doc = json.loads(led.to_json())
        assert [d["trigger"] for d in doc["decisions"]] == \
            ["drift", "recovery"]
        path = led.dump(str(tmp_path / "ledger.json"))
        with open(path, "r", encoding="utf-8") as f:
            assert json.load(f) == doc
        table = led.format_table()
        assert "synth:bb[synth]" in table and "8->2" in table
        assert "restored:compression" in table

    def test_registry_guard_clean(self):
        assert ctl_problems() == []

    def test_registry_guard_fires_on_drift(self, monkeypatch):
        import mpi4torch_tpu.ctl.__main__ as ctl_main
        monkeypatch.setattr(ctl_main, "LEDGER_COVERED", ("drift",))
        probs = ctl_problems()
        assert probs and "coverage literal" in probs[0]

    def test_policy_map_delegates_to_registered_triggers(self):
        from mpi4torch_tpu.resilience.degrade import DEGRADE_POLICIES
        assert set(ctl.POLICY_TRIGGER) == set(DEGRADE_POLICIES)
        assert set(ctl.POLICY_TRIGGER.values()) <= set(ctl.TRIGGER_KINDS)


# ---------------------------------------------------------------------------
# Controller (synthetic streams)
# ---------------------------------------------------------------------------

class TestController:
    def _controller(self, **kw):
        config.set_ctl_enabled(True)
        config.set_ctl_halflife(1.0)
        config.set_ctl_drift_patience(2)
        return SelfTuningController(n_ranks=NR, tiers=TIERS,
                                    nbytes=1 << 14, persist=False, **kw)

    def test_tier_stack_must_factor_the_world(self):
        with pytest.raises(ctl.CtlError):
            SelfTuningController(n_ranks=NR, tiers=(2, 2))

    def test_disabled_poll_is_inert(self):
        c = SelfTuningController(n_ranks=NR, tiers=TIERS)
        before = config.snapshot_process_state()
        assert c.poll() is None
        assert c.poll(synthetic_round(0, 1.0)) is None
        assert config.snapshot_process_state() == before
        assert len(c.ledger) == 0
        assert c.estimator.tier_samples() == (0, 0, 0)

    def test_drift_rerank_installs_exact_winner(self):
        c = self._controller()
        try:
            c.observe(synthetic_round(0, 1e6))
            c.calibrate()
            assert c.poll(synthetic_round(NR, 0.4e6)) is None
            d = c.poll(synthetic_round(2 * NR, 0.4e6))
            assert d is not None and d.trigger == "drift"
            assert d.tier == 2 and d.ratio == pytest.approx(0.4,
                                                            abs=0.01)
            assert d.new["codec"] == "synth"
            assert d.new["weighted_cost"] <= d.old["weighted_cost"]
            assert config.tier_bandwidths() is not None
            ent = tune.lookup("allreduce", "float32", 1 << 14, NR,
                              codec="synth", tiers=TIERS)
            assert ent is not None
            assert ent["algorithm"] == d.new["installed"]
            assert ent["ctl"] == {"provenance": "online-switched",
                                  "epoch": d.epoch, "trigger": "drift"}
        finally:
            c.reset()

    def test_crossover_escalates_codec(self):
        c = self._controller()
        try:
            c.observe(synthetic_round(0, 1e6))
            c.calibrate()
            c.poll(synthetic_round(NR, 1e3))
            d = c.poll(synthetic_round(2 * NR, 1e3))
            assert d is not None and d.trigger == "crossover"
            codec = config.default_compression()
            assert getattr(codec, "name", codec) == "q8"
            assert d.new["codec"] == "synth_q8"
            assert d.new["weighted_cost"] < d.old["weighted_cost"]
            assert d.new["tier_wire"][-1] < d.old["tier_wire"][-1]
        finally:
            c.reset()

    def test_min_epoch_hysteresis_suppresses_then_retries(self):
        c = self._controller()
        config.set_ctl_min_switch_epochs(5)
        try:
            c.observe(synthetic_round(0, 1e6))
            c.calibrate()
            c.poll(synthetic_round(NR, 1e3))
            d = c.poll(synthetic_round(2 * NR, 1e3))
            assert d is not None                    # first switch free
            # Recovered measurements, but the min-epochs hysteresis
            # suppresses the de-escalation switch...
            c.poll(synthetic_round(3 * NR, 1e6))
            d2 = c.poll(synthetic_round(4 * NR, 1e6))
            assert d2 is None and c._escalated
            # ...and the condition is STATE-based, so a later poll
            # (with the hysteresis relaxed) retries and ratifies.
            config.set_ctl_min_switch_epochs(1)
            d3 = c.poll(synthetic_round(5 * NR, 1e6))
            assert d3 is not None and d3.trigger == "recovery"
            assert config.default_compression() is None
        finally:
            c.reset()

    def test_fault_fast_path_shares_ledger_and_epoch(self):
        c = self._controller()
        try:
            tr = c.apply("codec_escalate")
            assert c.ledger.triggers() == ["fault"]
            d = list(c.ledger)[-1]
            assert d.policy == "codec_escalate"
            assert d.epoch == tr.epoch == c.runtime.epoch
        finally:
            c.reset()
        assert config.default_compression() is None


# ---------------------------------------------------------------------------
# The closed loop (real traffic, real fault, both transports)
# ---------------------------------------------------------------------------

class TestClosedLoop:
    @pytest.mark.parametrize("tiers,backend", [
        ((2, 2, 2), "thread"),
        ((8,), "thread"),
        ((2, 2, 2), "process"),
        pytest.param((8,), "process", marks=pytest.mark.slow),
    ])
    def test_brownout_escalate_recover_round_trip(self, tiers, backend):
        ev = closed_loop_episode(n=NR, tiers=tiers, backend=backend)
        esc, rec = ev["escalation"], ev["recovery"]
        assert ev["healthy_poll"] is None
        assert ev["patience_poll"] is None
        assert esc is not None and esc.trigger == "crossover"
        assert ev["compression_during"] == "q8"
        # Escalated phase rides the SAME wire as the explicit-q8
        # oracle — bitwise.
        for got, want in zip(ev["escalated"], ev["oracle_q8"]):
            assert np.array_equal(got, want)
        # A phase prepared against the pre-switch view is FENCED.
        assert ev["stale_fenced"] is True
        # Recovery restores the EXACT pre-episode configuration and
        # result.
        assert rec is not None and rec.trigger == "recovery"
        assert rec.epoch > esc.epoch
        assert ev["compression_after"] is None
        assert ev["bandwidths_after"] is None
        for got, want in zip(ev["recovered"], ev["exact_before"]):
            assert np.array_equal(got, want)
        assert ev["ledger"].triggers() == ["crossover", "recovery"]
        if len(tiers) > 1:
            # A real stack re-ranks to a DISTINCT lossy winner with
            # the weighted-cost improvement pinned; the installed
            # entry carries its online provenance for tune --show.
            assert esc.new["weighted_cost"] < esc.old["weighted_cost"]
            assert esc.new["tier_wire"][-1] < esc.old["tier_wire"][-1]
            ent = ev["tune_entry"]
            assert ent is not None
            assert ent["ctl"]["provenance"] == "online-switched"
            assert ent["ctl"]["epoch"] == esc.epoch
        if ev["fired_exact"] and ev["fired_q8"]:
            # The throttle reads wire bytes, so the codec flip shrinks
            # the browned sleep by the compression factor.
            assert max(f["bytes"] for f in ev["fired_q8"]) \
                < max(f["bytes"] for f in ev["fired_exact"])

    def test_stale_fence_names_epochs(self):
        c = SelfTuningController(n_ranks=4, tiers=(4,))
        stale = c.runtime.view
        c.runtime.consensus()
        with pytest.raises(StaleEpochError) as ei:
            c.runtime.run_phase(lambda pos, rid: None, view=stale)
        assert ei.value.have == stale.epoch
        assert ei.value.want == c.runtime.epoch


# ---------------------------------------------------------------------------
# Off-path discipline + surfaces
# ---------------------------------------------------------------------------

class TestOffPath:
    def test_lowering_bit_identical_and_eager_unchanged(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P
        from mpi4torch_tpu._compat import shard_map

        mesh = Mesh(np.asarray(jax.devices()), ("w",))
        cm = mpi.comm_from_mesh(mesh, "w")
        x = jnp.arange(128, dtype=jnp.float32)

        def lowered():
            return jax.jit(shard_map(
                lambda a: cm.Allreduce(a, mpi.MPI_SUM),
                mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False)).lower(x).as_text()

        def eager():
            return [np.asarray(o) for o in mpi.run_ranks(
                lambda r: mpi.COMM_WORLD.Allreduce(
                    jnp.arange(64, dtype=jnp.float32) * (r + 1),
                    mpi.MPI_SUM), 4)]

        text0, res0 = lowered(), eager()
        c = SelfTuningController(n_ranks=NR, tiers=TIERS)
        attached = c.poll(), c.poll(synthetic_round(0, 1.0))
        assert attached == (None, None)
        assert lowered() == text0
        for got, want in zip(eager(), res0):
            assert np.array_equal(got, want)

    def test_engine_consults_controller_between_steps(self):
        import jax
        import jax.numpy as jnp
        from mpi4torch_tpu.models import transformer as T
        from mpi4torch_tpu.serve import Engine, ServeConfig

        cfg = T.TransformerConfig(vocab=37, d_model=16, n_heads=4,
                                  n_layers=2, d_ff=32, max_seq=24)
        params = T.init_transformer(jax.random.PRNGKey(0), cfg,
                                    dtype=jnp.float64)
        eng = Engine(cfg, params, ServeConfig(slots=2))

        class _Probe:
            polls = 0

            def poll(self):
                _Probe.polls += 1

        eng.attach_controller(_Probe())
        eng.submit(np.array([1, 2, 3]), max_new=2)
        eng.step()
        eng.step()
        assert _Probe.polls == 2
        eng.attach_controller(None)
        eng.step()
        assert _Probe.polls == 2


class TestTuneShowProvenance:
    def test_rows_render_online_switched(self):
        from mpi4torch_tpu.tune.__main__ import _rows

        data = {"entries": {
            "allreduce|float32|16384|8|cpu|codec=synth_q8|tiers=2x2x2":
                {"algorithm": "synth:abcdef",
                 "program": {"phases": [{"steps": [{}, {}]}]},
                 "ctl": {"provenance": "online-switched", "epoch": 3,
                         "trigger": "crossover"}},
            "allreduce|float32|16384|8|cpu":
                {"algorithm": "ring", "measurements": {"ring": 1.0}},
        }}
        rows = _rows(data)
        sources = {r[6]: r[7] for r in rows}
        assert sources["synth:abcdef"] == \
            "online-switched(crossover@epoch 3, 2 steps)"
        assert sources["ring"] == "measured"

    def test_record_carries_ctl_stamp(self):
        tune.record("allreduce", "float32", 4096, 8, "ring",
                    persist=False,
                    ctl={"provenance": "online-switched", "epoch": 7,
                         "trigger": "drift"})
        ent = tune.lookup("allreduce", "float32", 4096, 8)
        assert ent["ctl"]["epoch"] == 7

"""Port of the reference collective tests (reference:
tests/test_collectives.py:1-147) onto the thread-SPMD eager runtime.

Same oracles and algebraic identities, same rank-conditional assertions;
``mpirun -np N`` becomes ``run_ranks(body, N)`` and ``tensor.backward()``
becomes ``jax.grad``.  Rank counts follow the reference CI matrix
{2, 5, 7} (reference: .github/workflows/test.yml:62-84).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm, run_ranks

SIZES = [2, 5, 7]


@pytest.fixture(params=SIZES)
def nranks(request):
    return request.param


class TestAllreduce:
    def test_simple(self, nranks):
        # reference: tests/test_collectives.py:8-12
        def body():
            tmp = jnp.asarray(np.random.rand(10))
            grad = jax.grad(lambda t: comm.Allreduce(t, mpi.MPI_SUM).sum())(tmp)
            assert (grad == comm.size * jnp.ones(10)).all()

        run_ranks(body, nranks)

    def test_forward_value(self, nranks):
        def body():
            tmp = jnp.ones(10) * (comm.rank + 1)
            res = comm.Allreduce(tmp, mpi.MPI_SUM)
            expected = comm.size * (comm.size + 1) / 2
            assert (res == expected * jnp.ones(10)).all()

        run_ranks(body, nranks)

    def test_non_sum_forward_ok_backward_raises(self, nranks):
        # Parity with MPIUnimplementedNode: forward works for MPI_MAX, the
        # backward pass raises (reference: csrc/extension.cpp:194-202,279-283).
        def body():
            tmp = jnp.ones(4) * (comm.rank + 1)
            res = comm.Allreduce(tmp, mpi.MPI_MAX)
            assert (res == comm.size * jnp.ones(4)).all()
            with pytest.raises(RuntimeError, match="MPI_MAX"):
                jax.grad(lambda t: comm.Allreduce(t, mpi.MPI_MAX).sum())(tmp)

        run_ranks(body, nranks)

    def test_eager_ops_reject_jit(self, nranks):
        # The eager backend must refuse to run under tracing with a clear
        # error (the traced path is the SPMD mesh backend).
        def body():
            tmp = jnp.ones(4)
            with pytest.raises(mpi.CommError, match="SPMD"):
                jax.jit(lambda t: comm.Allreduce(t, mpi.MPI_SUM))(tmp)

        run_ranks(body, 2)


class TestReduce:
    def test_simple_inplace(self, nranks):
        # reference: tests/test_collectives.py:24-28
        def body():
            tmp = jnp.asarray(np.random.rand(10))
            grad = jax.grad(lambda t: comm.Reduce_(t, mpi.MPI_SUM, 0).sum())(tmp)
            assert (grad == jnp.ones(10)).all()

        run_ranks(body, nranks)

    def test_forward_zeroes_nonroot(self, nranks):
        # reference semantics: non-root results zeroed (csrc/extension.cpp:443-447)
        def body():
            tmp = jnp.ones(10) * (comm.rank + 1)
            res = comm.Reduce_(tmp, mpi.MPI_SUM, 0)
            if comm.rank == 0:
                assert (res == comm.size * (comm.size + 1) / 2 * jnp.ones(10)).all()
            else:
                assert (res == jnp.zeros(10)).all()

        run_ranks(body, nranks)

    def test_noinplace_exception(self, nranks):
        # reference: tests/test_collectives.py:30-36 — reusing the input of
        # the in-place Reduce_ must raise.  The reference raises at backward
        # time via a poison autograd node (csrc/extension.cpp:451-462); the
        # functional runtime raises at the next communication op instead.
        def body():
            tmp = jnp.asarray(np.random.rand(10))
            comm.Reduce_(tmp, mpi.MPI_SUM, 0)
            with pytest.raises(mpi.InPlaceReuseError):
                comm.Allreduce(tmp, mpi.MPI_SUM)

        run_ranks(body, nranks)


class TestBcast:
    def test_simple_inplace(self, nranks):
        # reference: tests/test_collectives.py:39-46
        def body():
            tmp = jnp.asarray(np.random.rand(10))
            grad = jax.grad(lambda t: comm.Bcast_(t, 0).sum())(tmp)
            if comm.rank == 0:
                assert (grad == comm.size * jnp.ones(10)).all()
            else:
                assert (grad == jnp.zeros(10)).all()

        run_ranks(body, nranks)

    def test_forward_value(self, nranks):
        def body():
            tmp = jnp.ones(10) * (comm.rank + 1)
            res = comm.Bcast_(tmp, 0)
            assert (res == jnp.ones(10)).all()

        run_ranks(body, nranks)


class TestGather:
    def test_basic_functionality(self, nranks):
        # reference: tests/test_collectives.py:49-56
        def body():
            numdim = 4
            tmp = jnp.asarray(np.random.rand(2, 5, numdim, 2, 3))
            tmp = tmp.at[0, 0, :, 0, 0].set(comm.rank)
            res = comm.Gather(tmp, 2, 0)
            if comm.rank == 0:
                tmp2 = jnp.sum(res[0, 0, :, 0, 0])
                assert tmp2 == numdim * (comm.size - 1) * comm.size // 2

        run_ranks(body, nranks)

    def test_basic_ad(self, nranks):
        # reference: tests/test_collectives.py:58-63
        def body():
            tmp = jnp.asarray(np.random.rand(2, 5, 4, 2, 3))
            grad = jax.grad(lambda t: comm.Gather(t, 2, 0).sum())(tmp)
            assert (grad == jnp.ones_like(tmp)).all()

        run_ranks(body, nranks)


class TestAllgather:
    def test_basic_functionality(self, nranks):
        # reference: tests/test_collectives.py:66-72
        def body():
            numdim = 4
            tmp = jnp.asarray(np.random.rand(2, 5, numdim, 2, 3))
            tmp = tmp.at[0, 0, :, 0, 0].set(comm.rank)
            res = comm.Allgather(tmp, 2)
            tmp2 = jnp.sum(res[0, 0, :, 0, 0])
            assert tmp2 == numdim * (comm.size - 1) * comm.size // 2

        run_ranks(body, nranks)

    def test_basic_ad(self, nranks):
        # reference: tests/test_collectives.py:74-79
        def body():
            tmp = jnp.asarray(np.random.rand(2, 5, 4, 2, 3))
            grad = jax.grad(lambda t: comm.Allgather(t, 2).sum())(tmp)
            assert (grad == comm.size * jnp.ones_like(tmp)).all()

        run_ranks(body, nranks)

    def test_rank_varying_upstream_gradient(self, nranks):
        # The mathematically correct Allgather adjoint (ordered
        # reduce-scatter).  The reference's backward is wrong for
        # rank-varying upstream gradients (constant root=1 loop,
        # csrc/extension.cpp:627) — this test pins the *correct* behavior,
        # as SURVEY.md §2.2 prescribes.
        def body():
            tmp = jnp.asarray(np.random.rand(3))
            grad = jax.grad(
                lambda t: ((comm.rank + 1.0) * comm.Allgather(t, 0)).sum()
            )(tmp)
            # d/dx_k sum_r (r+1) * concat_j(x_j) = sum_r (r+1) = S(S+1)/2
            expected = comm.size * (comm.size + 1) / 2
            assert (grad == expected * jnp.ones_like(tmp)).all()

        run_ranks(body, nranks)


class TestReduceScatter:
    """TPU-native addition (no reference counterpart): block
    reduce-scatter — rank r keeps segment r of the rank-ordered
    reduction.  Adjoint (SUM only) is the allgather."""

    def test_forward_value(self, nranks):
        def body():
            # rank r contributes r+1 everywhere; segment values are
            # sum(1..size) regardless of segment.
            x = jnp.ones((nranks * 3,)) * (comm.rank + 1)
            out = comm.Reduce_scatter(x, mpi.MPI_SUM, 0)
            assert out.shape == (3,)
            assert (out == nranks * (nranks + 1) / 2).all()

        run_ranks(body, nranks)

    def test_allgather_of_reduce_scatter_is_allreduce(self, nranks):
        def body():
            rng = np.random.default_rng(comm.rank)
            x = jnp.asarray(rng.standard_normal((nranks * 2, 3)))
            rs = comm.Reduce_scatter(x, mpi.MPI_SUM, 0)
            ag = comm.Allgather(rs, 0)
            ar = comm.Allreduce(x, mpi.MPI_SUM)
            np.testing.assert_allclose(np.asarray(ag), np.asarray(ar),
                                       rtol=1e-12)

        run_ranks(body, nranks)

    def test_grad_is_allgather(self, nranks):
        # loss = sum(w_r * out_r) per rank; d loss_total / dx on every
        # rank is the concatenation of the per-rank weights along the
        # scatter axis (the allgather adjoint).
        def body():
            x = jnp.ones((nranks * 2,))
            w = float(comm.rank + 1)
            g = jax.grad(lambda t: jnp.sum(
                w * comm.Reduce_scatter(t, mpi.MPI_SUM, 0)))(x)
            want = np.repeat(np.arange(1, nranks + 1, dtype=float), 2)
            np.testing.assert_array_equal(np.asarray(g), want)

        run_ranks(body, nranks)

    def test_non_sum_forward_ok_backward_raises(self, nranks):
        def body():
            x = jnp.ones((nranks,)) * (comm.rank + 1)
            out = comm.Reduce_scatter(x, mpi.MPI_MAX, 0)
            assert (out == nranks).all()
            with pytest.raises(RuntimeError, match="MPI_MAX"):
                jax.grad(lambda t: comm.Reduce_scatter(
                    t, mpi.MPI_MAX, 0).sum())(x)

        run_ranks(body, nranks)

    def test_indivisible_axis_raises(self, nranks):
        def body():
            with pytest.raises(mpi.CommError, match="divisible"):
                comm.Reduce_scatter(jnp.ones((nranks * 2 + 1,)),
                                    mpi.MPI_SUM, 0)

        run_ranks(body, nranks)


class TestScatter:
    def test_basic_functionality(self, nranks):
        # reference: tests/test_collectives.py:82-90 — non-root input shapes
        # are ignored (shape broadcast from root, csrc/extension.cpp:788-796).
        def body():
            if comm.rank == 0:
                tmp = jnp.asarray(np.random.rand(2, 5, comm.size, 2, 3))
                for i in range(comm.size):
                    tmp = tmp.at[0, 0, i, 0, 0].set(i)
            else:
                tmp = jnp.asarray(np.random.rand(1))
            res = comm.Scatter(tmp, 2, 1, 0)
            assert (res[0, 0, :, 0, 0] == comm.rank).all()

        run_ranks(body, nranks)

    def test_scattergather(self, nranks):
        # reference: tests/test_collectives.py:92-100 — Scatter∘Gather = id
        def body():
            if comm.rank == 0:
                tmp = jnp.asarray(np.random.rand(2, 5, comm.size, 2, 3))
            else:
                tmp = jnp.asarray(np.random.rand(1))
            res = comm.Scatter(tmp, 2, 1, 0)
            res2 = comm.Gather(res, 2, 0)
            if comm.rank == 0:
                assert (res2 == tmp).all()

        run_ranks(body, nranks)

    def test_basic_ad(self, nranks):
        # reference: tests/test_collectives.py:102-112
        def body():
            if comm.rank == 0:
                tmp = jnp.asarray(np.random.rand(2, 5, comm.size, 2, 3))
            else:
                tmp = jnp.asarray(np.random.rand(1))
            grad = jax.grad(lambda t: comm.Scatter(t, 2, 1, 0).sum())(tmp)
            if comm.rank == 0:
                assert (grad == jnp.ones_like(tmp)).all()
            else:
                assert (grad == jnp.zeros_like(tmp)).all()

        run_ranks(body, nranks)

    def test_numelem_mismatch_raises(self, nranks):
        # reference check: sum(numelem) must equal the root's axis length
        # (csrc/extension.cpp:835-837)
        def body():
            tmp = jnp.asarray(np.random.rand(2, comm.size + 1, 3))
            with pytest.raises(ValueError, match="numelem"):
                comm.Scatter(tmp, 1, 1, 0)

        run_ranks(body, nranks)


class TestAlltoall:
    def test_gatherscatter_equivalence(self, nranks):
        # reference: tests/test_collectives.py:115-119
        def body():
            tmp = jnp.asarray(np.random.rand(3, 4, 1, 4, comm.size, 2))
            res1 = comm.Scatter(comm.Gather(tmp, 2, 0), 4, 1, 0)
            res2 = comm.Alltoall(tmp, 2, 4, 1)
            assert (res2 == res1).all()

        run_ranks(body, nranks)

    def test_gatherscatter_equivalence_varying_numelem(self, nranks):
        # reference: tests/test_collectives.py:121-125 — per-rank-varying
        # shard sizes on both axes
        def body():
            tmp = jnp.asarray(np.random.rand(
                3, 4, comm.rank + 1, 4, comm.size * (comm.size + 1) // 2, 2))
            res1 = comm.Scatter(comm.Gather(tmp, 2, 0), 4, comm.rank + 1, 0)
            res2 = comm.Alltoall(tmp, 2, 4, comm.rank + 1)
            assert (res2 == res1).all()

        run_ranks(body, nranks)

    def test_gatheraxis_scatteraxis_equal(self, nranks):
        # reference: tests/test_collectives.py:127-135
        def body():
            tmp = jnp.asarray(np.random.rand(3, 4, comm.rank + 1, 2))
            tmp = tmp.at[0, 0, :, 0].set(jnp.arange(
                comm.rank * (comm.rank + 1) // 2,
                (comm.rank + 1) * (comm.rank + 2) // 2, dtype=tmp.dtype))
            res = comm.Alltoall(tmp, 2, 2, comm.size - comm.rank)
            total = comm.size * (comm.size + 1) // 2
            lo = total - (comm.size - comm.rank) * (comm.size - comm.rank + 1) // 2
            hi = total - (comm.size - comm.rank - 1) * (comm.size - comm.rank) // 2
            correct = jnp.arange(lo, hi, dtype=tmp.dtype)
            assert (res[0, 0, :, 0] == correct).all()

        run_ranks(body, nranks)

    def test_identity_equivalence(self, nranks):
        # reference: tests/test_collectives.py:137-141 — Alltoall involution
        def body():
            tmp = jnp.asarray(np.random.rand(3, 4, 2, 4, 3 * comm.size, 2))
            res = comm.Alltoall(tmp, 2, 4, 3)
            res2 = comm.Alltoall(res, 4, 2, 2)
            assert (res2 == tmp).all()

        run_ranks(body, nranks)

    def test_basic_ad(self, nranks):
        # reference: tests/test_collectives.py:143-147
        def body():
            tmp = jnp.asarray(np.random.rand(3, 4, 2, 4, comm.size, 2))
            grad = jax.grad(lambda t: comm.Alltoall(t, 2, 4, 1).sum())(tmp)
            assert (grad == jnp.ones_like(tmp)).all()

        run_ranks(body, nranks)


class TestDeterminism:
    def test_allreduce_bit_exact_vs_ordered_oracle(self):
        # BASELINE.md north-star: gradients/results bit-exact vs. the
        # rank-ordered (MPI linear order) reduction oracle, and
        # run-to-run reproducible.
        nranks = 5
        rng = np.random.default_rng(0)
        data = rng.standard_normal((nranks, 1000)).astype(np.float32)

        def body(rank):
            res = comm.Allreduce(jnp.asarray(data[rank]), mpi.MPI_SUM)
            return np.asarray(res)

        out1 = run_ranks(body, nranks)
        out2 = run_ranks(body, nranks)
        oracle = data[0].copy()
        for r in range(1, nranks):
            oracle = oracle + data[r]
        for r in range(nranks):
            np.testing.assert_array_equal(out1[r], oracle)
            np.testing.assert_array_equal(out1[r], out2[r])


class TestDtypeAwareFoldGates:
    """ADVICE r5 regressions: the fold-delegation gates (Reduce_'s
    root-only fold, Allreduce's fold-once) must key on the dtype-aware
    predicate, so an op the dtype rejects (MPI_BAND on floats) raises the
    SAME informative error on EVERY rank — not a folding-rank death plus
    broken-barrier aborts elsewhere."""

    def test_reduce_band_on_floats_raises_on_every_rank(self):
        def body():
            with pytest.raises(TypeError):
                comm.Reduce_(jnp.ones(8), mpi.MPI_BAND, 0)
            return "raised"

        assert run_ranks(body, 3) == ["raised"] * 3

    def test_allreduce_fold_once_band_on_floats_symmetric(self, monkeypatch):
        from mpi4torch_tpu.ops import eager as eager_mod

        monkeypatch.setattr(eager_mod, "_FOLD_ONCE_MIN", 1)

        def body():
            with pytest.raises(TypeError):
                comm.Allreduce(jnp.ones(8), mpi.MPI_BAND)
            return "raised"

        assert run_ranks(body, 3) == ["raised"] * 3

    def test_fold_applicable_predicate(self):
        from mpi4torch_tpu import constants as C

        assert C.fold_applicable(mpi.MPI_BAND, np.int32)
        assert C.fold_applicable(mpi.MPI_BAND, np.bool_)
        assert not C.fold_applicable(mpi.MPI_BAND, np.float32)
        assert not C.fold_applicable(mpi.MPI_BXOR, np.float64)
        assert C.fold_applicable(mpi.MPI_SUM, np.float32)
        assert C.fold_applicable(mpi.MPI_LAND, np.float32)  # `!= 0` is fine
        assert not C.fold_applicable(mpi.MPI_MINLOC, np.float32)
        assert not C.fold_applicable(999, np.int32)

    def test_bitwise_on_ints_still_works_on_fold_once_path(self, monkeypatch):
        from mpi4torch_tpu.ops import eager as eager_mod

        monkeypatch.setattr(eager_mod, "_FOLD_ONCE_MIN", 1)

        def body():
            t = jnp.full(8, 1 << comm.rank, jnp.int32)
            res = comm.Allreduce(t, mpi.MPI_BOR)
            assert (np.asarray(res) == (1 << comm.size) - 1).all()

        run_ranks(body, 3)


class TestFoldOnceSharedResult:
    """ADVICE r5 regression: the fold-once Allreduce hands every rank the
    SAME result object; on the numpy path it must be frozen so one rank's
    in-place edit cannot silently corrupt the others' results."""

    def test_numpy_result_is_readonly(self, monkeypatch):
        from mpi4torch_tpu.ops import eager as eager_mod

        monkeypatch.setattr(eager_mod, "_FOLD_ONCE_MIN", 1)

        def body(rank):
            x = np.ones(256, np.float32) * (rank + 1)
            res = comm.Allreduce(x, mpi.MPI_SUM)
            if isinstance(res, np.ndarray):
                assert not res.flags.writeable
                with pytest.raises((ValueError, RuntimeError)):
                    res[0] = -1.0
            return np.asarray(res).copy()

        results = run_ranks(body, 3)
        for r in results:
            np.testing.assert_array_equal(r, np.full(256, 6.0, np.float32))

    def test_size_one_world_input_not_frozen(self, monkeypatch):
        # With one rank the fold returns the caller's own array; freezing
        # it would be a visible side effect on user data.
        from mpi4torch_tpu.ops import eager as eager_mod

        monkeypatch.setattr(eager_mod, "_FOLD_ONCE_MIN", 1)

        def body():
            x = np.ones(64, np.float32)
            comm.Allreduce(x, mpi.MPI_SUM)
            assert x.flags.writeable

        run_ranks(body, 1)

"""Multi-process runtime tests — the ``mpirun -np N`` analogue.

The integration test launches REAL OS processes (subprocesses with their
own JAX runtimes) that rendezvous through ``init_distributed`` and run
one compiled SPMD program spanning both — the true port of the
reference's launcher-based CI (reference: .github/workflows/test.yml:62-84
``mpirun -np N nose2``; init rendezvous csrc/extension.cpp:1313-1394).
mpi4py interop is tested with a faithful in-process stand-in for the
single-process case plus the error paths (the reference test's shape,
tests/test_mpi4pyinterop.py:1-20); the multi-process rendezvous path
shares all its machinery with the subprocess test.
"""

import os
import socket
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent("""
    import sys, os
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    import mpi4torch_tpu as mpi
    import jax.numpy as jnp
    import numpy as np

    info = mpi.init_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=n, process_id=pid)
    assert info.process_id == pid and info.process_count == n, info
    assert info.n_devices == n, info          # 1 CPU device per process
    assert mpi.is_distributed()

    def body():
        r = jnp.asarray(mpi.COMM_WORLD.rank)
        x = (r + 1.0) * jnp.ones((4,))

        def loss(x):
            y = mpi.COMM_WORLD.Allreduce(x, mpi.MPI_SUM)
            return jnp.vdot(y, jnp.ones((4,))), y

        (_, y), grad = jax.value_and_grad(loss, has_aux=True)(x)
        return y, grad

    y, grad = mpi.run_spmd(body)()            # default mesh = global devices
    ranks, yv = mpi.local_values(y)
    _, gv = mpi.local_values(grad)
    assert list(ranks) == [pid], (ranks, pid)
    # psum((r+1)*ones) over 2 ranks = 3; adjoint psum(ones) over 2 = 2.
    np.testing.assert_array_equal(yv[0], 3.0)
    np.testing.assert_array_equal(gv[0], float(n))

    # hybrid_mesh with process-granules: 2 single-device CPU processes
    # form 2 granules; the dp axis is the DCN/process-crossing tier.
    m = mpi.hybrid_mesh({"tp": 1}, {"dp": 2})
    assert m.axis_names == ("dp", "tp"), m.axis_names
    assert m.shape["dp"] == 2 and m.shape["tp"] == 1, m.shape

    # mpi4py interop on an already-initialized runtime: a stand-in comm
    # with the matching layout must validate and adopt it.
    class FakeComm:
        def Get_rank(self): return pid
        def Get_size(self): return n
        def bcast(self, v, root=0): raise AssertionError("no rendezvous needed")
    import types
    fake = types.ModuleType("mpi4py"); fake.MPI = types.SimpleNamespace()
    sys.modules["mpi4py"] = fake
    c = mpi.comm_from_mpi4py(FakeComm())
    assert c.rank == pid and c.size == n

    mpi.finalize_distributed()
    assert not mpi.is_distributed()
    print(f"WORKER-{pid}-OK", flush=True)
""")


_HYBRID_WORKER = textwrap.dedent("""
    import sys, os
    # 4 virtual devices per process -> 2 processes x 4 = 8 global devices,
    # 2 REAL granules (the process boundary is the CPU harness's DCN).
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    import mpi4torch_tpu as mpi
    import jax.numpy as jnp
    import numpy as np
    from mpi4torch_tpu._compat import shard_map
    from jax.sharding import PartitionSpec as P

    info = mpi.init_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=n, process_id=pid)
    assert info.n_devices == 8, info

    # VERDICT r4 item 6: hybrid_mesh with n_granules > 1 — the
    # dcn-axes-outermost layout logic (mesh.py) on real granules.
    m = mpi.hybrid_mesh({"tp": 4}, {"dp": 2})
    assert m.axis_names == ("dp", "tp"), m.axis_names
    devs = m.devices
    assert devs.shape == (2, 4), devs.shape
    # The layout contract: tp rows stay inside one process (ICI tier),
    # the dp axis crosses the process boundary (DCN tier).
    row_procs = [ {d.process_index for d in row} for row in devs ]
    assert all(len(s) == 1 for s in row_procs), row_procs
    assert row_procs[0] != row_procs[1], row_procs

    ctp = mpi.comm_from_mesh(m, "tp")
    cdp = mpi.comm_from_mesh(m, "dp")
    assert ctp.size == 4 and cdp.size == 2

    def body():
        tp_sum = ctp.Allreduce(jnp.asarray(ctp.rank + 1.0), mpi.MPI_SUM)
        dp_sum = cdp.Allreduce(jnp.asarray(cdp.rank + 1.0), mpi.MPI_SUM)
        return tp_sum, dp_sum

    tp_sum, dp_sum = jax.jit(shard_map(
        body, mesh=m, in_specs=(), out_specs=(P(), P()),
        check_vma=False))()
    # tp: 1+2+3+4 within each granule; dp: 1+2 ACROSS the two processes
    # (the value itself proves the collective crossed the boundary).
    np.testing.assert_array_equal(np.asarray(tp_sum), 10.0)
    np.testing.assert_array_equal(np.asarray(dp_sum), 3.0)

    # And a gradient through the dp-axis collective (adjoint also DCN).
    def loss():
        x = (jnp.asarray(cdp.rank) + 1.0) * jnp.ones((2,))
        def inner(x):
            return jnp.vdot(cdp.Allreduce(x, mpi.MPI_SUM), jnp.ones((2,)))
        return jax.grad(inner)(x)

    g = jax.jit(shard_map(loss, mesh=m, in_specs=(), out_specs=P(),
                          check_vma=False))()
    np.testing.assert_array_equal(np.asarray(g), 2.0)

    # VERDICT r4 weak 5: the "MPI linear order" oracle existed only at
    # thread scale — here the eager (single-process, 8-thread) oracle is
    # compared BIT FOR BIT against deterministic-mode results computed on
    # the real 2-process mesh, on both ordered-fold lowerings (gather
    # fold and the chunked ring fold).
    data = np.stack([np.sin(np.arange(513, dtype=np.float32) * (r + 1))
                     for r in range(8)]).astype(np.float32)
    datj = jnp.asarray(data)

    def eager_body(r):
        return np.asarray(mpi.COMM_WORLD.Allreduce(datj[r], mpi.MPI_SUM))

    oracle = mpi.run_ranks(eager_body, 8)

    def det_body():
        t = jax.lax.dynamic_index_in_dim(
            datj, jnp.asarray(mpi.COMM_WORLD.rank + 0), 0, keepdims=False)
        return mpi.COMM_WORLD.Allreduce(t, mpi.MPI_SUM)

    for fold in ("gather", "ring"):
        if fold == "ring":
            mpi.config.set_ordered_fold_gather_max_bytes(0)
            mpi.config.set_ordered_ring_chunk_bytes(256)
        with mpi.config.deterministic_mode(True):
            out = mpi.run_spmd(det_body)()     # global mesh, both procs
        ranks, vals = mpi.local_values(out)
        for rk, v in zip(ranks, vals):
            np.testing.assert_array_equal(np.asarray(v), oracle[rk],
                                          err_msg=f"{fold} rank {rk}")

    mpi.finalize_distributed()
    print(f"HYBRID-WORKER-{pid}-OK", flush=True)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# The two real-OS-process Mode A integration tests below exercise the
# coordination-service rendezvous end to end, but the COMPILED collective
# itself cannot run on this harness: the CPU PJRT backend has no
# multi-process collective implementation (workers die with
# "INVALID_ARGUMENT: Multiprocess computations aren't implemented on the
# CPU backend").  The gap is Mode A-ONLY: since the transport runtime
# landed (mpi4torch_tpu.transport), the SAME multi-process shapes run
# and PASS over the Mode B process backend — see the
# ``*_process_backend`` companions right below each xfail, which launch
# real worker processes through ``run_ranks(..., backend="process")``
# and assert bitwise parity against the thread oracle.  The xfail
# (non-strict) stays only on the compiled-collective variants, where a
# TPU/multi-host run — the one place the Mode A collective exists —
# reports xpass instead of being skipped.
_MULTIPROC_CPU_GAP = pytest.mark.xfail(
    reason="Mode A-only gap: multi-process COMPILED collectives are "
           "unimplemented on the CPU PJRT backend ('Multiprocess "
           "computations aren't implemented on the CPU backend'); the "
           "Mode B process-transport companion tests cover the "
           "multi-process semantics on this harness, this variant needs "
           "a real TPU/multi-host runtime",
    strict=False)


class TestTwoProcessIntegration:
    @_MULTIPROC_CPU_GAP
    def test_two_process_allreduce_fwd_bwd(self, tmp_path):
        script = tmp_path / "worker.py"
        script.write_text(_WORKER)
        port = _free_port()
        env = dict(os.environ)
        # The pytest process's 8-virtual-device XLA_FLAGS must NOT leak
        # into the workers: each worker is one process with ONE cpu
        # device, exactly like one rank of an mpirun launch.
        env.pop("XLA_FLAGS", None)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(pid), "2", str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env)
            for pid in range(2)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail("2-process run timed out (rendezvous hang?)\n"
                        + "\n".join(outs))
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {pid} failed:\n{out}"
            assert f"WORKER-{pid}-OK" in out

    def test_two_process_allreduce_fwd_bwd_process_backend(self):
        # The flipped half of the standing xfail above: the same
        # 2-real-process allreduce forward+backward, but through the
        # Mode B process transport — each rank is a REAL worker process
        # (distinct PID from the launcher), and the results must be
        # bitwise what the thread backend computes.
        def body(rank):
            x = (rank + 1.0) * jnp.ones((4,), jnp.float32)

            def loss(x):
                y = mpi.COMM_WORLD.Allreduce(x, mpi.MPI_SUM)
                return jnp.vdot(y, jnp.ones((4,))), y

            (_, y), grad = jax.value_and_grad(loss, has_aux=True)(x)
            return np.asarray(y), np.asarray(grad), os.getpid()

        got = mpi.run_ranks(body, 2, backend="process")
        oracle = mpi.run_ranks(body, 2, backend="thread")
        for rank in range(2):
            y, grad, pid = got[rank]
            np.testing.assert_array_equal(y, oracle[rank][0])
            np.testing.assert_array_equal(grad, oracle[rank][1])
            # y = sum_r (r+1) * ones = 3 * ones; the adjoint of an
            # allreduce-sum is another allreduce-sum, so the ones
            # cotangent comes back summed over both ranks: grad = 2.
            np.testing.assert_array_equal(y, 3.0 * np.ones(4, np.float32))
            np.testing.assert_array_equal(grad, 2.0 * np.ones(4, np.float32))
            assert pid != os.getpid(), "rank body ran in the launcher"
        assert got[0][2] != got[1][2], "both ranks shared one process"


class TestHybridMeshMultiGranule:
    @_MULTIPROC_CPU_GAP
    def test_two_process_hybrid_mesh_dp_over_dcn(self, tmp_path):
        script = tmp_path / "hybrid_worker.py"
        script.write_text(_HYBRID_WORKER)
        port = _free_port()
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)   # worker sets its own 4-device count
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(pid), "2", str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env)
            for pid in range(2)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail("2-process hybrid run timed out\n" + "\n".join(outs))
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {pid} failed:\n{out}"
            assert f"HYBRID-WORKER-{pid}-OK" in out

    def test_deterministic_fold_parity_process_backend(self):
        # The flipped half of the hybrid xfail: the deterministic
        # ordered-fold guarantee across REAL process boundaries.  The
        # thread-backend eager run is the oracle; the same body on the
        # process backend — workers inheriting the launcher's
        # ordered-fold knobs via the config-shipping contract — must
        # reproduce it bit for bit on both ordered-fold lowerings.
        data = np.stack([np.sin(np.arange(129, dtype=np.float32) * (r + 1))
                         for r in range(3)]).astype(np.float32)

        def det_body(rank):
            with mpi.config.deterministic_mode(True):
                return np.asarray(mpi.COMM_WORLD.Allreduce(
                    jnp.asarray(data[rank]), mpi.MPI_SUM))

        prev_gather = mpi.config.ordered_fold_gather_max_bytes()
        prev_chunk = mpi.config.ordered_ring_chunk_bytes()
        try:
            for fold in ("gather", "ring"):
                if fold == "ring":
                    mpi.config.set_ordered_fold_gather_max_bytes(0)
                    mpi.config.set_ordered_ring_chunk_bytes(256)
                oracle = mpi.run_ranks(det_body, 3, backend="thread")
                got = mpi.run_ranks(det_body, 3, backend="process")
                for rk in range(3):
                    np.testing.assert_array_equal(
                        got[rk], oracle[rk], err_msg=f"{fold} rank {rk}")
        finally:
            mpi.config.set_ordered_fold_gather_max_bytes(prev_gather)
            mpi.config.set_ordered_ring_chunk_bytes(prev_chunk)


_MPI4PY_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from mpi4py import MPI
    import numpy as np
    import jax.numpy as jnp
    import mpi4torch_tpu as mpi

    world = MPI.COMM_WORLD
    rank, size = world.Get_rank(), world.Get_size()

    # The reference interop test's shape
    # (reference: tests/test_mpi4pyinterop.py:1-20): rank/size agreement
    # with mpi4py, then Allreduce + backward through the converted
    # communicator.  This exercises the REAL rendezvous branch: rank 0
    # opens the coordinator port and bcasts host:port over mpi4py.
    comm = mpi.comm_from_mpi4py(world)
    assert comm.rank == rank, (comm.rank, rank)
    assert comm.size == size, (comm.size, size)
    info = mpi.distributed_info()
    assert info is not None and info.process_count == size

    def body():
        r = jnp.asarray(mpi.COMM_WORLD.rank)
        x = (r + 1.0) * jnp.ones((4,))

        def loss(x):
            y = mpi.COMM_WORLD.Allreduce(x, mpi.MPI_SUM)
            return jnp.vdot(y, jnp.ones((4,))), y

        (_, y), grad = jax.value_and_grad(loss, has_aux=True)(x)
        return y, grad

    y, grad = mpi.run_spmd(body)()
    _, yv = mpi.local_values(y)
    _, gv = mpi.local_values(grad)
    np.testing.assert_array_equal(yv[0], sum(range(1, size + 1)))
    np.testing.assert_array_equal(gv[0], float(size))

    # Cross-check against mpi4py's own allreduce (the two worlds agree).
    total = world.allreduce(rank + 1.0)
    assert total == sum(range(1, size + 1))
    print(f"MPIRUN-WORKER-{rank}-OK", flush=True)
""")


def _mpirun() -> str | None:
    import shutil

    return shutil.which("mpirun") or shutil.which("mpiexec")


def _have_mpi4py() -> bool:
    try:
        import mpi4py  # noqa: F401

        return True
    except ModuleNotFoundError:
        return False


@pytest.mark.skipif(_mpirun() is None or not _have_mpi4py(),
                    reason="needs mpirun + mpi4py (installed in the CI "
                           "mpi-interop job; not in every dev image)")
class TestRealMpirunInterop:
    """comm_from_mpi4py under an ACTUAL 2-process MPI launch — the port
    of the reference's launcher-based interop test (reference:
    tests/test_mpi4pyinterop.py:1-20 under .github/workflows/
    test.yml:62-84).  The FakeComm tests above cover the logic in every
    environment; this covers the real rendezvous."""

    def test_two_rank_launch(self, tmp_path):
        script = tmp_path / "mpi4py_worker.py"
        script.write_text(_MPI4PY_WORKER)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)      # one device per rank, like mpirun
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        # Single-host launch: the rendezvous must bind a locally
        # reachable address.
        env["MPI4TORCH_TPU_COORDINATOR_HOST"] = "127.0.0.1"
        cmd = [_mpirun(), "-np", "2", "--oversubscribe", sys.executable,
               str(script)]
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=300, env=env)
        except subprocess.TimeoutExpired:
            pytest.fail("mpirun interop launch timed out")
        if r.returncode != 0 and "--oversubscribe" in " ".join(
                r.stderr.splitlines()[:5]):
            # MPICH has no --oversubscribe flag.
            cmd.remove("--oversubscribe")
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=300, env=env)
        assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
        for rank in range(2):
            assert f"MPIRUN-WORKER-{rank}-OK" in r.stdout


class TestInitErrors:
    def test_reinit_with_conflicting_layout_raises(self, monkeypatch):
        from mpi4torch_tpu import distributed as dist

        monkeypatch.setitem(
            dist._STATE, "info",
            dist.DistributedInfo(process_id=0, process_count=2, n_devices=2,
                                 n_local_devices=1,
                                 coordinator_address="x:1"))
        with pytest.raises(mpi.CommError, match="already called"):
            mpi.init_distributed(num_processes=4, process_id=3)
        # Matching (or omitted) arguments are idempotent.
        assert mpi.init_distributed(num_processes=2).process_count == 2
        assert mpi.distributed_info().process_count == 2

    def test_finalize_without_init_is_noop(self):
        assert not mpi.is_distributed()
        mpi.finalize_distributed()


class TestLocalValues:
    def test_single_process_run_spmd_output(self):
        out = mpi.run_spmd(
            lambda: jnp.asarray(mpi.COMM_WORLD.rank) * jnp.ones(2),
            nranks=4)()
        ranks, vals = mpi.local_values(out)
        np.testing.assert_array_equal(ranks, np.arange(4))
        for r in range(4):
            np.testing.assert_array_equal(vals[r], float(r))

    def test_ndarray_passthrough(self):
        a = np.arange(6.0).reshape(3, 2)
        ranks, vals = mpi.local_values(a)
        np.testing.assert_array_equal(ranks, np.arange(3))
        np.testing.assert_array_equal(vals, a)

    def test_rejects_pytree(self):
        with pytest.raises(TypeError, match="per leaf"):
            mpi.local_values({"a": jnp.ones(2)})


class _FakeSize1Comm:
    def Get_rank(self):
        return 0

    def Get_size(self):
        return 1


class TestMpi4pyInterop:
    """Port of the reference's tests/test_mpi4pyinterop.py:1-20: rank/size
    agreement with the mpi4py comm + Allreduce forward/backward through
    the converted communicator."""

    def _with_fake_mpi4py(self, monkeypatch):
        import types

        fake = types.ModuleType("mpi4py")
        fake.MPI = types.SimpleNamespace(COMM_WORLD=_FakeSize1Comm())
        monkeypatch.setitem(sys.modules, "mpi4py", fake)
        return fake

    def test_rank_size_agreement(self, monkeypatch):
        self._with_fake_mpi4py(monkeypatch)
        mcomm = _FakeSize1Comm()
        comm = mpi.comm_from_mpi4py(mcomm)
        assert comm.rank == mcomm.Get_rank()
        assert comm.size == mcomm.Get_size()

    def test_allreduce_forward_backward(self, monkeypatch):
        # reference tests/test_mpi4pyinterop.py: Allreduce of ones and
        # the gradient of its sum through the converted communicator.
        self._with_fake_mpi4py(monkeypatch)
        comm = mpi.comm_from_mpi4py(_FakeSize1Comm())

        def loss(x):
            return jnp.sum(comm.Allreduce(x, mpi.MPI_SUM))

        x = jnp.ones((10,))
        val, grad = jax.value_and_grad(loss)(x)
        assert float(val) == 10.0 * comm.size
        np.testing.assert_array_equal(np.asarray(grad),
                                      float(comm.size))

    def test_works_inside_spmd_region(self, monkeypatch):
        self._with_fake_mpi4py(monkeypatch)
        comm = mpi.comm_from_mpi4py(_FakeSize1Comm())

        def body():
            return comm.Allreduce(jnp.ones(3), mpi.MPI_SUM)

        out = mpi.run_spmd(body, nranks=4)()
        np.testing.assert_array_equal(np.asarray(out), 4.0)

    def test_missing_mpi4py_raises(self, monkeypatch):
        import builtins

        real_import = builtins.__import__

        def blocked(name, *a, **k):
            if name.startswith("mpi4py"):
                raise ModuleNotFoundError("No module named 'mpi4py'")
            return real_import(name, *a, **k)

        monkeypatch.delitem(sys.modules, "mpi4py", raising=False)
        monkeypatch.setattr(builtins, "__import__", blocked)
        with pytest.raises(RuntimeError, match="mpi4py is not available"):
            mpi.comm_from_mpi4py(_FakeSize1Comm())

    def test_multiprocess_layout_mismatch_raises(self, monkeypatch):
        self._with_fake_mpi4py(monkeypatch)
        from mpi4torch_tpu import distributed as dist

        class Fake3Comm:
            def Get_rank(self):
                return 0

            def Get_size(self):
                return 3

        monkeypatch.setitem(
            dist._STATE, "info",
            dist.DistributedInfo(process_id=0, process_count=2, n_devices=2,
                                 n_local_devices=1,
                                 coordinator_address="x:1"))
        with pytest.raises(mpi.CommError, match="layout|processes"):
            mpi.comm_from_mpi4py(Fake3Comm())

    def test_size1_subcomm_under_multiprocess_launch_raises(self,
                                                            monkeypatch):
        # COMM_SELF inside an mpirun -np 2 launch must not silently adopt
        # the 2-process world.
        self._with_fake_mpi4py(monkeypatch)
        from mpi4torch_tpu import distributed as dist

        monkeypatch.setitem(
            dist._STATE, "info",
            dist.DistributedInfo(process_id=0, process_count=2, n_devices=2,
                                 n_local_devices=1,
                                 coordinator_address="x:1"))
        with pytest.raises(mpi.CommError, match="subcommunicator"):
            mpi.comm_from_mpi4py(_FakeSize1Comm())

    def test_rank_reordered_comm_raises(self, monkeypatch):
        self._with_fake_mpi4py(monkeypatch)
        from mpi4torch_tpu import distributed as dist

        class Reordered2Comm:
            def Get_rank(self):
                return 0        # MPI says 0 ...

            def Get_size(self):
                return 2

        monkeypatch.setitem(
            dist._STATE, "info",
            dist.DistributedInfo(process_id=1, process_count=2, n_devices=2,
                                 n_local_devices=1,   # ... JAX says 1
                                 coordinator_address="x:1"))
        with pytest.raises(mpi.CommError, match="rank-reordered|not match"):
            mpi.comm_from_mpi4py(Reordered2Comm())

    def test_top_level_ops_on_multiprocess_comm_raise(self, monkeypatch):
        self._with_fake_mpi4py(monkeypatch)
        from mpi4torch_tpu import distributed as dist

        class Fake2Comm:
            def Get_rank(self):
                return 1

            def Get_size(self):
                return 2

        monkeypatch.setitem(
            dist._STATE, "info",
            dist.DistributedInfo(process_id=1, process_count=2, n_devices=2,
                                 n_local_devices=1,
                                 coordinator_address="x:1"))
        comm = mpi.comm_from_mpi4py(Fake2Comm())
        assert comm.rank == 1 and comm.size == 2
        with pytest.raises(mpi.CommError, match="run_spmd"):
            comm.Allreduce(jnp.ones(2), mpi.MPI_SUM)

"""Fused block-attention kernel tests.

The Pallas kernel (run in interpret mode on the CPU host — the kernel-level
analogue of the CPU-mesh harness) must match the jnp reference path, which
itself must match the dense oracle; grads flow through the shared
custom_vjp backward.  Merging partials must reproduce un-blocked attention
exactly, because ring attention is built on it."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm
from mpi4torch_tpu.ops import flash
from mpi4torch_tpu.parallel import dense_attention, ring_attention

B, S, H, D = 2, 16, 2, 8          # jnp-path shapes (D too small for pallas)
PB, PS, PH, PD = 1, 256, 2, 128   # pallas-eligible shapes


def qkv(shape, seed=0, dtype=jnp.float64):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.standard_normal(shape), dtype)
                 for _ in range(3))


class TestJnpBlock:
    @pytest.mark.parametrize("causal", [False, True])
    def test_single_block_matches_dense(self, causal):
        q, k, v = qkv((B, S, H, D))
        out, _ = flash.flash_block_attention(q, k, v, causal=causal,
                                             impl="jnp")
        ref = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-12, atol=1e-14)

    @pytest.mark.parametrize("causal", [False, True])
    def test_merge_matches_dense(self, causal):
        q, k, v = qkv((B, S, H, D))
        o1, l1 = flash.flash_block_attention(
            q, k[:, :S // 2], v[:, :S // 2], causal=causal, impl="jnp")
        o2, l2 = flash.flash_block_attention(
            q, k[:, S // 2:], v[:, S // 2:], causal=causal,
            kv_offset=S // 2, impl="jnp")
        out, _ = flash.merge_partials(o1, l1, o2, l2)
        ref = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-10, atol=1e-12)

    def test_offsets_shift_the_causal_frontier(self):
        q, k, v = qkv((B, S, H, D))
        # q sits entirely after kv: causal mask passes everything.
        out, _ = flash.flash_block_attention(q, k, v, causal=True,
                                             q_offset=S, impl="jnp")
        ref = dense_attention(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-12, atol=1e-14)

    def test_fully_masked_block_is_neutral(self):
        q, k, v = qkv((B, S, H, D))
        out, lse = flash.flash_block_attention(q, k, v, causal=True,
                                               kv_offset=S, impl="jnp")
        assert np.all(np.asarray(out) == 0.0)
        assert np.all(np.asarray(lse) == flash.NEG_BIG)
        # Merging it changes nothing.
        o1, l1 = flash.flash_block_attention(q, k, v, impl="jnp")
        o2, l2 = flash.merge_partials(o1, l1, out, lse)
        np.testing.assert_allclose(np.asarray(o2), np.asarray(o1),
                                   rtol=1e-12, atol=1e-14)

    def test_tiled_backward_matches_dense_oracle(self):
        # sk=1024 crosses _BWD_TILE_ABOVE: the backward recomputes scores
        # in KV tiles; gradients must still match the dense oracle.
        q, k, v = qkv((1, 1024, 2, 8), seed=5)
        assert k.shape[1] > flash._BWD_TILE_ABOVE

        def f_flash(q, k, v):
            out, _ = flash.flash_block_attention(q, k, v, causal=True,
                                                 impl="jnp")
            return jnp.sum(out ** 2)

        def f_dense(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-9, atol=1e-11)

    def test_grads_match_dense_oracle(self):
        q, k, v = qkv((B, S, H, D))

        def f_flash(q, k, v):
            out, _ = flash.flash_block_attention(q, k, v, causal=True,
                                                 impl="jnp")
            return jnp.sum(out ** 2)

        def f_dense(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-10, atol=1e-12)


class TestPallasKernel:
    """f32 shapes meeting the TPU tiling constraints, run interpreted."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_jnp_path(self, causal):
        q, k, v = qkv((PB, PS, PH, PD), dtype=jnp.float32)
        o_p, l_p = flash.flash_block_attention(q, k, v, causal=causal,
                                               impl="pallas")
        o_j, l_j = flash.flash_block_attention(q, k, v, causal=causal,
                                               impl="jnp")
        np.testing.assert_allclose(np.asarray(o_p), np.asarray(o_j),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(l_p), np.asarray(l_j),
                                   rtol=1e-5, atol=1e-6)

    def test_traced_offsets(self):
        q, k, v = qkv((PB, PS, PH, PD), dtype=jnp.float32)

        @jax.jit
        def f(off):
            return flash.flash_block_attention(
                q, k, v, causal=True, q_offset=off, impl="pallas")[0]

        got = f(jnp.asarray(PS))
        ref, _ = flash.flash_block_attention(q, k, v, causal=True,
                                             q_offset=PS, impl="jnp")
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_grads_flow(self):
        q, k, v = qkv((PB, PS, PH, PD), dtype=jnp.float32)

        def f(q, k, v):
            out, _ = flash.flash_block_attention(q, k, v, causal=True,
                                                 impl="pallas")
            return jnp.sum(out ** 2)

        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        ref = jax.grad(
            lambda q, k, v: jnp.sum(
                flash.flash_block_attention(q, k, v, causal=True,
                                            impl="jnp")[0] ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestPallasBackwardKernel:
    """The fused dq/dk/dv kernels (interpret mode) vs the jnp backward.
    test_grads_flow above covers the plain-out cotangent; these cover the
    kernel-dispatch predicate and the lse cotangent (dlse is live under
    ring attention, whose merge consumes lse)."""

    def test_bwd_kernel_dispatch_predicate(self):
        q, k, v = qkv((PB, PS, PH, PD), dtype=jnp.float32)
        assert flash._bwd_eligible(q, k)
        qd, kd, vd = qkv((B, S, H, D))          # f64: x64 oracle suite
        assert not flash._bwd_eligible(qd, kd)

    def test_lse_cotangent_matches_jnp(self):
        q, k, v = qkv((1, 256, 2, 128), dtype=jnp.float32, seed=3)

        def loss(impl):
            def f(q, k, v):
                out, lse = flash.flash_block_attention(
                    q, k, v, causal=True, impl=impl)
                # lse participates with a nontrivial weight, as in the
                # ring merge.
                return jnp.sum(out ** 2) + jnp.sum(jnp.sin(lse))
            return f

        ga = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(loss("jnp"), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_fully_masked_rows_zero_grads(self):
        # kv entirely in the future of q: every row masked, lse=NEG_BIG;
        # the kernel's where-masking must keep p (= exp(garbage)) out of
        # the gradients, yielding exact zeros like the oracle.
        q, k, v = qkv((1, 128, 1, 64), dtype=jnp.float32)

        def f(q, k, v):
            out, _ = flash.flash_block_attention(
                q, k, v, causal=True, kv_offset=256, impl="pallas")
            return jnp.sum(out ** 2)

        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        for a in g:
            np.testing.assert_array_equal(np.asarray(a), 0.0)


class TestTunableTiles:
    """Non-default _Q_TILE/_KV_TILE configurations (the knobs
    bench_tradeoffs.py flash_tiling sweeps on chip) must stay
    oracle-correct, forward AND backward — KV tiles wider than the
    128-lane stat slab exercise _stat_tile's lane-tiling branch."""

    @pytest.mark.parametrize("qt,kt", [(256, 128), (256, 256),
                                       (512, 512), (128, 256)])
    def test_tiles_match_jnp_fwd_bwd(self, qt, kt, monkeypatch):
        monkeypatch.setattr(flash, "_Q_TILE", qt)
        monkeypatch.setattr(flash, "_KV_TILE", kt)
        q, k, v = qkv((1, 512, 2, 64), dtype=jnp.float32, seed=5)

        def loss(impl):
            return lambda q, k, v: jnp.sum(flash.flash_attention(
                q, k, v, causal=True, impl=impl) ** 2)

        out_p = flash.flash_attention(q, k, v, causal=True, impl="pallas")
        out_j = flash.flash_attention(q, k, v, causal=True, impl="jnp")
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_j),
                                   rtol=1e-5, atol=1e-6)
        gp = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        gj = jax.grad(loss("jnp"), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gj):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_windowed_gqa_at_wide_tiles(self, monkeypatch):
        monkeypatch.setattr(flash, "_Q_TILE", 256)
        monkeypatch.setattr(flash, "_KV_TILE", 256)
        q, _, _ = qkv((1, 512, 4, 64), dtype=jnp.float32, seed=7)
        _, k, v = qkv((1, 512, 2, 64), dtype=jnp.float32, seed=8)

        def loss(impl):
            return lambda q, k, v: jnp.sum(flash.flash_attention(
                q, k, v, causal=True, window=100, impl=impl) ** 2)

        gp = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        gj = jax.grad(loss("jnp"), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gj):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)


class TestLanePadding:
    """head_dim 64/96 take the kernel via zero-padding to the 128 lane
    width (round-1 gap: the common d=64 silently fell back to jnp)."""

    @pytest.mark.parametrize("d", [64, 96])
    @pytest.mark.parametrize("causal", [False, True])
    def test_padded_head_dim_matches_jnp(self, d, causal):
        q, k, v = qkv((1, 256, 2, d), dtype=jnp.float32)
        assert flash._eligible(q, k)
        a, la = flash.flash_block_attention(q, k, v, causal=causal,
                                            impl="pallas")
        b, lb = flash.flash_block_attention(q, k, v, causal=causal,
                                            impl="jnp")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-4, atol=1e-5)

    def test_padded_head_dim_grads_match(self):
        q, k, v = qkv((1, 128, 2, 64), dtype=jnp.float32)

        def loss(impl):
            return lambda q, k, v: jnp.sum(flash.flash_block_attention(
                q, k, v, causal=True, impl=impl)[0] ** 2)

        ga = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(loss("jnp"), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)


class TestCausalTileSkip:
    """The diagonal-cut loop bounds (_causal_n_live and the dkv i_start)
    must be exact at UNALIGNED offsets: a bound off by one tile either
    recomputes masked work (benign) or skips live keys (wrong output).
    Sweep odd offsets through the forced kernel path vs the jnp oracle —
    forward, lse, and all three gradients."""

    @pytest.mark.parametrize("q_off,kv_off", [
        (0, 0), (1, 0), (0, 1), (77, 0), (0, 77), (128, 200), (200, 128),
        (1000, 999), (999, 1000), (50, 300),
    ])
    def test_unaligned_offsets_match_jnp(self, q_off, kv_off):
        q, k, v = qkv((1, 256, 1, 64), dtype=jnp.float32,
                      seed=q_off * 7 + kv_off)

        def loss(impl):
            def f(q, k, v):
                out, lse = flash.flash_block_attention(
                    q, k, v, causal=True, q_offset=q_off,
                    kv_offset=kv_off, impl=impl)
                safe = jnp.where(lse > flash.NEG_BIG / 2, lse, 0.0)
                return jnp.sum(out ** 2) + jnp.sum(safe)
            return f

        op, lp = flash.flash_block_attention(
            q, k, v, causal=True, q_offset=q_off, kv_offset=kv_off,
            impl="pallas")
        oj, lj = flash.flash_block_attention(
            q, k, v, causal=True, q_offset=q_off, kv_offset=kv_off,
            impl="jnp")
        np.testing.assert_allclose(np.asarray(op), np.asarray(oj),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(lp), np.asarray(lj),
                                   rtol=1e-4, atol=1e-5)
        gp = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        gj = jax.grad(loss("jnp"), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gj):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)


class TestIntegerPositions:
    def test_positions_exact_beyond_f32_range(self):
        # Query block at position 2^24 against one key at 2^24 + 1.  The
        # earlier f32 position encoding rounded both to 2^24, unmasking
        # the future key for row 0; i32 positions keep the frontier exact
        # (the long-context correctness cliff, ADVICE round 1).
        big = 2 ** 24
        q, k, v = qkv((1, 8, 1, D))
        o, lse = flash.flash_block_attention(
            q, k[:, :1], v[:, :1], causal=True, q_offset=big,
            kv_offset=big + 1, impl="jnp")
        assert float(lse[0, 0, 0]) == flash.NEG_BIG     # masked
        np.testing.assert_array_equal(np.asarray(o[0, 0]), 0.0)
        assert np.all(np.asarray(lse[0, 1:]) > flash.NEG_BIG)  # visible

    def test_pallas_positions_exact_beyond_f32_range(self):
        # Same frontier exactness through the kernel's i32 SMEM offsets +
        # iota path (interpret mode): an f32 regression there would
        # unmask future keys only at long-context offsets.
        big = 2 ** 24
        q, k, v = qkv((1, 128, 1, 64), dtype=jnp.float32)
        a, la = flash.flash_block_attention(
            q, k, v, causal=True, q_offset=big, kv_offset=big + 1,
            impl="pallas")
        b, lb = flash.flash_block_attention(
            q, k, v, causal=True, q_offset=big, kv_offset=big + 1,
            impl="jnp")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
        # Row 0 sees no keys (first key is one position in its future).
        assert float(la[0, 0, 0]) <= -1e29
        assert float(lb[0, 0, 0]) <= -1e29


@pytest.mark.skipif(jax.devices()[0].platform != "tpu",
                    reason="compiled (non-interpret) kernel needs a TPU")
class TestCompiledKernelOnTPU:
    """Hardware gate: the non-interpret Pallas kernel vs the jnp oracle.

    Skipped on the CPU-mesh CI harness (conftest pins the cpu platform
    unless the ``MPI4TORCH_TPU_REAL_DEVICES=1`` hatch is set); run on
    hardware via ``make tpu-test`` — the driver's bench.py exercises the
    same compiled kernel through impl='auto'."""

    @pytest.mark.parametrize("d", [64, 128])
    def test_compiled_matches_jnp(self, d):
        q, k, v = qkv((2, 512, 4, d), dtype=jnp.float32)
        a, la = flash.flash_block_attention(q, k, v, causal=True,
                                            impl="pallas")
        b, lb = flash.flash_block_attention(q, k, v, causal=True,
                                            impl="jnp")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-4, atol=1e-5)

    def test_compiled_bench_shape_bf16(self):
        # The bench.py flash sub-bench shape — the exact configuration
        # whose lowering failure cost round 3 its numbers.
        q, k, v = qkv((4, 4096, 8, 128), dtype=jnp.bfloat16, seed=7)
        a, _ = flash.flash_block_attention(q, k, v, causal=True,
                                           impl="pallas")
        b, _ = flash.flash_block_attention(q, k, v, causal=True,
                                           impl="jnp")
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=5e-2, atol=5e-2)

    def test_compiled_grads_match_jnp(self):
        q, k, v = qkv((2, 512, 4, 128), dtype=jnp.float32)

        def loss(impl):
            return lambda q, k, v: jnp.sum(flash.flash_block_attention(
                q, k, v, causal=True, impl=impl)[0] ** 2)

        ga = jax.jit(jax.grad(loss("pallas"), argnums=(0, 1, 2)))(q, k, v)
        gb = jax.jit(jax.grad(loss("jnp"), argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)

    def test_compiled_chunked_long_kv(self):
        # Over-budget KV on the real chip: auto must scan the compiled
        # kernel over chunks and match the (chunked-jnp) oracle.
        q, _, _ = qkv((1, 128, 1, 128), dtype=jnp.float32, seed=12)
        rng = np.random.default_rng(13)
        k = jnp.asarray(rng.standard_normal((1, 32768, 1, 128)) * 0.3,
                        jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 32768, 1, 128)) * 0.3,
                        jnp.float32)
        assert flash._kv_chunk_for(q, k) == 8192
        got = flash.flash_attention(q, k, v, causal=True, impl="auto")
        want = flash.flash_attention(q, k, v, causal=True, impl="jnp",
                                     kv_chunk=8192)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    def test_compiled_gqa_matches_jnp(self):
        # GQA on hardware: grouped KV index maps in all three kernels
        # (fwd, dq, dkv-partial) must lower and match the repeat oracle.
        rng = np.random.default_rng(21)
        q = jnp.asarray(rng.standard_normal((2, 512, 8, 128)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((2, 512, 2, 128)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((2, 512, 2, 128)), jnp.float32)

        def loss(impl):
            return lambda q, k, v: jnp.sum(flash.flash_block_attention(
                q, k, v, causal=True, impl=impl)[0] ** 2)

        a, la = flash.flash_block_attention(q, k, v, causal=True,
                                            impl="pallas")
        b, lb = flash.flash_block_attention(q, k, v, causal=True,
                                            impl="jnp")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
        ga = jax.jit(jax.grad(loss("pallas"), argnums=(0, 1, 2)))(q, k, v)
        gb = jax.jit(jax.grad(loss("jnp"), argnums=(0, 1, 2)))(q, k, v)
        for x, y in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-3, atol=1e-4)

    def test_compiled_sliding_window_matches_jnp(self):
        # Windowed masking + two-frontier tile-skip on hardware, fwd and
        # bwd, window deliberately NOT a tile multiple.
        q, k, v = qkv((2, 1024, 4, 128), dtype=jnp.float32, seed=22)

        def loss(impl):
            return lambda q, k, v: jnp.sum(flash.flash_block_attention(
                q, k, v, causal=True, window=200, impl=impl)[0] ** 2)

        a, _ = flash.flash_block_attention(q, k, v, causal=True,
                                           window=200, impl="pallas")
        b, _ = flash.flash_block_attention(q, k, v, causal=True,
                                           window=200, impl="jnp")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
        ga = jax.jit(jax.grad(loss("pallas"), argnums=(0, 1, 2)))(q, k, v)
        gb = jax.jit(jax.grad(loss("jnp"), argnums=(0, 1, 2)))(q, k, v)
        for x, y in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-3, atol=1e-4)

    def test_auto_selects_pallas_and_runs(self):
        # impl='auto' on hardware must engage the compiled kernel (probe
        # passes) and agree with the oracle — the flagship-model path.
        q, k, v = qkv((2, 512, 4, 128), dtype=jnp.float32)
        assert flash._eligible(q, k)
        a = flash.flash_attention(q, k, v, causal=True, impl="auto")
        b = flash.flash_attention(q, k, v, causal=True, impl="jnp")
        assert flash._pallas_compiles(512, 512, 128, q.dtype, True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


class TestChunkedKV:
    """The long-KV scan path of flash_attention: budget-sized chunks
    through the block kernel, merged by the online-softmax rule — the
    path Ulysses long context takes when one KV block would blow VMEM."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_chunked_matches_unchunked_jnp(self, causal):
        q, k, v = qkv((1, 64, 2, 8), seed=2)   # f64: exact-oracle regime
        a = flash.flash_attention(q, k, v, causal=causal, impl="jnp",
                                  kv_chunk=16)
        b = flash.flash_attention(q, k, v, causal=causal, impl="jnp")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-10, atol=1e-12)

    def test_chunked_grads_match_unchunked(self):
        q, k, v = qkv((1, 64, 2, 8), seed=4)

        def loss(chunk):
            return lambda q, k, v: jnp.sum(flash.flash_attention(
                q, k, v, causal=True, impl="jnp", kv_chunk=chunk) ** 2)

        ga = jax.grad(loss(16), argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(loss(0), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-9, atol=1e-11)

    def test_chunked_pallas_blocks_match_oracle(self):
        # Forced kernel path (interpret off-TPU), 2 chunks of 128.
        q, k, v = qkv((1, 128, 1, 64), dtype=jnp.float32, seed=6)
        k2 = jnp.concatenate([k, k * 0.5], axis=1)
        v2 = jnp.concatenate([v, v * 2.0], axis=1)
        a = flash.flash_attention(q, k2, v2, causal=True, impl="pallas",
                                  kv_chunk=128)
        b = flash.flash_attention(q, k2, v2, causal=True, impl="jnp")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

    def test_auto_chunks_over_budget_kv(self):
        # 32K f32 keys at d=128 stage 32 MB — over the 8 MB budget; auto
        # must pick the largest dividing chunk (8192) instead of falling
        # back to the quadratic jnp path.
        q = jnp.zeros((1, 128, 1, 128), jnp.float32)
        k = jnp.zeros((1, 32768, 1, 128), jnp.float32)
        assert not flash._eligible(q, k)
        assert flash._kv_chunk_for(q, k) == 8192

    def test_no_chunk_when_shape_cannot_be_eligible(self):
        q = jnp.zeros((1, 128, 1, 8), jnp.float32)     # d too small
        k = jnp.zeros((1, 32768, 1, 8), jnp.float32)
        assert flash._kv_chunk_for(q, k) == 0
        kr = jnp.zeros((1, 32700, 1, 128), jnp.float32)  # not tile-divisible
        assert flash._kv_chunk_for(
            jnp.zeros((1, 128, 1, 128), jnp.float32), kr) == 0

    def test_bad_kv_chunk_raises(self):
        q, k, v = qkv((1, 128, 1, 64), dtype=jnp.float32)
        with pytest.raises(ValueError, match="kv_chunk"):
            flash.flash_attention(q, k, v, kv_chunk=100)

    def test_long_context_end_to_end(self):
        # A 16K-key attention through the auto-chunked scan (jnp blocks
        # on CPU), against the dense oracle on a thin query block — the
        # memory regime the path exists for, kept CPU-affordable.
        q, _, _ = qkv((1, 128, 1, 128), dtype=jnp.float32, seed=8)
        rng = np.random.default_rng(9)
        k = jnp.asarray(rng.standard_normal((1, 16384, 1, 128)) * 0.3,
                        jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 16384, 1, 128)) * 0.3,
                        jnp.float32)
        assert flash._kv_chunk_for(q, k) == 8192
        got = flash.flash_attention(q, k, v, causal=False, impl="auto")
        want = flash.flash_attention(q, k, v, causal=False, impl="jnp")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)


class TestGQA:
    """Grouped-query attention: k/v carry fewer heads than q; q head h
    attends through KV head h // g.  The jnp path realizes the grouping
    by KV repeat (oracle); the Pallas kernels resolve it in their KV
    BlockSpec index maps without duplicating KV."""

    @staticmethod
    def _gqa_qkv(b, s, hq, hkv, d, dtype, seed=0):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.standard_normal((b, s, hq, d)), dtype)
        k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), dtype)
        v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), dtype)
        return q, k, v

    @pytest.mark.parametrize("causal", [False, True])
    def test_jnp_matches_dense_repeat_oracle(self, causal):
        q, k, v = self._gqa_qkv(2, 16, 4, 2, 8, jnp.float64)
        out, _ = flash.flash_block_attention(q, k, v, causal=causal,
                                             impl="jnp")
        kr = jnp.repeat(k, 2, axis=2)
        vr = jnp.repeat(v, 2, axis=2)
        want = dense_attention(q, kr, vr, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-10, atol=1e-12)

    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_interpret_matches_jnp(self, causal):
        q, k, v = self._gqa_qkv(2, 256, 4, 2, 128, jnp.float32)
        a, la = flash.flash_block_attention(q, k, v, causal=causal,
                                            impl="pallas")
        b, lb = flash.flash_block_attention(q, k, v, causal=causal,
                                            impl="jnp")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-5, atol=1e-6)

    def test_pallas_bwd_interpret_grads_match(self):
        # impl='pallas' routes the backward through the fused dq and
        # per-q-head-partial dkv kernels (interpret mode off-TPU); the
        # group-summed dk/dv must match the jnp oracle's.
        q, k, v = self._gqa_qkv(1, 256, 4, 2, 128, jnp.float32, seed=3)

        def loss(impl):
            return lambda q, k, v: jnp.sum(flash.flash_block_attention(
                q, k, v, causal=True, impl=impl)[0] ** 2)

        ga = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(loss("jnp"), argnums=(0, 1, 2))(q, k, v)
        assert ga[1].shape == k.shape and ga[2].shape == v.shape
        for a, b in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)

    def test_grads_flow_through_grouping(self):
        # Each KV head's gradient is the SUM of its whole q group's
        # cotangents: the GQA dv must equal the explicit-repeat model's
        # per-head dv summed over the group.
        q, k, v = self._gqa_qkv(1, 16, 4, 1, 8, jnp.float64, seed=5)

        dv_gqa = jax.grad(lambda v: jnp.sum(flash.flash_block_attention(
            q, k, v, impl="jnp")[0]))(v)

        vr = jnp.repeat(v, 4, axis=2)
        dv_rep = jax.grad(lambda vr: jnp.sum(flash.flash_block_attention(
            q, jnp.repeat(k, 4, axis=2), vr, impl="jnp")[0]))(vr)
        want = dv_rep.reshape(1, 16, 1, 4, 8).sum(axis=3)
        np.testing.assert_allclose(np.asarray(dv_gqa), np.asarray(want),
                                   rtol=1e-10, atol=1e-12)

    def test_chunked_gqa_matches_unchunked(self):
        q, k, v = self._gqa_qkv(1, 64, 4, 2, 8, jnp.float64, seed=6)
        a = flash.flash_attention(q, k, v, causal=True, impl="jnp",
                                  kv_chunk=16)
        b = flash.flash_attention(q, k, v, causal=True, impl="jnp")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-10, atol=1e-12)

    def test_bad_head_ratio_raises(self):
        q, k, v = self._gqa_qkv(1, 16, 4, 3, 8, jnp.float64)
        with pytest.raises(ValueError, match="multiple of KV heads"):
            flash.flash_block_attention(q, k, v)


def _dense_windowed(q, k, v, window, q_off=0, kv_off=0):
    """Independent sliding-window oracle: explicit masked softmax."""
    sq, sk = q.shape[1], k.shape[1]
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale
    qp = q_off + np.arange(sq)[:, None]
    kp = kv_off + np.arange(sk)[None, :]
    mask = (qp >= kp) & (qp - kp < window)
    s = jnp.where(mask[None, :, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(mask[None, :, None, :], p, 0.0)
    return jnp.einsum("bqhk,bkhd->bqhd", p, v)


class TestSlidingWindow:
    """window > 0: each query attends its last `window` positions (itself
    included).  Masking is global-position-based; the kernels tile-skip
    BOTH frontiers (causal diagonal and window edge)."""

    @pytest.mark.parametrize("window", [1, 3, 7, 100])
    def test_jnp_matches_dense_oracle(self, window):
        q, k, v = qkv((2, 16, 2, 8), seed=11)
        out, _ = flash.flash_block_attention(q, k, v, causal=True,
                                             window=window, impl="jnp")
        want = _dense_windowed(q, k, v, window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-10, atol=1e-12)

    def test_offsets_shift_the_window(self):
        # A window spanning a block boundary: the second block's queries
        # must still see the first block's tail keys.
        q, k, v = qkv((1, 8, 1, 4), seed=12)
        q_hi = q[:, 4:]
        out, _ = flash.flash_block_attention(
            q_hi, k, v, causal=True, q_offset=4, window=6, impl="jnp")
        want = _dense_windowed(q, k, v, 6)[:, 4:]
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-10, atol=1e-12)

    @pytest.mark.parametrize("window", [64, 100, 1000])
    def test_pallas_interpret_matches_jnp(self, window):
        # Window a tile multiple, unaligned, and larger than the whole
        # sequence (=> plain causal).
        q, k, v = qkv((1, 256, 2, 128), dtype=jnp.float32, seed=13)
        a, la = flash.flash_block_attention(q, k, v, causal=True,
                                            window=window, impl="pallas")
        b, lb = flash.flash_block_attention(q, k, v, causal=True,
                                            window=window, impl="jnp")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-5, atol=1e-6)

    def test_pallas_interpret_unaligned_offsets(self):
        q, k, v = qkv((1, 256, 1, 128), dtype=jnp.float32, seed=14)
        a, _ = flash.flash_block_attention(
            q, k, v, causal=True, q_offset=300, kv_offset=170,
            window=200, impl="pallas")
        b, _ = flash.flash_block_attention(
            q, k, v, causal=True, q_offset=300, kv_offset=170,
            window=200, impl="jnp")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    def test_pallas_bwd_interpret_grads_match(self):
        q, k, v = qkv((1, 256, 2, 128), dtype=jnp.float32, seed=15)

        def loss(impl):
            return lambda q, k, v: jnp.sum(flash.flash_block_attention(
                q, k, v, causal=True, window=100, impl=impl)[0] ** 2)

        ga = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(loss("jnp"), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)

    def test_window_with_gqa(self):
        rng = np.random.default_rng(16)
        q = jnp.asarray(rng.standard_normal((1, 256, 4, 128)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 256, 2, 128)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 256, 2, 128)), jnp.float32)
        a, _ = flash.flash_block_attention(q, k, v, causal=True,
                                           window=64, impl="pallas")
        b, _ = flash.flash_block_attention(q, k, v, causal=True,
                                           window=64, impl="jnp")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    def test_chunked_windowed_matches_unchunked(self):
        q, k, v = qkv((1, 64, 2, 8), seed=17)
        a = flash.flash_attention(q, k, v, causal=True, window=20,
                                  impl="jnp", kv_chunk=16)
        b = flash.flash_attention(q, k, v, causal=True, window=20,
                                  impl="jnp")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-10, atol=1e-12)

    def test_validation(self):
        q, k, v = qkv((1, 16, 1, 8))
        with pytest.raises(ValueError, match="window must be >= 0"):
            flash.flash_block_attention(q, k, v, causal=True, window=-1)
        with pytest.raises(ValueError, match="requires causal"):
            flash.flash_block_attention(q, k, v, window=8)


class TestEligibility:
    def test_auto_falls_back_on_small_head_dim(self):
        q, k, v = qkv((B, S, H, D))
        # D=8 is below the padded-lane cutoff: auto must take the jnp path
        # (and agree with it bit-for-bit).
        assert not flash._eligible(q, k)
        a, la = flash.flash_block_attention(q, k, v, causal=True)
        b, lb = flash.flash_block_attention(q, k, v, causal=True,
                                            impl="jnp")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_bad_impl_raises(self):
        q, k, v = qkv((B, S, H, D))
        with pytest.raises(ValueError, match="unknown impl"):
            flash.flash_block_attention(q, k, v, impl="cuda")

    def test_forced_pallas_rejects_ineligible_shapes(self):
        # Silently dropping the 300 % 128 tail keys would be wrong output;
        # the forced path must refuse instead.
        q, k, v = qkv((1, 256, 2, 128), dtype=jnp.float32)
        k300 = jnp.concatenate([k, k[:, :44]], axis=1)
        v300 = jnp.concatenate([v, v[:, :44]], axis=1)
        with pytest.raises(ValueError, match="kernel-eligible"):
            flash.flash_block_attention(q, k300, v300, impl="pallas")

    def test_vmem_budget_bounds_kv_block(self):
        # A 32K-key f32 d=128 block stages 32 MB of KV — over budget.
        q = jnp.zeros((1, 128, 1, 128), jnp.float32)
        k = jnp.zeros((1, 32768, 1, 128), jnp.float32)
        assert not flash._eligible(q, k)
        k_ok = jnp.zeros((1, 4096, 1, 128), jnp.float32)
        assert flash._eligible(q, k_ok)


class TestRingAttentionPallas:
    def test_ring_with_pallas_blocks_matches_dense(self):
        # 4-rank ring over eligible f32 shapes, kernel interpreted: the
        # full CP path through the Pallas block primitive.
        NR = 4
        if len(jax.devices()) < NR:
            # Real-device mode exposes the single physical chip; the mesh
            # transport needs NR devices (CPU harness forces 8 virtual).
            pytest.skip(f"needs {NR} devices, have {len(jax.devices())}")
        S_TOT = 512
        q, k, v = qkv((1, S_TOT, 2, 128), dtype=jnp.float32)
        ref = dense_attention(q, k, v, causal=True)
        SL = S_TOT // NR

        def body():
            r = jnp.asarray(comm.rank)
            sl = [jax.lax.dynamic_slice_in_dim(t, r * SL, SL, 1)
                  for t in (q, k, v)]
            return ring_attention(comm, *sl, causal=True, impl="pallas")

        out = np.asarray(mpi.run_spmd(body, nranks=NR)())
        got = np.concatenate(list(out), axis=1)
        np.testing.assert_allclose(got, np.asarray(ref), rtol=2e-4,
                                   atol=2e-5)


class TestDotPrecision:
    """The on-chip precision contract (round-5 postmortem): TPU contracts
    f32 dot_generals in single bf16 passes at default precision, so every
    attention matmul keys its contract precision on the operand dtype —
    f32-or-wider pins HIGHEST, narrower stays on the fast single pass
    (Mosaic rejects fp32 contract precision on bf16 operands).  Asserted
    at the jaxpr level so the policy is CPU-checkable."""

    def test_dot_precision_by_dtype(self):
        assert flash.dot_precision(jnp.float32) == jax.lax.Precision.HIGHEST
        assert flash.dot_precision(jnp.float64) == jax.lax.Precision.HIGHEST
        assert flash.dot_precision(jnp.bfloat16) is None
        assert flash.dot_precision(jnp.float16) is None

    @pytest.mark.parametrize("fn", [
        lambda q: dense_attention(q, q, q, causal=True),
        lambda q: flash.flash_block_attention(q, q, q, causal=True,
                                              impl="jnp")[0],
        lambda q: jax.grad(lambda t: jnp.sum(flash.flash_block_attention(
            t, t, t, causal=True, impl="jnp")[0] ** 2))(q),
    ], ids=["dense", "flash_jnp_fwd", "flash_jnp_bwd"])
    def test_f32_pins_highest_bf16_does_not(self, fn):
        q32 = jnp.ones((1, 8, 1, 8), jnp.float32)
        assert "HIGHEST" in str(jax.make_jaxpr(fn)(q32))
        q16 = q32.astype(jnp.bfloat16)
        assert "HIGHEST" not in str(jax.make_jaxpr(fn)(q16))

    def test_dense_attention_precision_override(self):
        # Callers preferring the single-pass contract for f32 (speed over
        # exactness) can opt out.
        q = jnp.ones((1, 8, 1, 8), jnp.float32)
        jx = str(jax.make_jaxpr(lambda t: dense_attention(
            t, t, t, precision=jax.lax.Precision.DEFAULT))(q))
        assert "HIGHEST" not in jx

"""The static collective-schedule verifier (mpi4torch_tpu.analyze).

Four layers of evidence:

* **parser** — typed CollectiveOp records (kinds, replica_groups with
  declared shape, source_target_pairs, channels, payload dtype/bytes,
  named-scope labels) read off real lowerings, plus synthetic-text unit
  cases for the grammar corners;
* **lints** — each soundness lint exercised on a minimal synthetic
  program AND via the seeded-defect corpus on real mutated schedules
  (every defect caught BY ITS NAMED LINT, the ledger complete);
* **accounting** — the migrated ``wire_bytes_per_device`` /
  ``peak_live_bytes`` / ``scheduled_exposure`` passes regression-pinned
  BIT-IDENTICAL to the recorded PR 6/8/9 bench numbers (q8-bidir
  7280 B, the (8,)->(2,4) reshard migration 98304 B planned vs
  917504 B gather, the serve decode step's 14336 B / 3584.0 B-per-token
  wire and its exposure fractions), with the historical entry points
  (bench, overlap.census, reshard.census) verified to delegate;
* **sweep** — the full registry-wide lint sweep lints clean on the
  (1,), (3,), (8,) and (2,4) worlds.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import mpi4torch_tpu as mpi
from mpi4torch_tpu import analyze
from mpi4torch_tpu._compat import lowered_text, shard_map

NR = 8


@pytest.fixture(autouse=True)
def _isolated_tune_cache(tmp_path, monkeypatch):
    """Private tune cache per test: the sweep and the serve decode legs
    consult the selector, so an ambient user cache (or a winner another
    test measured) must not change which wire a lowering rides."""
    monkeypatch.setenv("MPI4TORCH_TPU_TUNE_CACHE",
                       str(tmp_path / "tune_cache.json"))
    from mpi4torch_tpu import tune
    tune.clear()
    yield
    tune.clear()


def _lower(body, *args, nr=NR, debug=True):
    mesh = Mesh(np.asarray(jax.devices()[:nr]), ("w",))
    comm = mpi.comm_from_mesh(mesh, "w")
    fn = shard_map(lambda *a: body(comm, *a), mesh=mesh, in_specs=P(),
                   out_specs=P(), check_vma=False)
    return lowered_text(jax.jit(fn).lower(*args), debug_info=debug)


# =========================================================================
# Synthetic programs: precise grammar-corner cases without lowering cost
# =========================================================================

def synth(*op_lines, npart=8):
    body = "\n".join(f"    {ln}" for ln in op_lines)
    return (
        "module @m attributes "
        f"{{mhlo.num_partitions = {npart} : i32, "
        "mhlo.num_replicas = 1 : i32} {\n"
        "  func.func public @main(%arg0: tensor<32xf32>) "
        "-> (tensor<32xf32>) {\n"
        f"{body}\n"
        "    return %arg0 : tensor<32xf32>\n"
        "  }\n"
        "}\n")


def permute_line(pairs, res="%1", arg="%arg0", handle=1,
                 ty="tensor<32xf32>"):
    table = str([list(p) for p in pairs])
    return (f'{res} = "stablehlo.collective_permute"({arg}) '
            f"<{{channel_handle = #stablehlo.channel_handle<handle = "
            f"{handle}, type = 1>, source_target_pairs = "
            f"dense<{table}> : tensor<{len(pairs)}x2xi64>}}> : "
            f"({ty}) -> {ty}")


def all_gather_line(groups, res="%1", arg="%arg0",
                    ty_in="tensor<32xf32>", ty_out="tensor<64xf32>"):
    table = str([list(g) for g in groups])
    r, c = len(groups), len(groups[0])
    return (f'{res} = "stablehlo.all_gather"({arg}) '
            f"<{{all_gather_dim = 0 : i64, channel_handle = "
            f"#stablehlo.channel_handle<handle = 1, type = 1>, "
            f"replica_groups = dense<{table}> : tensor<{r}x{c}xi64>, "
            f"use_global_device_ids}}> : ({ty_in}) -> {ty_out}")


class TestParser:
    def test_synthetic_permute_record(self):
        p = analyze.parse_program(
            synth(permute_line([(0, 1), (1, 2), (2, 0)], handle=7)))
        assert p.num_partitions == 8
        (op,) = p.ops("collective_permute")
        assert op.source_target_pairs == ((0, 1), (1, 2), (2, 0))
        assert op.channel == 7
        assert op.dtype == "f32"
        assert op.payload_bytes == 128
        assert op.replica_groups is None

    def test_synthetic_all_gather_record(self):
        p = analyze.parse_program(
            synth(all_gather_line([[0, 1, 2, 3], [4, 5, 6, 7]])))
        (op,) = p.ops("all_gather")
        assert op.replica_groups == ((0, 1, 2, 3), (4, 5, 6, 7))
        assert op.group_shape == (2, 4)
        assert op.group_size == 4
        assert op.operand_types == ("32xf32",)
        assert op.result_types == ("64xf32",)

    def test_splat_replica_groups(self):
        # A splat dense literal expands to the declared shape.
        line = all_gather_line([[0]]).replace(
            "dense<[[0]]> : tensor<1x1xi64>", "dense<0> : tensor<1x1xi64>")
        (op,) = analyze.parse_program(synth(line, npart=1)).collectives
        assert op.replica_groups == ((0,),)

    def test_tensor_bytes(self):
        assert analyze.tensor_bytes("8x128xf32") == 8 * 128 * 4
        assert analyze.tensor_bytes("16xi8") == 16
        assert analyze.tensor_bytes("f64") == 8
        assert analyze.tensor_bytes("?xf32") == 0      # dynamic dim
        assert analyze.tensor_bytes("4x!quant") == 0   # unknown elem

    def test_bucket_of(self):
        assert analyze.bucket_of(
            "jit(f)/mpi4torch.Allreduce_tree.bucket2of5.start/x") == \
            ("Allreduce_tree", 2, 5, "start")
        assert analyze.bucket_of("mpi4torch.Allreduce") is None

    def test_real_ring_lowering(self):
        txt = _lower(lambda c, x: c.Allreduce(x, mpi.MPI_SUM),
                     jnp.ones((64,), jnp.float32))
        p = analyze.parse_program(txt)
        assert p.num_partitions == NR
        (op,) = p.collectives
        assert op.kind == "all_reduce"
        assert op.group_size == NR
        assert sorted(v for g in op.replica_groups for v in g) == \
            list(range(NR))
        # The named scope survives onto the wire op's record — the
        # region op's loc sits on its `}) :` closing line.
        assert op.label == "mpi4torch.Allreduce"

    def test_real_bidir_rotations(self):
        # The typed records replace compress.int8_rotation_census-style
        # table matching: both counter-rotations appear as
        # source_target_pairs on the dual ring.
        txt = _lower(
            lambda c, x: c.Allreduce(x, mpi.MPI_SUM, algorithm="bidir"),
            jnp.ones((512,), jnp.float32))
        p = analyze.parse_program(txt)
        tables = {op.source_target_pairs
                  for op in p.ops("collective_permute")}
        fwd = tuple((i, (i + 1) % NR) for i in range(NR))
        bwd = tuple((i, (i - 1) % NR) for i in range(NR))
        assert fwd in tables and bwd in tables
        # distinct channels per hop
        chans = [op.channel for op in p.ops("collective_permute")]
        assert len(set(chans)) == len(chans)

    def test_census_matches_text_counts(self):
        txt = _lower(
            lambda c, x: c.Allreduce(x, mpi.MPI_SUM, algorithm="hier"),
            jnp.ones((512,), jnp.float32))
        got = analyze.parse_program(txt).census()
        want = {k: txt.count(f"stablehlo.{k}")
                for k in analyze.COLLECTIVE_KINDS}
        assert got == want


# =========================================================================
# Lints: synthetic corners
# =========================================================================

class TestLints:
    def test_clean_permute_lints_clean(self):
        assert analyze.run_lints(
            synth(permute_line([(0, 1), (1, 2)]))) == []

    def test_duplicate_target_fires(self):
        (v,) = analyze.run_lints(
            synth(permute_line([(0, 1), (2, 1)])))
        assert v.lint == "permute-pairs" and "target" in v.detail

    def test_duplicate_source_fires(self):
        (v,) = analyze.run_lints(
            synth(permute_line([(0, 1), (0, 2)])))
        assert v.lint == "permute-pairs" and "source" in v.detail

    def test_out_of_range_rank_fires(self):
        (v,) = analyze.run_lints(
            synth(permute_line([(0, 9)])))
        assert v.lint == "permute-pairs" and "outside" in v.detail

    def test_partial_permutation_is_legal(self):
        # A PARTIAL permutation (not every rank sends) is valid — the
        # tree/binomial schedules permute shrinking subsets.
        assert analyze.run_lints(
            synth(permute_line([(4, 0), (5, 1)]))) == []

    def test_non_partitioning_group_fires(self):
        viols = analyze.run_lints(
            synth(all_gather_line([[0, 1, 2, 3], [4, 5, 6, 6]])))
        assert {v.lint for v in viols} == {"replica-groups"}
        details = " ".join(v.detail for v in viols)
        assert "[6]" in details        # duplicated rank
        assert "[7]" in details        # rank in no group

    def test_group_partition_of_subset_mesh(self):
        # num_partitions comes from the module: 4-device groups over a
        # 4-partition module partition correctly.
        line = all_gather_line([[0, 1], [2, 3]])
        assert analyze.run_lints(synth(line, npart=4)) == []

    def test_vjp_symmetry_self(self):
        fwd = synth(permute_line([(0, 1), (1, 0)]))
        both = synth(permute_line([(0, 1), (1, 0)]),
                     permute_line([(0, 1), (1, 0)], res="%2", arg="%1",
                                  handle=2))
        assert analyze.check_vjp_symmetry(fwd, both, "self") == []
        (v,) = analyze.check_vjp_symmetry(fwd, fwd, "self")
        assert v.lint == "vjp-symmetry"

    def test_vjp_symmetry_transpose_mapping(self):
        # A gather-shaped schedule may declare its adjoint scatters:
        # fwd = all_gather, bwd adds a reduce_scatter.
        fwd = synth(all_gather_line([[0, 1, 2, 3, 4, 5, 6, 7]]))
        rs = all_gather_line([[0, 1, 2, 3, 4, 5, 6, 7]], res="%2",
                             arg="%1").replace(
            "stablehlo.all_gather", "stablehlo.reduce_scatter")
        both = synth(all_gather_line([[0, 1, 2, 3, 4, 5, 6, 7]]), rs)
        decl = {"all_gather": "reduce_scatter"}
        assert analyze.check_vjp_symmetry(fwd, both, decl) == []
        assert analyze.check_vjp_symmetry(fwd, both, "self") != []

    def test_unknown_declaration_raises(self):
        fwd = synth(permute_line([(0, 1)]))
        with pytest.raises(ValueError, match="vjp_census"):
            analyze.check_vjp_symmetry(fwd, fwd, "mirror")

    def test_every_registered_algorithm_declares_symmetry(self):
        from mpi4torch_tpu import tune
        for name in tune.available_algorithms():
            decl = tune.get_algorithm(name).vjp_census
            assert decl == "self" or isinstance(decl, dict), (name, decl)


# =========================================================================
# Seeded-defect corpus: every lint fires, by name
# =========================================================================

@pytest.fixture(scope="module")
def corpus_programs():
    from mpi4torch_tpu.analyze.__main__ import _corpus_programs
    return _corpus_programs()


class TestDefectCorpus:
    def test_every_defect_caught_by_its_named_lint(self, corpus_programs):
        records = analyze.run_defect_corpus(corpus_programs)
        assert sorted(r["defect"] for r in records) == sorted(
            analyze.DEFECTS)
        for rec in records:
            assert rec["clean_ok"], rec
            assert rec["fired"], rec

    def test_ledger_every_lint_covered(self, corpus_programs):
        records = analyze.run_defect_corpus(corpus_programs)
        assert analyze.defect_ledger_problems(records) == []

    def test_ledger_detects_uncovered_lint(self, monkeypatch):
        ghost = analyze.DEFECTS.pop("non-partitioning-group")
        try:
            problems = analyze.defect_ledger_problems()
            assert problems and "replica-groups" in " ".join(problems)
        finally:
            analyze.DEFECTS[ghost.name] = ghost

    def test_ledger_detects_unfired_defect(self, corpus_programs):
        records = analyze.run_defect_corpus(corpus_programs)
        records[0] = dict(records[0], fired=False)
        problems = analyze.defect_ledger_problems(records)
        assert any("did not fire" in p for p in problems)


# =========================================================================
# Accounting: recorded BENCH/smoke numbers, bit-identical
# =========================================================================

class TestWireBytesRegression:
    """The PR 6 multipath wire table, re-read through the analyzer
    parse: the recorded per-device bytes must reproduce EXACTLY."""

    @pytest.fixture(scope="class")
    def multipath(self):
        x = jnp.ones((1 << 12,), jnp.float32)   # the bench payload
        out = {}
        for label, codec, algo in (("fp32-bidir", False, "bidir"),
                                   ("q8-bidir", "q8", "bidir")):
            out[label] = _lower(
                lambda c, v, codec=codec, algo=algo: c.Allreduce(
                    v, mpi.MPI_SUM, compression=codec, algorithm=algo),
                x, debug=False)
        return out

    def test_q8_bidir_wire_bytes_pinned(self, multipath):
        wire, counts = analyze.wire_bytes_per_device(
            multipath["q8-bidir"])
        assert wire == 7280                      # BENCH r05 recorded
        assert counts == {"collective_permute": 28, "all_gather": 4}

    def test_fp32_bidir_wire_bytes_pinned(self, multipath):
        wire, counts = analyze.wire_bytes_per_device(
            multipath["fp32-bidir"])
        assert wire == 28672
        assert counts == {"collective_permute": 28}
        # the recorded 3.938x >= 3.5 wire-advantage verdict
        assert round(28672 / 7280, 3) == 3.938

    def test_bench_entry_point_delegates(self, multipath):
        import bench
        assert bench._hlo_wire_bytes_per_device(multipath["q8-bidir"]) \
            == analyze.wire_bytes_per_device(multipath["q8-bidir"])


class TestReshardCensusRegression:
    """The PR 8 (8,)->(2,4) migration census: wire bytes AND peak live
    bytes, planned vs gather, pinned to the recorded values.  The
    bench runs without x64 (the liveness scan prices i32 index
    constants there, i64 under the x64 test harness — wire bytes are
    invariant but peak live shifts by the constant widths), so the
    programs lower under ``disable_x64`` to reproduce the recorded
    numbers bit-identically."""

    @pytest.fixture(scope="class")
    def migration(self):
        from mpi4torch_tpu import reshard as rs
        fl = rs.layout((NR,), 0, None)
        tl = rs.layout((2, 4), 0, 1)
        G = (1024, 256)                          # the bench shapes
        x = jnp.zeros(fl.shard_shape(G), jnp.float32)
        with jax.experimental.disable_x64():
            return {
                strategy or "planned": _lower(
                    lambda c, v, s=strategy: c.Reshard(v, fl, tl,
                                                       strategy=s),
                    x, debug=False)
                for strategy in (None, "gather")}

    def test_planned_pinned(self, migration):
        wire, counts = analyze.wire_bytes_per_device(
            migration["planned"])
        assert (wire, counts) == (98304, {"all_to_all": 1})
        assert analyze.peak_live_bytes(migration["planned"]) == 426039

    def test_gather_pinned(self, migration):
        wire, counts = analyze.wire_bytes_per_device(
            migration["gather"])
        assert (wire, counts) == (917504, {"all_gather": 1})
        assert analyze.peak_live_bytes(migration["gather"]) == 1343606

    def test_reshard_entry_point_delegates(self, migration):
        from mpi4torch_tpu import reshard as rs
        assert rs.peak_live_bytes(migration["planned"]) == \
            analyze.peak_live_bytes(migration["planned"])
        assert rs.tensor_bytes("4x2xf32") == analyze.tensor_bytes(
            "4x2xf32")


class TestServeCensusRegression:
    """The PR 9 serve decode-step census: per-step/per-token wire bytes
    and the scheduled-exposure fractions, pinned to the recorded bench
    values (slots=4 on the 8-rank TP world)."""

    @pytest.fixture(scope="class")
    def decode(self):
        from mpi4torch_tpu.models import transformer as T
        from mpi4torch_tpu.serve import Engine, ServeConfig

        cfg = T.TransformerConfig(vocab=256, d_model=64, n_heads=8,
                                  n_layers=4, d_ff=128, max_seq=64)
        out = {}
        # The bench environment runs without x64 (see the reshard
        # regression class) and under the stand-in latency crossover
        # bench._serve_census installs (decode chunks land in the
        # latency tier, which picks the wire schedule the recorded
        # exposure fractions census).
        prev = mpi.config.latency_crossover_bytes()
        mpi.config.set_latency_crossover_bytes(1 << 14)
        try:
            with jax.experimental.disable_x64():
                params = T.init_transformer(jax.random.PRNGKey(0), cfg,
                                            dtype=jnp.float32)
                for name, ov in (("overlap", True), ("blocking", False)):
                    eng = Engine(cfg, params,
                                 ServeConfig(slots=4, overlap=ov),
                                 spmd=True, nranks=NR)
                    eng.submit(np.array([1, 2, 3, 4, 5]), max_new=3)
                    eng.step()
                    out[name] = lowered_text(eng.lower_step(),
                                             debug_info=True)
        finally:
            mpi.config.set_latency_crossover_bytes(prev)
        return out

    def test_wire_bytes_per_step_pinned(self, decode):
        for name in ("overlap", "blocking"):
            wire, _ = analyze.wire_bytes_per_device(decode[name])
            assert wire == 14336, name
            assert round(wire / 4, 1) == 3584.0   # per-token at slots=4

    def test_exposure_fractions_pinned(self, decode):
        ov = analyze.scheduled_exposure(decode["overlap"])
        bl = analyze.scheduled_exposure(decode["blocking"])
        assert (ov["n_buckets"], ov["exposed_fraction"]) == (16, 0.5625)
        assert (bl["n_buckets"], bl["exposed_fraction"]) == (8, 1.0)

    def test_overlap_entry_point_delegates(self, decode):
        assert mpi.overlap.scheduled_exposure(decode["overlap"]) == \
            analyze.scheduled_exposure(decode["overlap"])


# =========================================================================
# Registry guards + sweep
# =========================================================================

class TestRegistryGuards:
    def test_set_drift_formats_message(self):
        from mpi4torch_tpu.analyze.registry import set_drift
        assert set_drift({"a"}, {"a"}, "x") == []
        (msg,) = set_drift({"a", "b"}, {"a"},
                           "reg {registered} cov {covered}")
        assert msg == "reg ['a', 'b'] cov ['a']"

    def test_standing_problems_clean(self):
        from mpi4torch_tpu.analyze.registry import standing_problems
        assert standing_problems() == []

    def test_tune_guard_catches_ghost_algorithm(self):
        from mpi4torch_tpu import tune
        from mpi4torch_tpu.analyze.registry import tune_problems
        from mpi4torch_tpu.tune.registry import _REGISTRY, AlgorithmSpec

        ghost = AlgorithmSpec(name="ghost_algo")
        _REGISTRY[ghost.name] = ghost
        try:
            algos = tuple(a for a in tune.available_algorithms()
                          if a != "ghost_algo")
            problems = tune_problems(algos, algos,
                                     ("ring", "bidir", "torus"))
            assert problems and "ghost_algo" in " ".join(problems)
        finally:
            del _REGISTRY[ghost.name]


class TestSweep:
    """Satellite: the full registry sweep lints clean on the (1,),
    (3,), (8,) and (2,4) worlds.  The serve decode leg (an engine
    compile) runs once, on the full world."""

    @pytest.mark.parametrize("world", [(1,), (3,), (8,), (2, 4)])
    def test_sweep_world_lints_clean(self, world):
        res = analyze.run_sweep(world, include_serve=False)
        assert res["violations"] == []
        assert res["problems"] == []
        assert res["n_cases"] > 0

    def test_sweep_serve_leg_lints_clean(self):
        from mpi4torch_tpu.analyze.sweep import _sweep_serve
        records = []
        _sweep_serve(records, NR)
        assert [r["violations"] for r in records] == [[], []]
        exposures = {r["case"].split(".")[-1]: r["scheduled_exposure"]
                     for r in records}
        assert exposures["blocking"] == 1.0
        assert exposures["overlap"] < 1.0

    def test_sweep_worlds_enumeration(self):
        assert analyze.sweep_worlds(8) == [(8,), (3,), (1,), (2, 4)]
        assert analyze.sweep_worlds(2) == [(2,), (1,)]

    def test_sweep_rejects_oversized_world(self):
        with pytest.raises(ValueError, match="devices"):
            analyze.run_sweep((64,))

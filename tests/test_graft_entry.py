"""Driver entry-point contract tests: entry() compiles single-chip,
dryrun_multichip() compiles+executes the full distributed step on the
virtual 8-device CPU mesh, bench.py emits the one-line JSON."""

import pytest
import json
import os
import subprocess
import sys

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out.shape == (4, 128, 256)  # (batch, seq, vocab) logits


@pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
def test_dryrun_multichip_8():
    # 8 devices: the 3D dp x sp x ep mesh (MoE transformer; DP + ring
    # attention + expert dispatch in one program).
    graft.dryrun_multichip(8)


@pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
def test_dryrun_multichip_4():
    # Non-multiple-of-8: the 2D dp x sp dense-FFN fallback.
    graft.dryrun_multichip(4)


@pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget); runs full bench.py
def test_bench_json_line():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, env=env, cwd=repo, timeout=300,
    )
    assert res.returncode == 0, res.stderr
    line = res.stdout.strip().splitlines()[-1]
    data = json.loads(line)
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in data
    assert data["value"] > 0

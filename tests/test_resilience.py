"""Fault-tolerant collectives (mpi4torch_tpu.resilience, ISSUE 7).

Pins the tentpole contracts: deterministic fault injection at the Mode B
rendezvous/p2p chokepoints (composing with fused buckets, compressed
wires, and the overlap pipeline without per-subsystem hooks), failure
ATTRIBUTION (DeadlockError arrived/missing sets, RankFailedError naming
the dead rank, IntegrityError naming the lying rank), transient-fault
retry/backoff recovery, the zero-overhead-off integrity guards on both
backends (HLO-censused), preemption-safe checkpoint recovery, and the
registry-sync guard that makes a fault kind without matrix coverage a
CI failure.  The full fault matrix across the (3,)/(8,)/torus worlds
rides the `slow` lane (`make faults-smoke` runs it standalone); tier-1
keeps a fast representative subset.
"""

import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import resilience as rz
from mpi4torch_tpu.resilience import guards as rguards
from mpi4torch_tpu.resilience import matrix as rmatrix
# The checker body lives in the shared registry-guard home since the
# analyze subsystem landed; resilience.__main__._check_registry_sync
# delegates there, so the smoke lane and this file still share ONE
# implementation.
from mpi4torch_tpu.analyze.registry import \
    resilience_problems as _check_registry_sync

comm = mpi.COMM_WORLD


@pytest.fixture(autouse=True)
def _restore_resilience_config():
    yield
    mpi.config.set_comm_retries(0)
    mpi.config.set_comm_backoff(0.05)
    mpi.config.set_comm_finite_guard("off")
    mpi.config.set_comm_wire_checksum(False)
    mpi.config.set_fault_plan(None)
    rguards.clear_violations()


def _allreduce(rank):
    return comm.Allreduce(jnp.arange(16.0) * (rank + 1), mpi.MPI_SUM)


# =========================================================================
# Registry-sync guard (the PR 4/6 pattern)
# =========================================================================

class TestRegistrySync:
    def test_registry_and_coverage_in_sync(self):
        # Every registered fault kind has a matrix row covering every
        # subsystem its sites reach (and is non-inert somewhere);
        # every covered kind is registered.  ONE checker shared with
        # the `make faults-smoke` lane.
        assert _check_registry_sync() == []

    def test_unregistered_coverage_or_uncovered_kind_fails(self):
        ghost = rz.FaultKind("ghost_fault", frozenset({"exchange"}),
                             transient=False, doc="test-only")
        rz.FAULT_KINDS[ghost.name] = ghost
        try:
            problems = _check_registry_sync()
            assert problems and "ghost_fault" in " ".join(problems)
        finally:
            del rz.FAULT_KINDS[ghost.name]

    def test_expected_error_table_typed(self):
        for kind, err in rmatrix.EXPECTED_ERROR.items():
            assert issubclass(err, mpi.CommError), (kind, err)


# =========================================================================
# Failure attribution
# =========================================================================

class TestAttribution:
    def test_deadlock_carries_arrived_and_missing(self):
        def late(rank):
            if rank == 2:
                time.sleep(0.9)
            return _allreduce(rank)

        with pytest.raises(mpi.DeadlockError) as ei:
            mpi.run_ranks(late, 3, timeout=0.25)
        assert ei.value.arrived == frozenset({0, 1})
        assert ei.value.missing == frozenset({2})

    def test_rank_death_typed_and_attributed(self):
        with rz.fault_scope([rz.FaultSpec("rank_death", rank=1,
                                          op="Allreduce")]):
            with pytest.raises(mpi.RankFailedError) as ei:
                mpi.run_ranks(_allreduce, 3, timeout=5.0)
        assert ei.value.ranks == frozenset({1})

    def test_p2p_recv_names_dead_peer(self):
        # A receiver blocked on a dead rank's message gets the typed,
        # attributed error, not a generic timeout.
        def fn(rank):
            if rank == 0:
                return comm.Recv(jnp.zeros(4), 1, 7)
            return comm.Send(jnp.ones(4), 0, 7)   # rank 1 dies here

        with rz.fault_scope([rz.FaultSpec("rank_death", rank=1,
                                          op="p2p")]):
            with pytest.raises(mpi.RankFailedError) as ei:
                mpi.run_ranks(fn, 2, timeout=5.0)
        assert 1 in ei.value.ranks

    def test_health_check_ok(self):
        reports = mpi.run_ranks(lambda r: comm.check_health(timeout=5.0), 3)
        for rep in reports:
            assert rep.ok and rep.arrived == frozenset({0, 1, 2})
            assert rep.missing == frozenset()

    def test_health_check_names_missing_rank(self):
        def fn(rank):
            if rank == 2:
                time.sleep(0.6)     # never probes within the bound
                return None
            return comm.check_health(timeout=0.2)

        reports = mpi.run_ranks(fn, 3)
        for rep in reports[:2]:
            assert not rep.ok
            assert rep.missing == frozenset({2})
            assert rep.arrived == frozenset({0, 1})

    def test_health_probe_recovers_after_failed_round(self):
        # A failed probe must NOT latch: once the slow rank is back,
        # the next collective probe reports healthy again (the
        # dedicated health barrier resets after a broken round drains).
        def fn(rank):
            if rank == 2:
                time.sleep(0.5)      # misses probe round 1 entirely
                return comm.check_health(timeout=2.0)
            first = comm.check_health(timeout=0.2)
            assert not first.ok and first.missing == frozenset({2})
            return comm.check_health(timeout=2.0)

        reports = mpi.run_ranks(fn, 3)
        for rep in reports:
            assert rep.ok, rep

    def test_health_probe_attributes_despite_world_failure(self):
        # A rank crashing while its peers are blocked in check_health:
        # the abort must still attribute — the waiting probers ARRIVED,
        # only the crashed rank is missing.
        reports = {}

        def fn(rank):
            if rank == 2:
                time.sleep(0.4)
                raise RuntimeError("boom")
            reports[rank] = comm.check_health(timeout=5.0)

        with pytest.raises(RuntimeError, match="boom"):
            mpi.run_ranks(fn, 3, timeout=5.0)
        for rank in (0, 1):
            rep = reports[rank]
            assert not rep.ok
            assert rep.arrived == frozenset({0, 1})
            assert rep.missing == frozenset({2})

    def test_health_probe_counts_hung_rank_as_missing_alongside_dead(self):
        # One rank dead AND one rank merely hung: the probe must not
        # fabricate the hung rank as arrived — `arrived` only contains
        # ranks that answered THIS probe.
        from mpi4torch_tpu.runtime import current_rank_context

        reports = {}

        def fn(rank):
            ctx = current_rank_context()
            if rank == 1:
                err = mpi.RankFailedError("rank 1 died", ranks=(1,))
                ctx.world.mark_dead(1, err)
                raise err
            if rank == 2:
                time.sleep(0.7)      # wedged: never probes
                return None
            time.sleep(0.1)          # let the death land first
            reports[rank] = comm.check_health(timeout=0.3)

        with pytest.raises(mpi.RankFailedError):
            mpi.run_ranks(fn, 3)
        rep = reports[0]
        assert not rep.ok
        assert rep.arrived == frozenset({0})
        assert rep.missing == frozenset({1, 2})

    def test_health_check_single_rank_world(self):
        rep = comm.check_health(timeout=1.0)
        assert rep.ok and rep.size == 1

    def test_check_health_raises_inside_spmd(self):
        def body(x):
            comm.check_health()
            return x

        with pytest.raises(mpi.CommError, match="host-level"):
            mpi.run_spmd(body, nranks=2)(jnp.ones(4))


# =========================================================================
# Retry / backoff recovery
# =========================================================================

class TestRetryRecovery:
    def test_slow_rank_recovers_within_retries(self):
        baseline = mpi.run_ranks(_allreduce, 3)
        mpi.config.set_comm_retries(5)
        mpi.config.set_comm_backoff(0.15)
        with rz.fault_scope([rz.FaultSpec("delay", rank=1, op="Allreduce",
                                          seconds=0.5)]) as plan:
            got = mpi.run_ranks(_allreduce, 3, timeout=0.25)
        assert plan.fired_kinds() == frozenset({"delay"})
        for b, g in zip(baseline, got):
            np.testing.assert_array_equal(np.asarray(b), np.asarray(g))

    def test_dropped_message_redelivered_on_retry(self):
        def fn(rank):
            if rank == 0:
                return comm.Recv(jnp.zeros(4), 1, 3)
            return comm.Send(jnp.ones(4) * 2, 0, 3)

        mpi.config.set_comm_retries(3)
        mpi.config.set_comm_backoff(0.1)
        with rz.fault_scope([rz.FaultSpec("drop_p2p", rank=1,
                                          op="p2p")]) as plan:
            out = mpi.run_ranks(fn, 2, timeout=0.25)
        assert plan.fired_kinds() == frozenset({"drop_p2p"})
        np.testing.assert_array_equal(np.asarray(out[0]), 2 * np.ones(4))

    def test_dropped_message_without_retries_deadlocks(self):
        def fn(rank):
            if rank == 0:
                return comm.Recv(jnp.zeros(4), 1, 3)
            return comm.Send(jnp.ones(4), 0, 3)

        with rz.fault_scope([rz.FaultSpec("drop_p2p", rank=1, op="p2p")]):
            with pytest.raises(mpi.DeadlockError, match="fault-injected"):
                mpi.run_ranks(fn, 2, timeout=0.25)

    def test_retry_knob_validation(self):
        with pytest.raises(ValueError):
            mpi.config.set_comm_retries(-1)
        with pytest.raises(ValueError):
            mpi.config.set_comm_backoff(-0.5)
        with pytest.raises(ValueError):
            mpi.config.set_comm_finite_guard("loud")


# =========================================================================
# Integrity guards
# =========================================================================

class TestFiniteGuard:
    def test_raise_names_offending_rank(self):
        mpi.config.set_comm_finite_guard("raise")
        with rz.fault_scope([rz.FaultSpec("corrupt_nan", rank=2,
                                          op="Allreduce")]):
            with pytest.raises(mpi.IntegrityError) as ei:
                mpi.run_ranks(_allreduce, 3, timeout=5.0)
        assert ei.value.ranks == frozenset({2})

    def test_warn_mode_warns_and_completes(self):
        # Size-1 world on the main thread: deterministic warning capture.
        mpi.config.set_comm_finite_guard("warn")
        with pytest.warns(rz.IntegrityWarning):
            out = comm.Allreduce(jnp.asarray([np.nan, 1.0]), mpi.MPI_SUM)
        assert np.isnan(np.asarray(out)[0])

    def test_off_mode_is_silent(self):
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            out = comm.Allreduce(jnp.asarray([np.nan, 1.0]), mpi.MPI_SUM)
        assert np.isnan(np.asarray(out)[0])
        assert rguards.last_violation() is None

    def test_guard_rides_the_trace_fingerprint(self):
        fp0 = mpi.config.thresholds_fingerprint()
        mpi.config.set_comm_finite_guard("warn")
        fp1 = mpi.config.thresholds_fingerprint()
        mpi.config.set_comm_finite_guard("off")
        assert fp0 != fp1

    def test_huge_finite_float64_is_not_a_false_positive(self):
        # numpy float64 payloads are checked WITHOUT jnp
        # canonicalization: with x64 disabled, jnp.asarray would
        # downcast 1e300 to f32 inf and accuse an innocent rank.
        assert rguards._all_finite({"x": np.asarray([1e300, -1e300])})
        assert not rguards._all_finite({"x": np.asarray([1e300, np.inf])})
        assert not rguards._all_finite(np.asarray([np.nan]))

    def test_bf16_payload_checked(self):
        mpi.config.set_comm_finite_guard("raise")
        with pytest.raises(mpi.IntegrityError):
            comm.Allreduce(jnp.asarray([np.nan], jnp.bfloat16), mpi.MPI_SUM)


class TestWireChecksum:
    def test_bitflip_on_q8_wire_detected_and_attributed(self):
        def ag(rank):
            x = jnp.linspace(-2.0, 2.0, 48, dtype=jnp.float32) * (rank + 1)
            return comm.Allgather(x, 0, compression="q8")

        mpi.config.set_comm_wire_checksum(True)
        with rz.fault_scope([rz.FaultSpec("bitflip", rank=1,
                                          op="Allgather.c")]):
            with pytest.raises(mpi.IntegrityError) as ei:
                mpi.run_ranks(ag, 3, timeout=5.0)
        assert ei.value.ranks == frozenset({1})

    def test_checksum_off_bitflip_is_silent_corruption(self):
        # The negative control: without the checksum leg the flipped
        # block folds in silently — the guard exists for a reason.
        def ag(rank):
            x = jnp.linspace(-2.0, 2.0, 48, dtype=jnp.float32) * (rank + 1)
            return comm.Allgather(x, 0, compression="q8")

        baseline = mpi.run_ranks(ag, 2)
        with rz.fault_scope([rz.FaultSpec("bitflip", rank=1,
                                          op="Allgather.c")]) as plan:
            got = mpi.run_ranks(ag, 2, timeout=5.0)
        assert plan.fired_kinds() == frozenset({"bitflip"})
        assert not np.array_equal(np.asarray(got[0]), np.asarray(baseline[0]))

    def test_checksum_on_clean_wire_is_bitwise_inert(self):
        def ag(rank):
            x = jnp.linspace(-2.0, 2.0, 48, dtype=jnp.float32) * (rank + 1)
            return comm.Allgather(x, 0, compression="q8")

        baseline = mpi.run_ranks(ag, 2)
        mpi.config.set_comm_wire_checksum(True)
        got = mpi.run_ranks(ag, 2)
        for b, g in zip(baseline, got):
            np.testing.assert_array_equal(np.asarray(b), np.asarray(g))

    def test_verify_wire_covers_meta_too(self):
        # The CRC must protect codec meta (shape/dtype/scales steer the
        # decode) alongside the payload blocks.
        payload = {"q": jnp.zeros((4,), jnp.int8)}
        meta = ("q8", (4,), "float32")
        crc = rguards.wire_checksum((meta, payload))
        assert rguards.verify_wire([(meta, payload, crc)], "op") \
            == [(meta, payload)]
        tampered = ("q8", (8,), "float32")
        with pytest.raises(mpi.IntegrityError):
            rguards.verify_wire([(tampered, payload, crc)], "op")

    def test_wire_checksum_roundtrip(self):
        payload = {"q": jnp.asarray([[1, -3], [7, 9]], jnp.int8),
                   "scale": jnp.asarray([0.5, 2.0], jnp.float32)}
        c = rguards.wire_checksum(payload)
        assert c == rguards.wire_checksum(payload)
        flipped = dict(payload, q=payload["q"].at[0, 0].set(2))
        assert c != rguards.wire_checksum(flipped)


# =========================================================================
# Mode A (SPMD) guard: HLO census + violation ledger
# =========================================================================

class TestModeAGuardCensus:
    def _lowered(self, compression=False):
        from jax.sharding import Mesh, PartitionSpec as P

        from mpi4torch_tpu._compat import shard_map

        mesh = Mesh(np.asarray(jax.devices()), ("w",))
        cm = mpi.comm_from_mesh(mesh, "w")
        return jax.jit(shard_map(
            lambda a: cm.Allreduce(a, mpi.MPI_SUM,
                                   compression=compression),
            mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False)).lower(
                jnp.ones((256,), jnp.float32)).as_text()

    def test_off_path_bit_identical_to_guardless_build(self):
        # THE zero-overhead claim: guard off == the hook monkeypatched
        # out entirely, full StableHLO text equality (and no is_finite).
        text_off = self._lowered()
        hook = rguards.spmd_finite_value
        try:
            rguards.spmd_finite_value = lambda v, where: v
            text_bypassed = self._lowered()
        finally:
            rguards.spmd_finite_value = hook
        assert text_off == text_bypassed
        assert text_off.count("stablehlo.is_finite") == 0

    def test_checksum_knob_never_touches_mode_a(self):
        text_off = self._lowered()
        mpi.config.set_comm_wire_checksum(True)
        assert self._lowered() == text_off

    def test_guard_on_census_deltas(self):
        text_off = self._lowered()
        mpi.config.set_comm_finite_guard("warn")
        text_on = self._lowered()
        assert text_on.count("stablehlo.is_finite") \
            - text_off.count("stablehlo.is_finite") == 1
        assert text_on.count("stablehlo.custom_call") \
            - text_off.count("stablehlo.custom_call") == 1

    @pytest.mark.slow
    def test_guard_on_census_compressed(self):
        # The q8 leg of the census (an extra pair of lowerings) rides
        # the slow lane; the exact-path census above is the tier-1 pin.
        text_off = self._lowered("q8")
        mpi.config.set_comm_finite_guard("warn")
        text_on = self._lowered("q8")
        assert text_on.count("stablehlo.is_finite") \
            - text_off.count("stablehlo.is_finite") == 1

    def test_violation_ledger_records_nonfinite(self):
        from jax.sharding import Mesh, PartitionSpec as P

        from mpi4torch_tpu._compat import shard_map

        mesh = Mesh(np.asarray(jax.devices()), ("w",))
        cm = mpi.comm_from_mesh(mesh, "w")
        fn = jax.jit(shard_map(
            lambda a: cm.Allreduce(a, mpi.MPI_SUM),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))
        mpi.config.set_comm_finite_guard("warn")
        rguards.clear_violations()
        x = jnp.asarray([np.nan] + [1.0] * 255, jnp.float32)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            jax.block_until_ready(fn(x))
        rec = rguards.last_violation()
        assert rec is not None and rec["where"] == "Allreduce"

    def test_clean_input_leaves_ledger_empty(self):
        mpi.config.set_comm_finite_guard("warn")
        rguards.clear_violations()
        out = mpi.run_spmd(
            lambda x: comm.Allreduce(x, mpi.MPI_SUM), nranks=2)(
                jnp.ones((8,), jnp.float32))
        jax.block_until_ready(out)
        assert rguards.last_violation() is None


# =========================================================================
# Fault plan grammar
# =========================================================================

class TestFaultPlanGrammar:
    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            rz.FaultSpec("meteor_strike")

    def test_index_and_count_window(self):
        plan = rz.FaultPlan([rz.FaultSpec("corrupt_nan", rank=0,
                                          op="Allreduce", index=1)])
        p0 = plan.on_exchange(None, 0, ("Allreduce", 1), jnp.ones(4))
        assert not np.isnan(np.asarray(p0)).any()      # call 0: skipped
        p1 = plan.on_exchange(None, 0, ("Allreduce", 2), jnp.ones(4))
        assert np.isnan(np.asarray(p1)).any()          # call 1: fires
        p2 = plan.on_exchange(None, 0, ("Allreduce", 3), jnp.ones(4))
        assert not np.isnan(np.asarray(p2)).any()      # count=1: done
        assert len(plan.fired) == 1

    def test_rank_and_op_filters(self):
        plan = rz.FaultPlan([rz.FaultSpec("corrupt_inf", rank=1,
                                          op="Allreduce")])
        p = plan.on_exchange(None, 0, ("Allreduce", 1), jnp.ones(4))
        assert np.isfinite(np.asarray(p)).all()        # wrong rank
        p = plan.on_exchange(None, 1, ("Bcast_", 0), jnp.ones(4))
        assert np.isfinite(np.asarray(p)).all()        # wrong op
        p = plan.on_exchange(None, 1, ("Allreduce.q8hop", 0), jnp.ones(4))
        assert np.isinf(np.asarray(p)).any()           # prefix matches

    def test_bitflip_targets_integer_wire_only(self):
        plan = rz.FaultPlan([rz.FaultSpec("bitflip", rank=0)])
        f = plan.on_exchange(None, 0, ("Allreduce", 0), jnp.ones(4))
        np.testing.assert_array_equal(np.asarray(f), np.ones(4))
        assert plan.fired == []                        # float: inert
        q = {"q": jnp.zeros((4,), jnp.int8), "s": jnp.ones(2)}
        flipped = plan.on_exchange(None, 0, ("Allreduce", 1), q)
        assert np.asarray(flipped["q"]).any()          # a bit moved
        np.testing.assert_array_equal(np.asarray(flipped["s"]), np.ones(2))
        assert len(plan.fired) == 1

    def test_bitflip_wraparound_does_not_cancel_itself(self):
        # nflips > payload bytes: revisited bytes must advance to the
        # next BIT, not re-flip bit 0 back to the original value.
        plan = rz.FaultPlan([rz.FaultSpec("bitflip", rank=0, nflips=8)])
        q = {"q": jnp.zeros((4,), jnp.int8)}     # 4 wire bytes, 8 flips
        flipped = plan.on_exchange(None, 0, ("Allreduce", 0), q)
        assert np.asarray(flipped["q"]).any(), (
            "wrapped flips cancelled the corruption while the ledger "
            "recorded it as fired")

    def test_fault_scope_restores_previous_plan(self):
        assert mpi.config.fault_plan() is None
        with rz.fault_scope([rz.FaultSpec("delay", seconds=0.0)]):
            assert mpi.config.fault_plan() is not None
            with rz.fault_scope([rz.FaultSpec("bitflip")]) as inner:
                assert mpi.config.fault_plan() is inner
            assert mpi.config.fault_plan() is not None
        assert mpi.config.fault_plan() is None

    def test_set_fault_plan_coerces_spec_lists(self):
        mpi.config.set_fault_plan([rz.FaultSpec("delay", seconds=0.0)])
        assert isinstance(mpi.config.fault_plan(), rz.FaultPlan)
        mpi.config.set_fault_plan(None)


# =========================================================================
# run_ranks timeout default (satellite bugfix)
# =========================================================================

class TestWorldTimeoutEnv:
    def test_run_ranks_honors_env_timeout(self, monkeypatch):
        # run_ranks used to hard-code timeout=60.0, silently bypassing
        # MPI4TORCH_TPU_WORLD_TIMEOUT; both paths must honor it now.
        from mpi4torch_tpu.runtime import World, current_rank_context

        monkeypatch.setenv("MPI4TORCH_TPU_WORLD_TIMEOUT", "123.5")
        out = mpi.run_ranks(
            lambda r: current_rank_context().world.timeout, 2)
        assert out == [123.5, 123.5]
        assert World(2).timeout == 123.5

    def test_run_ranks_explicit_timeout_still_wins(self, monkeypatch):
        from mpi4torch_tpu.runtime import current_rank_context

        monkeypatch.setenv("MPI4TORCH_TPU_WORLD_TIMEOUT", "123.5")
        out = mpi.run_ranks(
            lambda r: current_rank_context().world.timeout, 2,
            timeout=7.0)
        assert out == [7.0, 7.0]


# =========================================================================
# Fault matrix: fast representative subset (tier-1) + full sweep (slow)
# =========================================================================

# One representative cell per outcome class on the (3,) world — the
# fast lane's proof the matrix machinery is exercised end-to-end; the
# FULL matrix (every kind × subsystem × world) runs on the slow lane
# and in `make faults-smoke`, keeping tier-1 inside its 870s budget.
_FAST_CELLS = [
    ("rank_death", "fused"),        # raise, typed + attributed
    ("delay", "plain"),             # recover via retry/backoff
    ("drop_p2p", "overlap"),        # recover via redelivery
    ("corrupt_nan", "compressed"),  # raise via finite guard
    ("bitflip", "compressed"),      # raise via wire checksum
    ("bitflip", "fused"),           # inert off the encoded wire
]


class TestFaultMatrixFast:
    @pytest.mark.parametrize("kind,subsystem", _FAST_CELLS)
    def test_cell(self, kind, subsystem):
        rec = rmatrix.run_cell(kind, subsystem, nranks=3)
        assert rec["status"] == "ok", rec


@pytest.mark.slow
class TestFaultMatrixFull:
    @pytest.mark.parametrize("nranks,algorithm", rmatrix.WORLDS)
    def test_world(self, nranks, algorithm):
        failures = []
        for kind, subsystem in rmatrix.coverage_cells():
            if subsystem == "checkpoint":
                continue
            if algorithm is not None and subsystem not in (
                    "plain", "compressed"):
                continue
            rec = rmatrix.run_cell(kind, subsystem, nranks=nranks,
                                   algorithm=algorithm)
            if rec["status"] != "ok":
                failures.append(rec)
        assert not failures, failures

    def test_checkpoint_cell(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        rec = rmatrix.run_checkpoint_cell(str(tmp_path / "run"))
        assert rec["status"] == "ok", rec

"""Per-rank-varying ``numelem`` on the dense collectives, mesh backend —
the SPMD mirror of the eager varying-``numelem`` oracles
(tests/test_collectives.py:319-345; reference
tests/test_collectives.py:121-141) over capacity-padded buffers + static
count tuples (ops/packed.py; VERDICT r4 item 5).  The same program runs
on BOTH backends; the cross-backend tests assert slot-for-slot equality.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm

NR = 8
COUNTS = tuple(r + 1 for r in range(NR))          # per-rank varying
TOTAL = sum(COUNTS)
CAP = max(COUNTS)
OFFS = np.concatenate([[0], np.cumsum(COUNTS)])


def run(fn, **kw):
    return mpi.run_spmd(fn, nranks=NR, **kw)


def rank_padded_rows(x0):
    """(CAP, 2) block whose first rank+1 rows are rank-stamped values —
    same recipe on either backend (comm.rank materializes)."""
    rows = jnp.arange(CAP, dtype=x0.dtype)[:, None] + 10.0 * (1 + comm.rank)
    return rows * jnp.ones((CAP, 2), x0.dtype) * x0


class TestPackedGather:
    def test_gather_packs_valid_prefixes(self):
        def prog(x0):
            return comm.Gather(rank_padded_rows(x0), 0, 0, numelem=COUNTS)

        out = np.asarray(run(prog)(jnp.ones(())))
        assert out.shape == (NR, TOTAL, 2)
        # Root holds the packed concatenation of each rank's valid prefix.
        for r in range(NR):
            seg = out[0, OFFS[r]:OFFS[r + 1]]
            want = (np.arange(COUNTS[r])[:, None] + 10.0 * (1 + r)) * \
                np.ones((COUNTS[r], 2))
            np.testing.assert_array_equal(seg, want)
        assert (out[1:] == 0).all()      # non-root zeroed

    def test_allgather_everywhere_and_grad(self):
        def prog(x0):
            return comm.Allgather(rank_padded_rows(x0), 0, numelem=COUNTS)

        out = np.asarray(run(prog)(jnp.ones(())))
        assert out.shape == (NR, TOTAL, 2)
        for r in range(1, NR):
            np.testing.assert_array_equal(out[r], out[0])

        # Padding must not leak gradient: d(sum)/dx0 counts only valid
        # slots, summed over all ranks' outputs.
        g = jax.grad(lambda x: run(prog)(x).sum())(jnp.ones(()))
        want = NR * sum(
            2 * sum(i + 10.0 * (1 + r) for i in range(COUNTS[r]))
            for r in range(NR))
        assert float(g) == want

    def test_count_exceeding_capacity_raises(self):
        bad = (CAP + 1,) + (1,) * (NR - 1)
        with pytest.raises(ValueError, match="exceeds"):
            run(lambda x: comm.Gather(rank_padded_rows(x), 0, 0,
                                      numelem=bad))(jnp.ones(()))


class TestPackedScatter:
    def test_scatter_pads_and_masks(self):
        def prog(x0):
            packed = jnp.arange(TOTAL, dtype=x0.dtype)[:, None] \
                * jnp.ones((TOTAL, 3), x0.dtype) * x0
            return comm.Scatter(packed, 0, COUNTS, 0)

        out = np.asarray(run(prog)(jnp.ones(())))
        assert out.shape == (NR, CAP, 3)
        for r in range(NR):
            want = np.zeros((CAP, 3))
            want[:COUNTS[r]] = np.arange(OFFS[r], OFFS[r + 1])[:, None]
            np.testing.assert_array_equal(out[r], want)

    def test_sum_mismatch_raises(self):
        # reference check csrc/extension.cpp:835-837
        with pytest.raises(ValueError, match="sum"):
            run(lambda x: comm.Scatter(x, 0, COUNTS, 0))(
                jnp.ones((TOTAL + 1,)))

    def test_scatter_grad_reaches_only_valid_slots(self):
        def prog(x):
            return comm.Scatter(x, 0, COUNTS, 0)

        g = np.asarray(jax.grad(
            lambda x: run(prog)(x).sum())(jnp.ones((TOTAL,))))
        # Every packed element lands on exactly one rank's valid slot, and
        # the adjoint (Gather of the upstream grads, masked to root —
        # reference csrc/extension.cpp:736-767) routes exactly one
        # cotangent back per element: grad == ones, the reference's
        # Scatter test_basic_ad oracle.
        np.testing.assert_array_equal(g, np.ones((TOTAL,)))


class TestPackedAlltoall:
    def test_scatter_gather_equivalence_varying_numelem(self):
        # THE mirror of tests/test_collectives.py:319 on the mesh backend.
        def make(x0):
            base = jnp.arange(3 * 4 * CAP * 4 * TOTAL * 2,
                              dtype=x0.dtype).reshape(3, 4, CAP, 4, TOTAL, 2)
            return base * (1.0 + comm.rank) * x0

        def res1(x0):
            t = make(x0)
            return comm.Scatter(comm.Gather(t, 2, 0, numelem=COUNTS),
                                4, COUNTS, 0)

        def res2(x0):
            return comm.Alltoall(make(x0), 2, 4, COUNTS)

        o1 = np.asarray(run(res1)(jnp.ones(())))
        o2 = np.asarray(run(res2)(jnp.ones(())))
        # Both contracts: gather axis packed to TOTAL, scatter axis padded
        # to CAP and masked.
        assert o2.shape == (NR, 3, 4, TOTAL, 4, CAP, 2)
        np.testing.assert_array_equal(o2, o1)

    def test_alltoall_grad_ones_on_valid(self):
        def prog(x):
            return comm.Alltoall(x, 2, 4, COUNTS)

        x = jnp.ones((2, 3, CAP, 1, TOTAL, 2))
        g = np.asarray(jax.grad(lambda x: run(prog)(x).sum())(x))
        # Valid gather rows (first numelem[rank] of axis 2) contribute one
        # cotangent per replica... summed over the NR traced ranks: each
        # rank's valid region differs, so slot (.., i, .., j, ..) gets a
        # count = #ranks r with i < COUNTS[r] whose scatter slot j is
        # valid for its receiver — receiver j owns packed interval.
        want = np.zeros_like(g)
        for r in range(NR):
            for dest in range(NR):
                if COUNTS[r] == 0:
                    continue
                want[:, :, :COUNTS[r], :, OFFS[dest]:OFFS[dest + 1], :] += 1
        np.testing.assert_array_equal(g, want)

    def test_same_axis_redistribution(self):
        # Mirror of tests/test_collectives.py:331 (reference :127-135):
        # repartition the global arange from COUNTS to NEW.
        NEW = tuple(NR - r for r in range(NR))
        assert sum(NEW) == TOTAL
        new_offs = np.concatenate([[0], np.cumsum(NEW)])
        new_cap = max(NEW)

        def prog(x0):
            vals = (OFFS[:-1][np.newaxis, :].repeat(CAP, 0).T
                    + np.arange(CAP)[np.newaxis, :])
            mine = jnp.take(jnp.asarray(vals, jnp.float64),
                            jnp.asarray(comm.rank + 0), axis=0)[:, None] * x0
            return comm.Alltoall(mine, 0, 0, NEW, current_numelem=COUNTS)

        out = np.asarray(run(prog)(jnp.ones(())))
        assert out.shape == (NR, new_cap, 1)
        for r in range(NR):
            want = np.zeros((new_cap, 1))
            want[:NEW[r], 0] = np.arange(new_offs[r], new_offs[r + 1])
            np.testing.assert_array_equal(out[r], want)

    def test_same_axis_redistribution_grad(self):
        # Cotangents must route back through the repartition to exactly
        # the valid source slots (each global element appears in exactly
        # one new span; padding contributes nothing).
        NEW = tuple(NR - r for r in range(NR))

        def prog(x):
            mine = jnp.take(x, jnp.asarray(comm.rank + 0), axis=0)
            out = comm.Alltoall(mine, 0, 0, NEW, current_numelem=COUNTS)
            w = 1.0 + jnp.asarray(comm.rank + 0, out.dtype)
            return jnp.sum(out * w)

        x = jnp.ones((NR, CAP, 2))
        g = np.asarray(jax.grad(lambda x: run(prog)(x).sum())(x))
        # Rank r's valid slot feeding new-owner j gets weight 1+j; its
        # padding slots get exactly zero.
        new_offs = np.concatenate([[0], np.cumsum(NEW)])
        flat_owner = np.zeros(TOTAL, np.int64)
        for j in range(NR):
            flat_owner[new_offs[j]:new_offs[j + 1]] = j
        for r in range(NR):
            for i in range(CAP):
                if i < COUNTS[r]:
                    owner = flat_owner[OFFS[r] + i]
                    assert (g[r, i] == 1.0 + owner).all(), (r, i)
                else:
                    assert (g[r, i] == 0).all(), (r, i)

    def test_same_axis_requires_current_numelem(self):
        with pytest.raises(ValueError, match="current_numelem"):
            run(lambda x: comm.Alltoall(x, 0, 0, COUNTS))(
                jnp.ones((CAP, 2)))

    def test_partition_total_mismatch_raises(self):
        bad = (TOTAL,) + (0,) * (NR - 1)
        with pytest.raises(ValueError, match="partition different totals"):
            run(lambda x: comm.Alltoall(x, 0, 0, COUNTS,
                                        current_numelem=bad[:-1] + (1,)))(
                jnp.ones((CAP, 2)))


class TestDispatchEdges:
    def test_numpy_integer_numelem_stays_dense(self):
        # np.int64 counts (e.g. from shape/cumsum arithmetic) must route
        # to the dense path exactly like a Python int.
        def prog(x):
            return comm.Scatter(x, 0, np.int64(2), 0)

        out = np.asarray(run(prog)(jnp.arange(2 * NR, dtype=jnp.float64)))
        for r in range(NR):
            np.testing.assert_array_equal(out[r], [2 * r, 2 * r + 1])

        def prog2(x):
            return comm.Alltoall(x, 0, 0, np.int64(1))

        out = np.asarray(run(prog2)(jnp.arange(NR, dtype=jnp.float64)))
        assert out.shape == (NR, NR)

    def test_int_numelem_on_gather_means_uniform_prefix(self):
        # An int numelem must not be silently dropped: it is the uniform
        # per-rank count over the padded axis.
        def prog(x0):
            t = rank_padded_rows(x0)
            return comm.Allgather(t, 0, numelem=2)

        out = np.asarray(run(prog)(jnp.ones(())))
        assert out.shape == (NR, 2 * NR, 2)
        for r in range(NR):
            seg = out[0, 2 * r:2 * r + 2]
            want = (np.arange(2)[:, None] + 10.0 * (1 + r)) * np.ones((2, 2))
            np.testing.assert_array_equal(seg, want)

    def test_current_numelem_with_distinct_axes_raises(self):
        with pytest.raises(ValueError, match="only applies"):
            run(lambda x: comm.Alltoall(x, 0, 1, COUNTS,
                                        current_numelem=COUNTS))(
                jnp.ones((CAP, TOTAL)))


class TestCrossBackend:
    """The same padded program must produce identical results eagerly
    (thread runtime) and traced (mesh SPMD) — the TorchScript-parity
    analogue for the packed forms."""

    def _run_eager(self, prog):
        res = {}

        def body():
            res[comm.rank] = np.asarray(prog(jnp.ones(())))

        mpi.run_ranks(body, NR)
        return np.stack([res[r] for r in range(NR)])

    def test_gather_scatter_alltoall_match(self):
        def via_gather_scatter(x0):
            t = rank_padded_rows(x0)[:, None, :]        # (CAP, 1, 2)
            packed = comm.Gather(t, 0, 0, numelem=COUNTS)
            return comm.Scatter(packed, 0, COUNTS, 0)

        def via_alltoall(x0):
            t = rank_padded_rows(x0)[None, :, :]        # (1, CAP, 2)
            packed = comm.Allgather(t, 1, numelem=COUNTS)   # (1, TOTAL, 2)
            flat = jnp.moveaxis(packed, 1, 0)[:, 0, :]      # (TOTAL, 2)
            return comm.Scatter(flat, 0, COUNTS, 0)

        for prog in (via_gather_scatter, via_alltoall):
            spmd = np.asarray(run(prog)(jnp.ones(())))
            eager = self._run_eager(prog)
            np.testing.assert_array_equal(spmd, eager, err_msg=prog.__name__)

    def test_same_axis_redistribution_matches(self):
        NEW = tuple(NR - r for r in range(NR))

        def prog(x0):
            rows = jnp.arange(CAP, dtype=x0.dtype) * (1.0 + comm.rank)
            return comm.Alltoall(rows[:, None] * x0, 0, 0, NEW,
                                 current_numelem=COUNTS)

        spmd = np.asarray(run(prog)(jnp.ones(())))
        eager = self._run_eager(prog)
        np.testing.assert_array_equal(spmd, eager)

"""Communicator serialization — reference parity with fixed semantics.

The reference pickles only ``MPI_COMM_WORLD`` and its deserializer throws
on the very string it wrote (inverted condition, csrc/extension.cpp:
1290-1296 — SURVEY.md §2.1 documents it as a latent bug).  Here the round
trip must actually work: COMM_WORLD restores to the singleton and is
immediately usable; mesh-derived communicators refuse to pickle with a
clear message."""

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm


class TestCommWorldPickle:
    def test_round_trip_restores_singleton(self):
        blob = pickle.dumps(comm)
        restored = pickle.loads(blob)
        assert restored is mpi.COMM_WORLD

    def test_restored_comm_is_usable_eager(self):
        restored = pickle.loads(pickle.dumps(comm))

        def body():
            x = jnp.full(3, float(restored.rank) + 1.0)
            return np.asarray(restored.Allreduce(x, mpi.MPI_SUM))

        outs = mpi.run_ranks(body, 4)
        for o in outs:
            np.testing.assert_array_equal(o, np.full(3, 10.0))

    def test_restored_comm_is_usable_spmd(self):
        restored = pickle.loads(pickle.dumps(comm))

        def body():
            return restored.Allreduce(jnp.ones(2), mpi.MPI_SUM)

        out = np.asarray(mpi.run_spmd(body, nranks=4)())
        np.testing.assert_array_equal(out, np.full((4, 2), 4.0))

    def test_pickle_inside_rank_context(self):
        # Pickled on a rank thread, the blob still denotes the world —
        # not a rank-bound view (rank binding is resolved at use time).
        def body():
            return pickle.dumps(comm)

        blobs = mpi.run_ranks(body, 2)
        assert pickle.loads(blobs[0]) is mpi.COMM_WORLD
        assert blobs[0] == blobs[1]


class TestMeshCommRefusesPickle:
    def test_mesh_comm_raises_with_guidance(self):
        from jax.sharding import Mesh

        devs = np.asarray(jax.devices()[:2])
        mesh = Mesh(devs, ("x",))
        c = mpi.comm_from_mesh(mesh, "x")
        with pytest.raises(pickle.PicklingError,
                           match="only COMM_WORLD"):
            pickle.dumps(c)


class TestCopySemantics:
    def test_copy_returns_same_handle_for_every_kind(self):
        # Communicators are handles, not data: copying a pytree/config
        # holding one must succeed for ALL kinds (including mesh-derived,
        # which refuses to pickle) and hand back the same handle.
        import copy

        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:2]), ("x",))
        for c in (comm, mpi.comm_from_mesh(mesh, "x")):
            assert copy.copy(c) is c
            assert copy.deepcopy(c) is c
            state = {"comm": c, "params": [jnp.ones(2)]}
            state2 = copy.deepcopy(state)
            assert state2["comm"] is c
            assert state2["params"] is not state["params"]

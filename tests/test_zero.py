"""ZeRO-1 sharded optimizer (parallel/zero.py): per-rank optimizer state
is 1/size of the replicated state, gradients arrive by reduce-scatter,
updated shards return by allgather — and for element-wise optimizers the
trajectory must EXACTLY match plain replicated DP."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm
from mpi4torch_tpu.parallel import all_average_tree, zero_init, zero_step

N, D, STEPS = 32, 5, 12
NR = 4


def _data():
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.standard_normal((N, D)))
    y = x @ jnp.asarray(rng.standard_normal((D,)))
    # A pytree with an awkward leaf shape (3, D) so padding (3*5=15,
    # not divisible by 4) is exercised.
    params0 = {"w": jnp.zeros((D,)), "m": jnp.zeros((3, D))}
    return x, y, params0


def _local_loss(p, xl, yl):
    pred = xl @ p["w"] + jnp.sum(p["m"]) * 0.01
    return jnp.sum((yl - pred) ** 2)


def _replicated_oracle(opt, x, y, params):
    """Single-process trajectory of the plain-DP lock-step: the DP loss
    is the rank-MEAN of local losses (Allreduce/size), so the oracle
    gradient is the full-batch loss divided by the rank count — the same
    mean the reduce-scatter/size inside zero_step produces."""
    state = opt.init(params)
    for _ in range(STEPS):
        g = jax.grad(lambda p: _local_loss(p, x, y) / NR)(params)
        updates, state = opt.update(g, state, params)
        params = jax.tree.map(jnp.add, params, updates)
    return params


@pytest.mark.parametrize("make_opt", [
    lambda: optax.adam(1e-1),
    lambda: optax.sgd(1e-2, momentum=0.9),
], ids=["adam", "sgd-momentum"])
def test_zero_matches_replicated_oracle_eager(make_opt):
    x, y, params0 = _data()
    ref = _replicated_oracle(make_opt(), x, y, params0)
    shard = N // NR

    def body():
        xl = x[comm.rank * shard:(comm.rank + 1) * shard]
        yl = y[comm.rank * shard:(comm.rank + 1) * shard]
        opt = make_opt()
        params = params0
        state = zero_init(comm, opt, params)
        for _ in range(STEPS):
            # UN-reduced local grads: the reduce-scatter inside
            # zero_step performs the global reduction.
            g = jax.grad(lambda p: _local_loss(p, xl, yl))(params)
            params, state = zero_step(comm, opt, params, g, state)
        return params

    outs = mpi.run_ranks(body, NR)
    for got in outs:
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-9, atol=1e-12),
            got, ref)


def test_zero_matches_replicated_oracle_spmd():
    # The whole training loop is ONE compiled SPMD program: per-rank
    # shard states live inside the region (sliced at the symbolic rank),
    # only the final replicated params come out (rank-stacked by
    # run_spmd; every row must equal the oracle).
    x, y, params0 = _data()
    opt = optax.adam(1e-1)
    ref = _replicated_oracle(opt, x, y, params0)
    shard = N // NR

    def body():
        r = jnp.asarray(comm.rank)
        xl = jax.lax.dynamic_slice_in_dim(x, r * shard, shard, 0)
        yl = jax.lax.dynamic_slice_in_dim(y, r * shard, shard, 0)
        params = params0
        state = zero_init(comm, opt, params)
        for _ in range(STEPS):
            g = jax.grad(lambda p: _local_loss(p, xl, yl))(params)
            params, state = zero_step(comm, opt, params, g, state)
        return params

    stacked = mpi.run_spmd(body, nranks=NR)()
    for rank in range(NR):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a)[rank], np.asarray(b), rtol=1e-9,
                atol=1e-12),
            stacked, ref)


def test_state_is_sharded():
    def body():
        opt = optax.adam(1e-1)
        p = {"w": jnp.zeros((NR * 6,))}
        state = zero_init(comm, opt, p)
        # Adam's mu/nu leaves are shard-sized: 1/size of the params.
        mu = state[0].mu["w"]
        assert mu.shape == (6,)
        return True

    assert all(mpi.run_ranks(body, NR))


def test_global_norm_clipping_matches_replicated():
    """Global-norm clipping through the grad_transform hook: the sharded
    norm helper must reproduce optax.chain(clip_by_global_norm, adam)
    on the replicated oracle exactly — shard-LOCAL clipping would not
    (each rank would scale by a different factor)."""
    x, y, params0 = _data()
    max_norm = 0.5  # far below the actual grad norm: clipping engages
    chain = optax.chain(optax.clip_by_global_norm(max_norm),
                        optax.adam(1e-1))
    ref = _replicated_oracle(chain, x, y, params0)
    shard = N // NR

    from mpi4torch_tpu.parallel import shard_global_norm

    def body():
        xl = x[comm.rank * shard:(comm.rank + 1) * shard]
        yl = y[comm.rank * shard:(comm.rank + 1) * shard]
        opt = optax.adam(1e-1)
        params = params0
        state = zero_init(comm, opt, params)

        def clip(gs):
            # The documented zero-safe form (NaN-free at norm == 0).
            norm = shard_global_norm(comm, gs)
            scale = max_norm / jnp.maximum(norm, max_norm)
            return jax.tree.map(lambda g: g * scale, gs)

        for _ in range(STEPS):
            g = jax.grad(lambda p: _local_loss(p, xl, yl))(params)
            params, state = zero_step(comm, opt, params, g, state,
                                      grad_transform=clip)
        return params

    outs = mpi.run_ranks(body, NR)
    for got in outs:
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-9, atol=1e-12),
            got, ref)

    # Same thing on the SPMD mesh backend (symbolic rank, psum
    # lowering, 0-d scalar Allreduce inside the norm).
    def spmd_body():
        r = jnp.asarray(comm.rank)
        xl = jax.lax.dynamic_slice_in_dim(x, r * shard, shard, 0)
        yl = jax.lax.dynamic_slice_in_dim(y, r * shard, shard, 0)
        opt = optax.adam(1e-1)
        params, state = params0, zero_init(comm, opt, params0)

        def clip(gs):
            norm = shard_global_norm(comm, gs)
            scale = max_norm / jnp.maximum(norm, max_norm)
            return jax.tree.map(lambda g: g * scale, gs)

        for _ in range(STEPS):
            g = jax.grad(lambda p: _local_loss(p, xl, yl))(params)
            params, state = zero_step(comm, opt, params, g, state,
                                      grad_transform=clip)
        return params

    stacked = mpi.run_spmd(spmd_body, nranks=NR)()
    for rank in range(NR):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a)[rank], np.asarray(b), rtol=1e-9,
                atol=1e-12),
            stacked, ref)


def test_shard_global_norm_equals_full_norm():
    rng = np.random.default_rng(3)
    tree = {"a": jnp.asarray(rng.standard_normal((13,))),
            "b": jnp.asarray(rng.standard_normal((3, 5)))}
    want = float(jnp.sqrt(sum(jnp.sum(jnp.square(v))
                              for v in tree.values())))

    from mpi4torch_tpu.parallel import shard_global_norm
    from mpi4torch_tpu.parallel.zero import _my_shard, _pad_flat

    def body():
        shards = jax.tree.map(
            lambda p: _my_shard(comm, _pad_flat(p, comm.size)), tree)
        return float(shard_global_norm(comm, shards))

    for got in mpi.run_ranks(body, NR):
        np.testing.assert_allclose(got, want, rtol=1e-12)


def _checkpoint_resume_harness(tmp_path, init_fn, step_fn, final_fn):
    """Shared crash/resume oracle for the ZeRO stages: run STEPS
    uninterrupted, run STEPS/2 + save per rank + restore + STEPS/2, and
    require identical final replicated parameters on every rank.

    ``init_fn() -> carry``; ``step_fn(carry, xl, yl) -> carry``;
    ``final_fn(carry) -> replicated params tree`` — all called inside a
    rank-thread.  Per-rank carries are DIFFERENT trees of the same
    shape: each rank persists its own directory.  IO runs serialized on
    the main thread — orbax checkpointers are not safe to call from the
    rank-threads concurrently (under the multi-process runtime each
    process has its own interpreter, so this is a thread-harness
    artifact, not a deployment constraint).  The just-saved carries
    serve as their own restore templates (restore only consumes
    shape/dtype structure)."""
    x, y, _ = _data()
    shard = N // NR
    half = STEPS // 2

    from mpi4torch_tpu.utils import save_checkpoint, restore_checkpoint

    def local_xy():
        xl = x[comm.rank * shard:(comm.rank + 1) * shard]
        yl = y[comm.rank * shard:(comm.rank + 1) * shard]
        return xl, yl

    def run_steps(carry, n):
        xl, yl = local_xy()
        for _ in range(n):
            carry = step_fn(carry, xl, yl)
        return carry

    ref = mpi.run_ranks(lambda: final_fn(run_steps(init_fn(), STEPS)), NR)

    halves = mpi.run_ranks(lambda: run_steps(init_fn(), half), NR)
    for r, carry in enumerate(halves):
        save_checkpoint(str(tmp_path / f"rank{r}"), carry)
    restored = [
        restore_checkpoint(str(tmp_path / f"rank{r}"), halves[r])
        for r in range(NR)
    ]

    outs = mpi.run_ranks(
        lambda: final_fn(run_steps(restored[comm.rank], STEPS - half)),
        NR)
    for got, want in zip(outs, ref):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-12),
            got, want)


@pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
def test_zero_state_checkpoint_resume(tmp_path):
    """Crash/resume with SHARDED optimizer state: each rank saves its
    own shard, restores it, and the resumed trajectory is identical to
    the uninterrupted run on every rank."""
    _, _, params0 = _data()
    opt = optax.adam(1e-1)

    def init_fn():
        return {"params": params0, "opt": zero_init(comm, opt, params0)}

    def step_fn(carry, xl, yl):
        g = jax.grad(lambda p: _local_loss(p, xl, yl))(carry["params"])
        params, state = zero_step(comm, opt, carry["params"], g,
                                  carry["opt"])
        return {"params": params, "opt": state}

    _checkpoint_resume_harness(tmp_path, init_fn, step_fn,
                               lambda c: c["params"])


class TestZero3:
    """ZeRO-3 (parallel/zero.py zero3_*): parameters persist as 1/size
    flat shards between steps, gathered on use; the gradient arrives
    sharded through the Allgather ADJOINT (the reduce-scatter), and the
    trajectory must exactly match plain replicated DP."""

    @pytest.mark.parametrize("make_opt", [
        lambda: optax.adam(1e-1),
        lambda: optax.sgd(1e-2, momentum=0.9),
    ], ids=["adam", "sgd-momentum"])
    def test_matches_replicated_oracle_eager(self, make_opt):
        from mpi4torch_tpu.parallel import zero3_init, zero3_params, \
            zero3_step
        x, y, params0 = _data()
        ref = _replicated_oracle(make_opt(), x, y, params0)
        shard = N // NR

        def body():
            xl = x[comm.rank * shard:(comm.rank + 1) * shard]
            yl = y[comm.rank * shard:(comm.rank + 1) * shard]
            opt = make_opt()
            p_shards, state = zero3_init(comm, opt, params0)
            for _ in range(STEPS):
                _, p_shards, state = zero3_step(
                    comm, opt, p_shards, params0,
                    lambda p: _local_loss(p, xl, yl), state)
            return zero3_params(comm, p_shards, params0)

        outs = mpi.run_ranks(body, NR)
        for got in outs:
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-9, atol=1e-12),
                got, ref)

    def test_matches_replicated_oracle_spmd(self):
        from mpi4torch_tpu.parallel import zero3_init, zero3_params, \
            zero3_step
        x, y, params0 = _data()
        opt = optax.adam(1e-1)
        ref = _replicated_oracle(opt, x, y, params0)
        shard = N // NR

        def body():
            r = jnp.asarray(comm.rank)
            xl = jax.lax.dynamic_slice_in_dim(x, r * shard, shard, 0)
            yl = jax.lax.dynamic_slice_in_dim(y, r * shard, shard, 0)
            p_shards, state = zero3_init(comm, opt, params0)
            for _ in range(STEPS):
                _, p_shards, state = zero3_step(
                    comm, opt, p_shards, params0,
                    lambda p: _local_loss(p, xl, yl), state)
            return zero3_params(comm, p_shards, params0)

        stacked = mpi.run_spmd(body, nranks=NR)()
        for rank in range(NR):
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a)[rank], np.asarray(b), rtol=1e-9,
                    atol=1e-12),
                stacked, ref)

    def test_everything_is_sharded(self):
        from mpi4torch_tpu.parallel import zero3_init

        def body():
            opt = optax.adam(1e-1)
            p = {"w": jnp.zeros((NR * 6,)), "m": jnp.zeros((3, 5))}
            p_shards, state = zero3_init(comm, opt, p)
            # Parameters AND Adam moments are shard-sized (padded:
            # 15 -> ceil(15/4) = 4 per rank).
            assert p_shards["w"].shape == (6,)
            assert p_shards["m"].shape == (4,)
            assert state[0].mu["w"].shape == (6,)
            assert state[0].nu["m"].shape == (4,)
            return True

        assert all(mpi.run_ranks(body, NR))

    def test_wire_pattern_hlo(self):
        # ZeRO-3's canonical overhead: one step lowers to allgathers
        # (params, forward) + reduce-scatters (gradient adjoint) — and
        # crucially NO all_reduce (a full gradient allreduce would mean
        # the sharding saved nothing on the wire).
        from mpi4torch_tpu._compat import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from mpi4torch_tpu.parallel import zero3_init, zero3_step

        mesh = Mesh(np.asarray(jax.devices()[:NR]), ("z",))
        c = mpi.comm_from_mesh(mesh, "z")
        x, y, params0 = _data()
        opt = optax.sgd(1e-2)

        def body():
            p_shards, state = zero3_init(c, opt, params0)
            _, p_shards, state = zero3_step(
                c, opt, p_shards, params0,
                lambda p: _local_loss(p, x, y), state)
            return jax.tree.leaves(p_shards)[0]

        txt = jax.jit(shard_map(body, mesh=mesh, in_specs=(),
                                out_specs=P(), check_vma=False)).lower()
        txt = txt.as_text()
        assert txt.count("stablehlo.all_gather") >= 1
        assert txt.count("stablehlo.reduce_scatter") >= 1
        assert txt.count("stablehlo.all_reduce") == 0, txt

    @pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
    def test_zero3_state_checkpoint_resume(self, tmp_path):
        """Crash/resume with SHARDED PARAMETERS: each rank persists its
        1/size parameter shard + optimizer shard (the whole point of
        stage 3 — no rank ever needs to materialize the full tree to
        checkpoint), and the resumed trajectory is identical to the
        uninterrupted run."""
        from mpi4torch_tpu.parallel import (zero3_init, zero3_params,
                                            zero3_step)

        _, _, params0 = _data()
        opt = optax.adam(1e-1)

        def init_fn():
            ps, st = zero3_init(comm, opt, params0)
            return {"p_shards": ps, "opt": st}

        def step_fn(carry, xl, yl):
            _, ps, st = zero3_step(
                comm, opt, carry["p_shards"], params0,
                lambda p: _local_loss(p, xl, yl), carry["opt"])
            return {"p_shards": ps, "opt": st}

        _checkpoint_resume_harness(
            tmp_path, init_fn, step_fn,
            lambda c: zero3_params(comm, c["p_shards"], params0))

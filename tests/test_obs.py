"""mpi4torch_tpu.obs — unified runtime observability (ISSUE 12).

Covers the five layers: chokepoint comm tracing (typed CommEvents at
World.exchange + the p2p mailboxes, zero per-subsystem hooks), the
process-wide metrics registry (retry events / integrity violations /
serve counters under one namespace, Prometheus export, the shared
percentile rule), the failure flight recorder (rank-attributed
postmortems — tested through the fault matrix's rank_death cell,
alongside the existing attribution cells), Chrome-trace export, and
the static-vs-runtime reconciliation (measured Mode B wire == analyze
predictions EXACTLY).  The off-path contract — obs disabled lowers
bit-identical to an obs-less build — is censused here and in
bench._bench_obs_overhead; `make obs-smoke` runs the full lane.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm, analyze, config, obs
from mpi4torch_tpu._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _lower(fn, *args):
    mesh = Mesh(np.asarray(jax.devices()), ("w",))
    cm = mpi.comm_from_mesh(mesh, "w")
    return jax.jit(shard_map(lambda *a: fn(cm, *a), mesh=mesh,
                             in_specs=P(), out_specs=P(),
                             check_vma=False)).lower(*args)


class TestCommTracing:
    def test_off_by_default(self):
        assert config.comm_tracer() is None
        # The untraced path still works (and records nothing anywhere).
        out = mpi.run_ranks(
            lambda r: comm.Allreduce(jnp.ones(4, jnp.float32) * (r + 1),
                                     mpi.MPI_SUM), 2)
        np.testing.assert_array_equal(np.asarray(out[0]),
                                      np.full(4, 3.0))

    def test_exchange_events_censused(self):
        with obs.trace() as t:
            mpi.run_ranks(
                lambda r: comm.Allreduce(
                    jnp.arange(256, dtype=jnp.float32) * (r + 1),
                    mpi.MPI_SUM, algorithm="ring"), 3)
        assert config.comm_tracer() is None   # restored on exit
        evs = t.events_for(rank=0, channel="exchange")
        assert len(evs) == 1
        ev = evs[0]
        assert ev.op == "Allreduce"
        assert ev.family == "all_reduce"
        assert ev.payload_bytes == 256 * 4
        assert ev.algorithm == "ring"
        assert ev.world_size == 3
        assert ev.status == "ok"
        assert ev.duration_s >= 0
        # every rank recorded its own copy of the logical collective
        assert {e.rank for e in t.events_for(channel="exchange")} \
            == {0, 1, 2}

    def test_values_unchanged_under_tracing(self):
        def body(rank):
            x = jnp.full(5, float(rank) + 1.0)
            y = comm.Allreduce(x, mpi.MPI_SUM)
            g = jax.grad(
                lambda v: jnp.sum(comm.Allreduce(v, mpi.MPI_SUM)))(x)
            return np.asarray(y), np.asarray(g)

        plain = mpi.run_ranks(body, 3)
        with obs.trace():
            traced = mpi.run_ranks(body, 3)
        for (y0, g0), (y1, g1) in zip(plain, traced):
            np.testing.assert_array_equal(y0, y1)
            np.testing.assert_array_equal(g0, g1)

    def test_bucket_labels_on_fused_buckets(self):
        def body(rank):
            tree = {"a": jnp.arange(96, dtype=jnp.float32) * (r0 + 1)
                    for r0 in [rank]}
            return comm.Allreduce_tree(tree, mpi.MPI_SUM,
                                       bucket_bytes=128)
        with obs.trace() as t:
            mpi.run_ranks(body, 2)
        labels = {e.bucket for e in t.events_for(rank=0)
                  if e.bucket is not None}
        assert labels, "fused buckets recorded no bucket labels"
        assert all("Allreduce_tree.bucket" in b for b in labels)

    def test_p2p_events(self):
        def body(rank):
            h = comm.Isend(jnp.ones(8), (rank + 1) % 2, 3)
            buf = mpi.JoinDummies(jnp.zeros(8), [h.dummy])
            y = comm.Recv(buf, (rank - 1) % 2, 3)
            ret = comm.Wait(mpi.JoinDummiesHandle(h, [y]))
            return mpi.JoinDummies(y, [ret])
        with obs.trace() as t:
            mpi.run_ranks(body, 2)
        sends = t.events_for(channel="p2p_send")
        recvs = t.events_for(channel="p2p_recv")
        assert len(sends) == 2 and len(recvs) == 2
        # x64 harness: default dtype is f64 -> 8 bytes/elem
        itemsize = jnp.ones(1).dtype.itemsize
        assert all(e.payload_bytes == 8 * itemsize for e in sends)
        assert all(e.payload_bytes == 8 * itemsize for e in recvs)
        assert sends[0].peer is not None and sends[0].tag == 3

    def test_ring_buffer_bounded(self):
        with obs.trace(ring=4) as t:
            def body(rank):
                x = jnp.ones(2, jnp.float32)
                for _ in range(9):
                    x = comm.Allreduce(x, mpi.MPI_SUM)
                return x
            mpi.run_ranks(body, 2)
        tails = t.tails()
        assert all(len(v) == 4 for v in tails.values())
        # newest-last ordering
        for tail in tails.values():
            assert tail[-1].seq == max(e.seq for e in tail)


class TestModeAEvents:
    def test_spmd_hook_off_is_bit_identical(self):
        mesh = Mesh(np.asarray(jax.devices()), ("w",))
        cm = mpi.comm_from_mesh(mesh, "w")
        x = jnp.ones(64, jnp.float32)

        def lowered():
            return jax.jit(shard_map(
                lambda a: cm.Allreduce(a, mpi.MPI_SUM), mesh=mesh,
                in_specs=P(), out_specs=P(),
                check_vma=False)).lower(x).as_text()

        base = lowered()
        hook = obs.tracing.spmd_collective_event
        try:
            obs.tracing.spmd_collective_event = lambda v, where: v
            assert lowered() == base
        finally:
            obs.tracing.spmd_collective_event = hook
        # A Mode B-only tracer must not move the lowering either.
        with obs.trace():
            assert lowered() == base
        # A mode_a tracer prices exactly one host callback.
        with obs.trace(mode_a=True):
            on = lowered()
        assert on.count("stablehlo.custom_call") \
            - base.count("stablehlo.custom_call") == 1

    def test_mode_a_flag_rides_fingerprint(self):
        base = config.thresholds_fingerprint()
        assert base[-1] is False
        with obs.trace(mode_a=True):
            assert config.thresholds_fingerprint()[-1] is True
        with obs.trace():   # Mode B-only: no retrace forced
            assert config.thresholds_fingerprint() == base

    def test_mode_a_events_recorded(self):
        with obs.trace(mode_a=True) as t:
            step = mpi.run_spmd(
                lambda v: comm.Allreduce(v, mpi.MPI_SUM), nranks=4)
            jax.block_until_ready(step(jnp.ones(32, jnp.float32)))
        evs = t.events_for(channel="spmd")
        assert evs and evs[0].op == "Allreduce"
        assert evs[0].payload_bytes == 32 * 4


class TestMetricsRegistry:
    def test_counter_gauge_histogram_snapshot(self):
        reg = obs.MetricsRegistry()
        reg.inc("widgets_total", 2, help="widgets")
        reg.inc("widgets_total")
        reg.set_gauge("depth", 7)
        for v in (0.5e-4, 2e-3, 5.0):
            reg.observe("latency_seconds", v)
        snap = reg.snapshot()
        assert snap["counters"]["widgets_total"] == 3
        assert snap["gauges"]["depth"] == 7
        h = snap["histograms"]["latency_seconds"]
        assert h["count"] == 3 and h["sum"] == pytest.approx(5.00205)
        reg.reset()
        assert reg.snapshot()["counters"] == {}

    def test_prometheus_text(self):
        reg = obs.MetricsRegistry()
        reg.inc("events_total", 5, help="events seen")
        reg.observe("dur_seconds", 0.02)
        text = reg.prometheus_text()
        assert "# TYPE mpi4torch_events_total counter" in text
        assert "mpi4torch_events_total 5" in text
        assert 'mpi4torch_dur_seconds_bucket{le="+Inf"} 1' in text
        assert "mpi4torch_dur_seconds_count 1" in text

    def test_collectors_polled_at_snapshot(self):
        reg = obs.MetricsRegistry()
        state = {"n": 1}
        reg.register_collector("thing", lambda: dict(state))
        assert reg.snapshot()["collected"]["thing"] == {"n": 1}
        state["n"] = 9
        assert reg.snapshot()["collected"]["thing"] == {"n": 9}

    def test_broken_collector_isolated(self):
        reg = obs.MetricsRegistry()
        reg.register_collector("bad", lambda: 1 / 0)
        got = reg.snapshot()["collected"]["bad"]
        assert "error" in got and "ZeroDivisionError" in got["error"]

    def test_default_registry_has_serve_collector(self):
        snap = obs.snapshot()
        assert "serve" in snap["collected"]
        assert "n_engines" in snap["collected"]["serve"]

    def test_every_serve_counter_mirrors_as_metric(self):
        # ISSUE 17 satellite: the paging counters (prefix_hits,
        # cow_copies, blocks_in_use, ...) must reach the exposition
        # like every other ServeStats counter — registry-sync, not a
        # hand-picked subset, so a new counter cannot ship unmirrored.
        from mpi4torch_tpu import serve
        from mpi4torch_tpu.utils.profiling import (ServeStats,
                                                   _register_serve_stats)

        serve.reset_stats()
        s = _register_serve_stats(ServeStats())
        for name in ServeStats._COUNTERS:
            s.count(name, 0)
        try:
            text = obs.prometheus_text()
            for name in ServeStats._COUNTERS:
                assert f"mpi4torch_serve_{name} " in text, name
            for paging in ("prefix_hits", "cow_copies", "preempted",
                           "blocks_in_use", "blocks_free",
                           "blocks_cached"):
                assert paging in ServeStats._COUNTERS
        finally:
            serve.reset_stats()

    def test_percentile_matches_bench_rule(self):
        vals = [5.0, 1.0, 3.0, 2.0, 4.0]
        # bench's historical rule: sorted[min(int(q*n), n-1)]
        s = sorted(vals)
        for q in (0.0, 0.5, 0.9, 0.99, 1.0):
            assert obs.percentile(vals, q) \
                == s[min(int(q * len(s)), len(s) - 1)]
        assert obs.percentile([], 0.5) is None


class TestAdHocSurfacesUnified:
    """The satellite contract: retry_events and last_violation() keep
    their historical access paths AND appear as obs metrics."""

    def test_retry_events_mirrored(self):
        from mpi4torch_tpu.resilience import fault_scope

        obs.reset_metrics()
        spec = mpi.FaultSpec("drop_p2p", rank=0, op="p2p", index=0)
        seen = {}
        config.set_comm_retries(4)
        config.set_comm_backoff(0.05)
        try:
            with obs.trace() as t:
                def body(rank):
                    from mpi4torch_tpu.runtime import \
                        current_rank_context
                    ctx = current_rank_context()
                    if rank == 0:
                        ctx.world.p2p_send(0, 1, 9, jnp.ones(4))
                    else:
                        got = ctx.world.p2p_recv(0, 1, 9)
                        seen["retry_events"] = ctx.world.retry_events
                        return got
                with fault_scope([spec]):
                    mpi.run_ranks(body, 2, timeout=0.3)
        finally:
            config.set_comm_retries(0)
            config.set_comm_backoff(0.05)
        assert seen["retry_events"] >= 1          # old surface intact
        counters = obs.snapshot()["counters"]
        assert counters.get("comm_retry_events_total", 0) >= 1
        # ... and the recovering receive's event carries its retries.
        recvs = t.events_for(channel="p2p_recv")
        assert any(e.retries >= 1 for e in recvs)

    def test_violation_ledger_mirrored(self):
        import warnings

        from mpi4torch_tpu.resilience import guards

        obs.reset_metrics()
        guards.clear_violations()
        config.set_comm_finite_guard("warn")
        try:
            def body(rank):
                x = jnp.full(4, float("nan") if rank == 1 else 1.0)
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    return comm.Allreduce(x, mpi.MPI_SUM)
            mpi.run_ranks(body, 2)
        finally:
            config.set_comm_finite_guard("off")
        viol = guards.last_violation()            # old surface intact
        assert viol is not None and viol["ranks"] == [1]
        counters = obs.snapshot()["counters"]
        assert counters.get("integrity_violations_total", 0) >= 1
        guards.clear_violations()

    def test_tune_cache_counters(self):
        from mpi4torch_tpu import tune

        obs.reset_metrics()
        tune.autotuner.lookup("allreduce", jnp.float32, 123456789, 3,
                              platform="nosuch")
        counters = obs.snapshot()["counters"]
        assert counters.get("tune_cache_misses_total", 0) >= 1


class TestServeStatsRehome:
    """One weakref registry implementation: ServeStats registration
    rides obs.metrics.sources(); serve.stats()/reset_stats() keep
    their semantics; snapshot gains p50/p99 via the shared rule."""

    def test_registry_is_the_obs_one(self):
        from mpi4torch_tpu import serve
        from mpi4torch_tpu.utils.profiling import (ServeStats,
                                                   _register_serve_stats)

        serve.reset_stats()
        s = _register_serve_stats(ServeStats())
        from mpi4torch_tpu.obs.metrics import sources
        assert s in sources().live("serve")
        s.count("steps", 3)
        assert serve.stats()["steps"] == 3
        serve.reset_stats()
        assert sources().live("serve") == []
        assert serve.stats()["steps"] == 0
        assert s.counters["steps"] == 0    # reset IN PLACE, as before

    def test_snapshot_p50_p99(self):
        from mpi4torch_tpu.utils.profiling import ServeStats

        s = ServeStats()
        for i, rid in enumerate(("a", "b", "c")):
            s.mark(rid, "submitted")
            s.spans[rid]["first_token"] = \
                s.spans[rid]["submitted"] + 0.1 * (i + 1)
            s.spans[rid]["finished"] = \
                s.spans[rid]["submitted"] + 0.2 * (i + 1)
        snap = s.snapshot()
        ttft = [0.1, 0.2, 0.3]
        assert snap["ttft_s"]["p50"] == pytest.approx(
            obs.percentile(ttft, 0.50))
        assert snap["ttft_s"]["p99"] == pytest.approx(
            obs.percentile(ttft, 0.99))
        assert snap["e2e_s"]["p50"] == pytest.approx(0.4)
        assert {"mean", "max", "p50", "p99"} <= set(snap["e2e_s"])


class TestFlightRecorder:
    """The postmortem cell, alongside the fault matrix's existing
    rank_death attribution cells (resilience.matrix)."""

    def test_rank_death_postmortem_in_matrix_cell(self):
        from mpi4torch_tpu.resilience import matrix

        with obs.trace(ring=8) as t:
            rec = matrix.run_cell("rank_death", "plain", nranks=3)
        assert rec["status"] == "ok", rec     # the existing cell holds
        pm = t.last_postmortem()
        assert pm is not None
        assert pm["error"] == "RankFailedError"
        assert pm["failed_ranks"] == [1]      # the matrix's target rank
        # survivor tails consistent: everyone's last event is the torn
        # collective the dead rank also recorded last.
        from mpi4torch_tpu.obs.flight import last_event_signature
        dead_sig = last_event_signature(pm, 1)
        assert dead_sig is not None
        for r in range(3):
            assert last_event_signature(pm, r) == dead_sig

    def test_postmortem_format_and_dump(self, tmp_path):
        spec = mpi.FaultSpec("rank_death", rank=1, op="Allreduce",
                             index=1)
        from mpi4torch_tpu.resilience import fault_scope

        with obs.trace(ring=8) as t:
            with fault_scope([spec]):
                with pytest.raises(mpi.RankFailedError):
                    def body(rank):
                        x = jnp.ones(8, jnp.float32)
                        for _ in range(3):
                            x = comm.Allreduce(x, mpi.MPI_SUM)
                        return x
                    mpi.run_ranks(body, 3, timeout=2.0)
        pm = t.last_postmortem()
        text = obs.format_postmortem(pm)
        assert "FLIGHT RECORDER POSTMORTEM" in text
        assert "rank(s): [1]" in text
        assert "** FAILED/MISSING **" in text
        paths = obs.dump_postmortem(pm, str(tmp_path))
        import json
        with open(paths["json"], encoding="utf-8") as f:
            loaded = json.load(f)
        assert loaded["failed_ranks"] == [1]
        assert "tails" in loaded and loaded["tails"]

    def test_integrity_error_postmortem(self):
        """Failures raised OUTSIDE the chokepoints (the guards verify
        the decoded list after the rendezvous) still get a postmortem
        via the run_ranks reaper hook."""
        spec = mpi.FaultSpec("corrupt_nan", rank=1, op="Allreduce")
        from mpi4torch_tpu.resilience import fault_scope

        config.set_comm_finite_guard("raise")
        try:
            with obs.trace() as t:
                with fault_scope([spec]):
                    with pytest.raises(mpi.IntegrityError):
                        mpi.run_ranks(
                            lambda r: comm.Allreduce(
                                jnp.ones(8, jnp.float32), mpi.MPI_SUM),
                            2, timeout=2.0)
        finally:
            config.set_comm_finite_guard("off")
        pm = t.last_postmortem()
        assert pm is not None and pm["error"] == "IntegrityError"
        assert pm["failed_ranks"] == [1]


class TestChromeTraceExport:
    def test_export_structure(self, tmp_path):
        with obs.trace() as t:
            mpi.run_ranks(
                lambda r: comm.Allreduce(jnp.ones(16, jnp.float32),
                                         mpi.MPI_SUM), 2)
        doc = obs.chrome_trace(t.events)
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(xs) == 2
        assert {e["tid"] for e in xs} == {0, 1}
        assert all(e["args"]["payload_bytes"] == 64 for e in xs)
        assert all(e["dur"] >= 0 and e["ts"] >= 0 for e in xs)
        path = obs.write_chrome_trace(str(tmp_path / "t.json"),
                                      t.events)
        import json
        with open(path, encoding="utf-8") as f:
            assert json.load(f)["traceEvents"]


class TestReconcile:
    """The static-vs-runtime contract on tier-1-sized workloads (the
    full four-schedule matrix incl. q8 + serve decode runs in `make
    obs-smoke`)."""

    def test_ring_allreduce_exact(self):
        x = jnp.arange(512, dtype=jnp.float32)
        with obs.trace() as t:
            mpi.run_ranks(
                lambda r: comm.Allreduce(x * (r + 1), mpi.MPI_SUM,
                                         algorithm="ring"), 8)
        low = _lower(lambda cm, a: cm.Allreduce(a, mpi.MPI_SUM,
                                                algorithm="ring"), x)
        rep = obs.reconcile(t.events, low, dropped=t.dropped)
        assert rep["ok"], rep
        assert rep["measured"]["wire_bytes"] \
            == rep["predicted"]["wire_bytes"] == 2 * 7 * 512 * 4 // 8
        assert rep["measured"]["counts"] == {"all_reduce": 1}

    def test_reshard_migration_exact(self):
        from mpi4torch_tpu import reshard as rs

        fl = rs.layout((8,), 0, None)
        tl = rs.layout((2, 4), 0, 1)
        G = (64, 32)
        shard = fl.shard_shape(G)
        with obs.trace() as t:
            def body(rank):
                x = jnp.arange(int(np.prod(shard)), dtype=jnp.float32
                               ).reshape(shard) * (rank + 1)
                return comm.Reshard(x, fl, tl)
            mpi.run_ranks(body, 8)
        low = _lower(lambda cm, a: cm.Reshard(a, fl, tl),
                     jnp.zeros(shard, jnp.float32))
        rep = obs.reconcile(t.events, low, dropped=t.dropped)
        assert rep["ok"], rep

    def test_bookkeeping_excluded_and_determinism_checked(self):
        # Barrier + fold-share rounds are bookkeeping, not wire.
        with obs.trace() as t:
            def body(rank):
                from mpi4torch_tpu.runtime import current_rank_context
                ctx = current_rank_context()
                ctx.world.barrier(ctx.rank)
                return comm.Allreduce(jnp.ones(4, jnp.float32),
                                      mpi.MPI_SUM)
            mpi.run_ranks(body, 2)
        mt = obs.measured_wire_table(t.events)
        assert mt["excluded"]["bookkeeping"] == 1
        assert mt["logical_events"] == 1
        assert mt["per_rank_consistent"]

    def test_mismatch_detected(self):
        # A prediction for a DIFFERENT payload must not reconcile.
        x = jnp.arange(512, dtype=jnp.float32)
        with obs.trace() as t:
            mpi.run_ranks(
                lambda r: comm.Allreduce(x, mpi.MPI_SUM,
                                         algorithm="ring"), 4)
        low = _lower(
            lambda cm, a: cm.Allreduce(a, mpi.MPI_SUM,
                                       algorithm="ring"),
            jnp.arange(1024, dtype=jnp.float32))
        rep = obs.reconcile(t.events, low, dropped=t.dropped)
        assert not rep["ok"]
        assert not rep["matches"]["wire_bytes"]

    def test_dropped_events_fail_the_contract(self):
        x = jnp.ones(64, jnp.float32)
        with obs.trace() as t:
            mpi.run_ranks(
                lambda r: comm.Allreduce(x, mpi.MPI_SUM,
                                         algorithm="ring"), 8)
        low = _lower(lambda cm, a: cm.Allreduce(a, mpi.MPI_SUM,
                                                algorithm="ring"), x)
        good = obs.reconcile(t.events, low, dropped=0)
        bad = obs.reconcile(t.events, low, dropped=3)
        assert good["ok"] and not bad["ok"]
        # Passing the tracer itself reads .dropped automatically — the
        # canonical form cannot under-report a truncated census.
        assert obs.reconcile(t, low)["ok"]
        t.dropped = 5
        assert not obs.reconcile(t, low)["ok"]

    def test_spmd_events_counted_in_exclusions(self):
        # Mode A step events are not rendezvous wire, but they must
        # appear in the exclusion report, never vanish silently.
        with obs.trace(mode_a=True) as t:
            step = mpi.run_spmd(
                lambda v: comm.Allreduce(v, mpi.MPI_SUM), nranks=4)
            jax.block_until_ready(step(jnp.ones(16, jnp.float32)))
        mt = obs.measured_wire_table(t.events)
        assert mt["excluded"]["spmd"] == len(
            t.events_for(channel="spmd")) > 0

    def test_compressed_allgather_unmodeled_not_crashed(self):
        # The rendezvous-codec Allgather's encoded wire has no
        # event-reproducible Mode A census: it must land in the
        # unmodeled exclusion report, never raise out of the table.
        with obs.trace() as t:
            mpi.run_ranks(
                lambda r: comm.Allgather(
                    jnp.linspace(-1, 1, 64,
                                 dtype=jnp.float32) * (r + 1),
                    0, compression="q8"), 2)
        mt = obs.measured_wire_table(t.events)
        assert mt["excluded"]["unmodeled"].get("Allgather.c", 0) == 1
        assert mt["logical_events"] == 0

    def test_wire_contribution_shared_formula(self):
        # The ONE formula: analyze's static pass and the runtime
        # conversion agree by construction.
        assert analyze.wire_contribution("collective_permute", 100) \
            == 100
        assert analyze.wire_contribution("all_gather", 100, 4) == 300
        assert analyze.wire_contribution("all_reduce", 100, 4) \
            == pytest.approx(150.0)
        assert analyze.wire_contribution("reduce_scatter", 100, 4) \
            == pytest.approx(75.0)
        with pytest.raises(ValueError):
            analyze.wire_contribution("all_reduce", 100, None)
        with pytest.raises(ValueError):
            analyze.wire_contribution("nosuch", 100, 4)

"""mpi4torch_tpu.elastic — live world resize (ISSUE 13).

Coverage per the acceptance criteria:

* membership consensus: agreement (leaving/joining), post-death probe
  consensus on a world with absent ranks, injected disagreement →
  typed rank-attributed ``ConsensusError``, a second failure
  mid-consensus → attributed ``RankFailedError`` — never a hang;
* epoch fencing at every layer: consensus tags, the driver's
  ``StaleEpochError`` (naming both epochs), and the checkpoint stamp
  (``expect_epoch`` raises a typed ``CommError`` naming both epochs;
  ``restore_or_init`` surfaces skipped torn steps in its return
  value);
* ``reshard.plan_resize``: cross-world-size axis-0 re-deals bitwise vs
  the numpy oracle (shrink, grow, padded flat, TP rows), the gather
  baseline strictly more expensive, adjoint = the grow-back, VJP
  intact;
* the ``preempt`` fault kind: notice board semantics, death at the
  window end, and its resilience-matrix row;
* hot-spare mirrors: the spare's full replica bitwise vs the owners',
  zero-reshard takeover;
* serve drain/re-admission: in-flight requests survive a resize with
  token streams bitwise vs per-request ``generate()``;
* the grow-after-shrink round-trip: (8,)→(6,)→(8,) ZeRO training state
  bitwise vs the NEVER-FAILED oracle (sample-dealt SUM gradients make
  the global math world-size-independent and dyadic-exact);
* the censused elastic matrix: fast representative cells in tier-1,
  the full (kind × subsystem × action) sweep on the ``slow`` lane, and
  the registry-sync guard.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4torch_tpu as mpi
from mpi4torch_tpu import elastic as E
from mpi4torch_tpu import reshard as rs
from mpi4torch_tpu.elastic import matrix as ematrix
from mpi4torch_tpu.runtime import CommError, RankFailedError


# --------------------------------------------------------------------------
# registry sync
# --------------------------------------------------------------------------


class TestRegistrySync:
    def test_elastic_registry_in_sync(self):
        from mpi4torch_tpu.analyze.registry import elastic_problems

        assert elastic_problems() == []

    def test_missing_resilience_row_detected(self, monkeypatch):
        from mpi4torch_tpu.analyze.registry import elastic_problems
        from mpi4torch_tpu.resilience import matrix as rmatrix

        cov = {k: v for k, v in rmatrix.COVERAGE.items()
               if k != "preempt"}
        monkeypatch.setattr(rmatrix, "COVERAGE", cov)
        assert any("preempt" in p for p in elastic_problems())

    def test_coverage_drift_detected(self, monkeypatch):
        from mpi4torch_tpu.analyze.registry import elastic_problems

        cov = dict(ematrix.COVERAGE)
        cov.pop(("preempt", "zero", "shrink"))
        monkeypatch.setattr(ematrix, "COVERAGE", cov)
        assert any("drift" in p for p in elastic_problems())

    def test_preempt_registered_and_covered(self):
        from mpi4torch_tpu.resilience import FAULT_KINDS
        from mpi4torch_tpu.resilience.matrix import (COVERAGE,
                                                     EXPECTED_ERROR)

        assert "preempt" in FAULT_KINDS
        assert not FAULT_KINDS["preempt"].transient
        assert set(COVERAGE["preempt"]) == {"plain", "fused",
                                            "compressed", "overlap"}
        assert EXPECTED_ERROR["preempt"] is RankFailedError


# --------------------------------------------------------------------------
# WorldView / epoch fencing
# --------------------------------------------------------------------------


class TestWorldView:
    def test_initial_and_mapping(self):
        v = E.initial_view(4)
        assert v.epoch == 0 and v.size == 4
        assert v.alive == (0, 1, 2, 3) and v.mesh_shape == (4,)
        assert v.position(2) == 2 and v.id_at(3) == 3
        v2 = E.WorldView(3, (0, 2, 5), (3,))
        assert v2.position(5) == 2
        with pytest.raises(E.ElasticError):
            v2.position(1)

    def test_validation(self):
        with pytest.raises(E.ElasticError):
            E.WorldView(-1, (0,), (1,))
        with pytest.raises(E.ElasticError):
            E.WorldView(0, (1, 0), (2,))          # unsorted
        with pytest.raises(E.ElasticError):
            E.WorldView(0, (0, 0), (2,))          # duplicate
        with pytest.raises(E.ElasticError):
            E.WorldView(0, (0, 1, 2), (2, 2))     # mesh != members

    def test_fence_tags_disjoint(self):
        tags = {E.fence_tag(e, p) for e in range(5) for p in range(4)}
        assert len(tags) == 20

    def test_stale_epoch_fenced_by_driver(self):
        rt = E.ElasticRuntime(2, world_timeout=5.0)
        stale = rt.view
        # Adopt epoch 1 (everyone alive, no change besides the epoch).
        rt.consensus()
        assert rt.epoch == 1
        with pytest.raises(E.StaleEpochError) as ei:
            rt.run_phase(lambda pos, rid: None, view=stale)
        assert ei.value.have == 0 and ei.value.want == 1


# --------------------------------------------------------------------------
# consensus
# --------------------------------------------------------------------------


class TestConsensus:
    def test_agreement_with_leaving_and_joining(self):
        view = E.initial_view(4)

        def body(rank):
            return E.agree_world_view(view, leaving=[1], joining=[7],
                                      probe_timeout=2.0)

        outs = mpi.run_ranks(body, 4, timeout=10.0)
        assert len(set(outs)) == 1
        got = outs[0]
        assert got.epoch == 1 and got.alive == (0, 2, 3, 7)

    def test_post_death_probe_consensus_excludes_missing(self):
        rt = E.ElasticRuntime(4, probe_timeout=0.5, world_timeout=8.0)
        rt.note_dead(2, "reported by the driver")
        got = rt.consensus()
        assert got.alive == (0, 1, 3) and got.epoch == 1
        assert rt.view is got

    def test_disagreement_raises_attributed(self):
        rec = ematrix.run_consensus_cell("disagree")
        assert rec["status"] == "ok", rec["detail"]

    def test_second_failure_raises_attributed(self):
        rec = ematrix.run_consensus_cell("second_failure")
        assert rec["status"] == "ok", rec["detail"]
        assert "rank_death" in rec["fired"]

    def test_transition_metrics(self):
        from mpi4torch_tpu.obs import metrics as om

        om.reset_metrics()
        rt = E.ElasticRuntime(3, world_timeout=8.0)
        rt.consensus()
        snap = om.snapshot()
        assert snap["counters"]["elastic_epoch_transitions_total"] == 1
        assert snap["gauges"]["elastic_world_epoch"] == 1
        assert snap["gauges"]["elastic_world_size"] == 3

    def test_consensus_failure_gets_flight_postmortem(self):
        """A failed resize is postmortem-worthy: ConsensusError rides
        the SAME reaper entry every attributed failure does (zero new
        hooks), so the flight recorder snapshots the wire tails and
        names the disagreeing id."""
        from mpi4torch_tpu import obs

        view = E.initial_view(3)

        def body(rank):
            def propose(p):
                if rank == 1:
                    return E.WorldView(p.epoch, p.alive, (1, 3))
                return p
            return E.agree_world_view(view, probe_timeout=0.5,
                                      _propose=propose)

        with obs.trace() as tr:
            with pytest.raises(E.ConsensusError):
                mpi.run_ranks(body, 3, timeout=8.0)
            pm = tr.last_postmortem()
        assert pm is not None
        assert pm["error"] == "ConsensusError"
        assert pm["failed_ranks"] == [1]

    def test_leaving_unknown_id_raises(self):
        view = E.initial_view(2)

        def body(rank):
            return E.agree_world_view(view, leaving=[5],
                                      probe_timeout=1.0)

        with pytest.raises(E.ElasticError):
            mpi.run_ranks(body, 2, timeout=5.0)


class TestHealthProbeMetrics:
    def test_probe_duration_and_counters(self):
        from mpi4torch_tpu.obs import metrics as om

        om.reset_metrics()

        def body(rank):
            return mpi.COMM_WORLD.check_health(2.0)

        reps = mpi.run_ranks(body, 3, timeout=8.0)
        assert all(r.ok for r in reps)
        assert all(r.probe_duration_s >= 0.0 for r in reps)
        counters = om.snapshot()["counters"]
        assert counters['comm_health_probes_total{result="ok"}'] == 3
        text = om.prometheus_text()
        # The labeled sample keeps its label set; the TYPE header uses
        # the bare family name exactly once.
        assert ('mpi4torch_comm_health_probes_total{result="ok"} 3'
                in text)
        assert text.count(
            "# TYPE mpi4torch_comm_health_probes_total counter") == 1

    def test_failed_probe_counter(self):
        from mpi4torch_tpu.obs import metrics as om

        om.reset_metrics()

        def body(rank):
            if rank == 1:
                return None        # never probes: the others time out
            return mpi.COMM_WORLD.check_health(0.3)

        reps = mpi.run_ranks(body, 3, timeout=8.0)
        failed = [r for r in reps if r is not None]
        assert all(not r.ok and 1 in r.missing for r in failed)
        counters = om.snapshot()["counters"]
        assert counters['comm_health_probes_total{result="failed"}'] == 2


# --------------------------------------------------------------------------
# preempt fault kind
# --------------------------------------------------------------------------


class TestPreemptKind:
    def test_notice_then_survival_inside_window(self):
        from mpi4torch_tpu.resilience import (FaultSpec, fault_scope,
                                              pending_preemptions)

        def body(rank):
            x = jnp.arange(8, dtype=jnp.float32)
            for _ in range(3):
                mpi.COMM_WORLD.Allreduce(x, mpi.MPI_SUM)
            return pending_preemptions()

        spec = FaultSpec("preempt", rank=1, op="Allreduce", index=0,
                         count=10)
        with fault_scope([spec]) as plan:
            outs = mpi.run_ranks(body, 3, timeout=8.0)
        assert "preempt" in plan.fired_kinds()
        # Inside the body after 3 ops: death at op index 9, so 7 remain.
        assert outs[0] == {1: 7}
        # Board persists past the world: the driver polls between
        # phases.
        assert plan.preemption_notices() == {1: 7}
        plan.clear_preemption(1)
        assert plan.preemption_notices() == {}

    def test_death_at_window_end_attributed(self):
        from mpi4torch_tpu.resilience import FaultSpec, fault_scope

        def body(rank):
            x = jnp.arange(4, dtype=jnp.float32)
            for _ in range(4):
                mpi.COMM_WORLD.Allreduce(x, mpi.MPI_SUM)

        spec = FaultSpec("preempt", rank=1, op="Allreduce", index=0,
                         count=3)
        with fault_scope([spec]) as plan:
            with pytest.raises(RankFailedError) as ei:
                mpi.run_ranks(body, 3, timeout=2.0)
        assert ei.value.ranks == frozenset({1})
        assert "advance notice" in str(ei.value)
        assert "preempt" in plan.fired_kinds()

    def test_resilience_matrix_row(self):
        from mpi4torch_tpu.resilience import matrix as rmatrix

        rec = rmatrix.run_cell("preempt", "plain", nranks=3)
        assert rec["status"] == "ok", rec["detail"]


# --------------------------------------------------------------------------
# plan_resize
# --------------------------------------------------------------------------


def _exec_resize(plan, inputs, exec_size, differentiable=False):
    def body(rank):
        return np.asarray(rs.apply_plan(
            mpi.COMM_WORLD, plan, jnp.asarray(inputs[rank]),
            differentiable=differentiable))

    return mpi.run_ranks(body, exec_size, timeout=20.0)


class TestPlanResize:
    def _flat_case(self, n, W, M, strategy=None):
        perW, perM = -(-n // W), -(-n // M)
        data = np.arange(n, dtype=np.float64)
        src = np.pad(data, (0, perW * W - n))
        want = np.pad(data, (0, perM * M - n))
        plan = rs.plan_resize(n, (), W, M, np.float64,
                              embed_from=tuple(range(W)),
                              embed_to=tuple(range(M)),
                              exec_size=max(W, M), strategy=strategy)
        inputs = [src[r * perW:(r + 1) * perW] if r < W
                  else np.zeros(perW) for r in range(max(W, M))]
        outs = _exec_resize(plan, inputs, max(W, M))
        for j in range(M):
            np.testing.assert_array_equal(
                outs[j], want[j * perM:(j + 1) * perM])
        return plan

    def test_shrink_padded_flat_bitwise(self):
        self._flat_case(100, 8, 6)

    def test_grow_padded_flat_bitwise(self):
        self._flat_case(100, 6, 8)

    def test_gather_strategy_bitwise_and_costlier(self):
        p = self._flat_case(96, 8, 6)
        g = self._flat_case(96, 8, 6, strategy="gather")
        assert g.strategy == "gather"
        assert p.wire_bytes < g.wire_bytes
        assert p.peak_bytes < g.peak_bytes

    def test_rows_resize_bitwise(self):
        bank = np.arange(24 * 4, dtype=np.float32).reshape(24, 4)
        plan = rs.plan_resize(24, (4,), 8, 6, np.float32,
                              embed_from=tuple(range(8)),
                              embed_to=tuple(range(6)), exec_size=8)
        inputs = [bank[r * 3:(r + 1) * 3] for r in range(8)]
        outs = _exec_resize(plan, inputs, 8)
        for j in range(6):
            np.testing.assert_array_equal(outs[j],
                                          bank[j * 4:(j + 1) * 4])

    def test_adjoint_is_grow_back(self):
        plan = rs.plan_resize(24, (), 8, 6, np.float32,
                              embed_from=tuple(range(8)),
                              embed_to=tuple(range(6)), exec_size=8)
        adj = plan.adjoint()
        assert adj.in_shape == plan.out_shape
        assert adj.out_shape == plan.in_shape
        data = np.arange(24, dtype=np.float32)

        def body(rank):
            comm = mpi.COMM_WORLD
            x = jnp.asarray(data[rank * 3:(rank + 1) * 3])
            y = rs.apply_plan(comm, plan, x, differentiable=False)
            back = rs.apply_plan(comm, adj, y, differentiable=False)
            return np.asarray(back)

        outs = mpi.run_ranks(body, 8, timeout=20.0)
        for r in range(8):
            np.testing.assert_array_equal(outs[r],
                                          data[r * 3:(r + 1) * 3])

    def test_vjp_round_trips_cotangents(self):
        plan = rs.plan_resize(24, (), 8, 6, np.float32,
                              embed_from=tuple(range(8)),
                              embed_to=tuple(range(6)), exec_size=8)
        data = np.arange(24, dtype=np.float32)

        def body(rank):
            x = jnp.asarray(data[rank * 3:(rank + 1) * 3])

            def f(v):
                y = rs.apply_plan(mpi.COMM_WORLD, plan, v)
                return jnp.sum(y * 3.0)

            return np.asarray(jax.grad(f)(x))

        grads = mpi.run_ranks(body, 8, timeout=20.0)
        for r in range(8):
            np.testing.assert_array_equal(grads[r],
                                          np.full(3, 3.0, np.float32))

    def test_validation(self):
        with pytest.raises(CommError):
            rs.plan_resize(24, (), 8, 6, np.float32,
                           embed_from=(0,), embed_to=tuple(range(6)),
                           exec_size=8)
        with pytest.raises(CommError):
            rs.plan_resize(24, (), 8, 6, np.float32,
                           embed_from=tuple(range(8)),
                           embed_to=(0, 0, 1, 2, 3, 4), exec_size=8)
        with pytest.raises(CommError):
            rs.plan_resize(24, (), 8, 6, np.float32,
                           embed_from=tuple(range(8)),
                           embed_to=(0, 1, 2, 3, 4, 9), exec_size=8)

    def test_plan_reshard_still_refuses_size_change(self):
        with pytest.raises(CommError, match="world size"):
            rs.plan_reshard(rs.layout((8,), 0), rs.layout((6,), 0),
                            (24,), np.float32)


# --------------------------------------------------------------------------
# checkpoint epoch + skipped ledger
# --------------------------------------------------------------------------


@pytest.fixture
def _orbax():
    pytest.importorskip("orbax.checkpoint")


class TestCheckpointEpoch:
    def _state(self, s):
        return {"w": jnp.arange(6, dtype=jnp.float32) * (s + 1)}

    def test_epoch_stamp_and_stale_fence(self, tmp_path, _orbax):
        from mpi4torch_tpu.utils.checkpoint import (CheckpointManager,
                                                    saved_epoch)

        d = str(tmp_path / "ck")
        with CheckpointManager(d) as mgr:
            mgr.save(0, self._state(0), force=True, epoch=2)
            mgr.wait_until_finished()
            assert saved_epoch(mgr._step_path(0)) == 2
            with pytest.raises(CommError) as ei:
                mgr.restore(0, template=self._state(0), expect_epoch=5)
            assert "epoch 2" in str(ei.value)
            assert "epoch 5" in str(ei.value)
            got = mgr.restore(0, template=self._state(0), expect_epoch=2)
        np.testing.assert_array_equal(np.asarray(got["w"]),
                                      np.asarray(self._state(0)["w"]))

    def test_unstamped_step_passes_any_expectation(self, tmp_path,
                                                   _orbax):
        from mpi4torch_tpu.utils.checkpoint import CheckpointManager

        d = str(tmp_path / "ck")
        with CheckpointManager(d) as mgr:
            mgr.save(0, self._state(0), force=True)
            mgr.wait_until_finished()
            mgr.restore(0, template=self._state(0), expect_epoch=7)

    def test_restore_or_init_surfaces_skipped_steps(self, tmp_path,
                                                    _orbax):
        import warnings

        from mpi4torch_tpu.resilience import (FaultSpec, fault_scope,
                                              restore_or_init)
        from mpi4torch_tpu.utils.checkpoint import CheckpointManager

        d = str(tmp_path / "ck")
        spec = FaultSpec("truncate_save", rank=0, op="ckpt_save",
                         index=2)
        with fault_scope([spec]):
            with CheckpointManager(d) as mgr:
                for s in range(3):
                    mgr.save(s, self._state(s), force=True, epoch=0)
                mgr.wait_until_finished()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            res = restore_or_init(d, template=self._state(0),
                                  expect_epoch=0)
        state, step = res              # tuple compatibility intact
        assert step == 1 and res.step == 1 and res.state is state
        assert [s.step for s in res.skipped] == [2]
        assert res.skipped[0].reason    # the why, not just the what
        np.testing.assert_array_equal(np.asarray(state["w"]),
                                      np.asarray(self._state(1)["w"]))

    def test_restore_or_init_stale_epoch_raises(self, tmp_path, _orbax):
        from mpi4torch_tpu.resilience import restore_or_init
        from mpi4torch_tpu.utils.checkpoint import CheckpointManager

        d = str(tmp_path / "ck")
        with CheckpointManager(d) as mgr:
            mgr.save(0, self._state(0), force=True, epoch=0)
            mgr.wait_until_finished()
        with pytest.raises(CommError) as ei:
            restore_or_init(d, template=self._state(0), expect_epoch=3)
        assert "epoch 0" in str(ei.value) and "epoch 3" in str(ei.value)


# --------------------------------------------------------------------------
# spare mirrors
# --------------------------------------------------------------------------


class TestSpare:
    def test_bank_mirror_and_takeover(self):
        n_data, world = 3, 4
        bank0 = np.arange(12 * 2, dtype=np.float32).reshape(12, 2)

        def body(rank):
            slot = rank if rank < n_data else None
            per = 12 // n_data
            st = (jnp.asarray(bank0) if slot is None
                  else jnp.asarray(bank0[slot * per:(slot + 1) * per]))
            for t in range(2):
                contrib = (ematrix._delta(t, slot, bank0.shape)
                           if slot is not None
                           else np.zeros(bank0.shape, np.float32))
                st = E.bank_spare_step(mpi.COMM_WORLD, st,
                                       jnp.asarray(contrib),
                                       n_data=n_data, slot=slot)
            return np.asarray(st)

        outs = mpi.run_ranks(body, world, timeout=10.0)
        oracle = ematrix._bank_oracle(bank0,
                                      [((0, 1), range(n_data))])
        per = 12 // n_data
        for slot in range(n_data):
            np.testing.assert_array_equal(
                outs[slot], oracle[slot * per:(slot + 1) * per])
        # The mirror replicates the full bank, and its takeover slice
        # of any slot is bitwise the owner's shard.
        np.testing.assert_array_equal(outs[n_data], oracle)
        np.testing.assert_array_equal(
            np.asarray(E.takeover_bank_slot(outs[n_data], 1, n_data)),
            outs[1])

    def test_zero_mirror_segments_match_owners(self):
        n_data, world = 4, 5
        opt = ematrix._Momentum()
        params0 = {k: np.arange(int(np.prod(s)), dtype=np.float32)
                   .reshape(s) for k, s in ematrix._ZSHAPES.items()}

        def body(rank):
            slot = rank if rank < n_data else None
            p = {k: jnp.asarray(x) for k, x in params0.items()}
            st = E.zero_spare_init(opt, p, n_data, slot)
            for t in range(2):
                grads = ({k: jnp.asarray(x) for k, x in
                          ematrix._zero_grads(t, slot).items()}
                         if slot is not None else
                         {k: jnp.zeros(s, jnp.float32)
                          for k, s in ematrix._ZSHAPES.items()})
                p, st = E.zero_spare_step(mpi.COMM_WORLD, opt, p, grads,
                                          st, n_data=n_data, slot=slot)
            return ({k: np.asarray(x) for k, x in p.items()}, st)

        outs = mpi.run_ranks(body, world, timeout=15.0)
        o_params, o_m = ematrix._zero_oracle([((0, 1), range(n_data))])
        for k in ematrix._ZSHAPES:
            for r in range(world):
                np.testing.assert_array_equal(outs[r][0][k],
                                              o_params[k])
        taken = E.takeover_shard(
            outs[n_data][1], 2, n_data,
            {k: jnp.asarray(x) for k, x in params0.items()})
        for k in ematrix._ZSHAPES:
            np.testing.assert_array_equal(np.asarray(taken[k]),
                                          np.asarray(outs[2][1][k]))

    def test_bad_slots_table_raises(self):
        def body(rank):
            return E.zero_spare_step(
                mpi.COMM_WORLD, ematrix._Momentum(),
                {"w": jnp.zeros(4)}, {"w": jnp.zeros(4)},
                {"w": jnp.zeros(2)}, n_data=2, slot=0,
                slots=(0, 0, None))

        with pytest.raises(E.ElasticError):
            mpi.run_ranks(body, 3, timeout=5.0)


# --------------------------------------------------------------------------
# serve drain / re-admission
# --------------------------------------------------------------------------


class TestServeDrain:
    def test_drain_readmit_tokens_bitwise_single_rank(self):
        from mpi4torch_tpu.serve import Engine, ServeConfig

        cfg = ematrix._serve_cfg()
        params = ematrix._serve_params(cfg)
        oracle = ematrix._serve_oracle(cfg, params)

        eng = Engine(cfg, params, ServeConfig(slots=2))
        for i, (p, n) in enumerate(zip(ematrix._SERVE_PROMPTS,
                                       ematrix._SERVE_BUDGETS)):
            eng.submit(np.asarray(p), rid=i, max_new=n)
        for _ in range(3):
            eng.step()
        tickets, results = E.drain_tickets(eng)
        assert eng.pending() == 0          # drained for real
        assert any(t.emitted for t in tickets)

        eng2 = Engine(cfg, params, ServeConfig(slots=2))
        E.readmit(eng2, tickets)
        results.update(eng2.run())
        stitched = E.stitched_results(results, tickets)
        for i in oracle:
            np.testing.assert_array_equal(
                np.asarray(stitched[i], np.int64),
                np.asarray(oracle[i], np.int64))

    def test_snapshot_is_nondestructive(self):
        from mpi4torch_tpu.serve import Engine, ServeConfig

        cfg = ematrix._serve_cfg()
        params = ematrix._serve_params(cfg)
        eng = Engine(cfg, params, ServeConfig(slots=2))
        eng.submit(np.asarray([3, 4, 5]), rid="a", max_new=4)
        eng.step()
        before = eng.pending()
        recs = eng.snapshot_inflight()
        assert eng.pending() == before
        assert recs and recs[0]["rid"] == "a"
        assert list(recs[0]["emitted"])    # progress captured

    def test_drained_rid_reusable(self):
        from mpi4torch_tpu.serve import Engine, ServeConfig

        cfg = ematrix._serve_cfg()
        params = ematrix._serve_params(cfg)
        eng = Engine(cfg, params, ServeConfig(slots=2))
        eng.submit(np.asarray([3, 4]), rid="a", max_new=3)
        eng.step()
        eng.drain()
        # The drained rid left this engine's ledger: re-admission (on
        # this or another engine) must not collide.
        eng.submit(np.asarray([3, 4, 5]), rid="a", max_new=2)


# --------------------------------------------------------------------------
# the grow-after-shrink round-trip (the satellite)
# --------------------------------------------------------------------------


class TestRoundTrip:
    N_SAMPLES = 24

    def _sample_grads(self, t):
        return {k: np.sum([ematrix._delta(t * 31 + s, s, shape)
                           for s in range(self.N_SAMPLES)], axis=0)
                for k, shape in ematrix._ZSHAPES.items()}

    def _local_grads(self, t, view, pos):
        per = self.N_SAMPLES // view.size
        out = {}
        for k, shape in ematrix._ZSHAPES.items():
            out[k] = np.sum(
                [ematrix._delta(t * 31 + s, s, shape)
                 for s in range(pos * per, (pos + 1) * per)], axis=0)
        return out

    def test_zero_state_bitwise_vs_never_failed_oracle(self):
        """(8,)→(6,)→(8,): the same 24-sample global batch dealt to
        whatever membership is current, SUM reduction — dyadic-exact,
        so the never-failed 8-world oracle is bit-for-bit the law for
        every world the schedule visits."""
        from mpi4torch_tpu.parallel.zero import zero_step

        opt = ematrix._Momentum()
        params0 = {k: np.arange(int(np.prod(s)), dtype=np.float32)
                   .reshape(s) for k, s in ematrix._ZSHAPES.items()}
        rt = E.ElasticRuntime(8, probe_timeout=0.5, world_timeout=20.0)

        def phase(params_in, states, view, ts):
            def body(pos, rid):
                p = {k: jnp.asarray(x) for k, x in params_in.items()}
                st = states[rid]
                for t in ts:
                    g = {k: jnp.asarray(x) for k, x in
                         self._local_grads(t, view, pos).items()}
                    p, st = zero_step(mpi.COMM_WORLD, opt, p, g, st,
                                      mean=False)
                return ({k: np.asarray(x) for k, x in p.items()},
                        {k: np.asarray(x) for k, x in st.items()})
            return rt.run_phase(body)

        view0 = rt.view
        states = {rid: {k: jnp.zeros(
            (-(-int(np.prod(s)) // 8),), jnp.float32)
            for k, s in ematrix._ZSHAPES.items()} for rid in view0.alive}
        res = phase(params0, states, view0, (0, 1))
        params = res[0][0]
        states = {view0.alive[p]: {k: jnp.asarray(res[p][1][k])
                                   for k in ematrix._ZSHAPES}
                  for p in range(8)}

        # Planned descale (no fault): drain 8 -> 6 with the live replan.
        def drain_body(pos, rid, old_view, new_view):
            out = E.replan_zero(mpi.COMM_WORLD, states[rid], params0,
                                old_view, new_view, mode="drain")
            return {k: np.asarray(x) for k, x in out.items()}

        outs = rt.drain(drain_body, leaving=[2, 7])
        view1 = rt.view
        assert view1.size == 6
        states = {rid: {k: jnp.asarray(outs[view0.position(rid)][k])
                        for k in ematrix._ZSHAPES}
                  for rid in view1.alive}
        res = phase(params, states, view1, (2,))
        params = res[0][0]
        states = {view1.alive[p]: {k: jnp.asarray(res[p][1][k])
                                   for k in ematrix._ZSHAPES}
                  for p in range(6)}

        # Grow back to 8; joiners receive their shards on the wire.
        view2 = rt.consensus(joining=[2, 7])
        assert view2.size == 8 and view2.epoch == 2

        def grow_body(pos, rid):
            if rid in view1.alive:
                st = states[rid]
            else:
                st = {k: jnp.zeros(
                    (-(-int(np.prod(s)) // 6),), jnp.float32)
                    for k, s in ematrix._ZSHAPES.items()}
            out = E.replan_zero(mpi.COMM_WORLD, st, params0, view1,
                                view2, mode="grow")
            return {k: np.asarray(x) for k, x in out.items()}

        res = rt.run_phase(grow_body)
        states = {view2.alive[p]: {k: jnp.asarray(res[p][k])
                                   for k in ematrix._ZSHAPES}
                  for p in range(8)}
        res = phase(params, states, view2, (3,))
        params = res[0][0]
        states = {view2.alive[p]: res[p][1] for p in range(8)}

        # The NEVER-FAILED oracle: four steps on the 8-world, same
        # global batch — numpy, replicated.
        o_params = dict(params0)
        o_m = {k: np.zeros(s, np.float32)
               for k, s in ematrix._ZSHAPES.items()}
        for t in range(4):
            g = self._sample_grads(t)
            for k in ematrix._ZSHAPES:
                o_m[k] = o_m[k] * 0.5 + g[k]
                o_params[k] = o_params[k] + o_m[k] * (-0.25)
        for k in ematrix._ZSHAPES:
            np.testing.assert_array_equal(params[k], o_params[k])
        for rid in view2.alive:
            j = view2.position(rid)
            for k in ematrix._ZSHAPES:
                np.testing.assert_array_equal(
                    np.asarray(states[rid][k]),
                    ematrix._np_shard(o_m[k], 8, j))


# --------------------------------------------------------------------------
# the elastic matrix
# --------------------------------------------------------------------------


_FAST_CELLS = [
    ("preempt", "plain", "shrink"),
    ("rank_death", "plain", "spare"),
    ("preempt", "zero", "shrink"),
    ("rank_death", "moe", "shrink"),
]


class TestMatrixFast:
    @pytest.mark.parametrize("kind,subsystem,action", _FAST_CELLS)
    def test_cell(self, kind, subsystem, action):
        rec = ematrix.run_cell(kind, subsystem, action)
        assert rec["status"] == "ok", rec["detail"]
        assert kind in rec["fired"]


@pytest.mark.slow
class TestMatrixFull:
    def test_every_cell(self):
        failures = []
        for key in sorted(ematrix.COVERAGE):
            rec = ematrix.run_cell(*key)
            if rec["status"] != "ok":
                failures.append((key, rec["detail"]))
        for kind in sorted(ematrix.EXPECTED_CONSENSUS_ERROR):
            rec = ematrix.run_consensus_cell(kind)
            if rec["status"] != "ok":
                failures.append((kind, rec["detail"]))
        assert not failures, failures

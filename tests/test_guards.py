"""Misuse-detector tests — the framework's domain-specific 'race detectors'
(SURVEY.md §5 'Race detection'): wait-handle bifurcation and exactly-once
completion (reference guards csrc/extension.cpp:1196-1202, 1231-1237),
in-place reuse (csrc/extension.cpp:395-403), plus the detectors this
framework adds beyond the reference: collective-mismatch detection and
deadlock timeouts (MPI would hang or corrupt; we raise)."""

import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm, run_ranks


def test_double_wait_raises():
    def body():
        a = jnp.asarray([1.0 + comm.rank])
        h = comm.Isend(a, (comm.rank + 1) % comm.size, 0)
        comm.Recv(jnp.empty_like(a), (comm.rank - 1 + comm.size) % comm.size, 0)
        comm.Wait(h)
        with pytest.raises(mpi.BifurcationError, match="bifurcation"):
            comm.Wait(h)

    run_ranks(body, 2)


def test_swapped_handle_parts_raise():
    # Splicing the descriptor of one request onto the buffer of another is
    # the 'bifurcation' hazard the reference hash-guards against
    # (csrc/extension.cpp:1231-1237).
    def body():
        a = jnp.ones(3) * comm.rank
        b = jnp.ones(5) * comm.rank
        h1 = comm.Isend(a, (comm.rank + 1) % comm.size, 0)
        h2 = comm.Isend(b, (comm.rank + 1) % comm.size, 1)
        comm.Recv(jnp.empty(3), (comm.rank - 1 + comm.size) % comm.size, 0)
        comm.Recv(jnp.empty(5), (comm.rank - 1 + comm.size) % comm.size, 1)
        frankenstein = mpi.WaitHandle(
            [h1._handle[0], h2._handle[1], h2._handle[2]])
        with pytest.raises(mpi.BifurcationError):
            comm.Wait(frankenstein)
        comm.Wait(h2)

    run_ranks(body, 2)


def test_collective_mismatch_detected():
    # MPI deadlocks or corrupts buffers when ranks disagree on the
    # collective; this runtime raises on every rank.
    def body():
        x = jnp.ones(4)
        with pytest.raises(mpi.CollectiveMismatchError):
            if comm.rank == 0:
                comm.Allreduce(x, mpi.MPI_SUM)
            else:
                comm.Bcast_(x, 0)

    run_ranks(body, 2)


def test_shape_mismatch_detected():
    # Allreduce requires identical shapes on all ranks; MPI would read out
    # of bounds.
    def body():
        x = jnp.ones(4 + comm.rank)
        with pytest.raises(mpi.CollectiveMismatchError):
            comm.Allreduce(x, mpi.MPI_SUM)

    run_ranks(body, 2)


def test_recv_deadlock_times_out():
    def body():
        if comm.rank == 0:
            with pytest.raises(mpi.DeadlockError, match="timed out"):
                comm.Recv(jnp.empty(3), 1, 99)
        # rank 1 never sends

    run_ranks(body, 2, timeout=1.0)


def test_missing_collective_times_out():
    def body():
        if comm.rank == 0:
            with pytest.raises((mpi.DeadlockError, mpi.CommError)):
                comm.Allreduce(jnp.ones(3), mpi.MPI_SUM)
        # rank 1 never joins the collective

    run_ranks(body, 2, timeout=1.0)


def test_invalid_root_raises():
    def body():
        with pytest.raises(mpi.CommError, match="root"):
            comm.Bcast_(jnp.ones(3), 7)

    run_ranks(body, 2)


def test_minloc_rejected_with_explanation():
    # reference forwards MPI_MINLOC to MPI with a scalar dtype, which MPI
    # rejects at runtime (no pair datatype, csrc/extension.cpp:106-129); we
    # reject with a clear error up front.
    def body():
        with pytest.raises(NotImplementedError, match="MINLOC"):
            comm.Allreduce(jnp.ones(3), mpi.MPI_MINLOC)

    run_ranks(body, 2)


def test_fold_once_result_consumption_is_per_rank():
    # Above _FOLD_ONCE_MIN the eager Allreduce folds once on rank 0 and
    # hands EVERY rank the same (immutable) result object.  The in-place
    # consumed guard keys per (rank, id): rank 0 consuming its result via
    # Reduce_ must not taint rank 1's use of the shared object (in MPI
    # these would be distinct buffers in distinct processes).
    from mpi4torch_tpu.ops import eager

    n = eager._FOLD_ONCE_MIN

    def body():
        y = comm.Allreduce(jnp.ones(n), mpi.MPI_SUM)
        if comm.rank == 0:
            comm.Reduce_(y, mpi.MPI_SUM, 0)
            # The guard raises BEFORE any rendezvous, so this is not a
            # collective — rank 1 sees nothing.
            with pytest.raises(mpi.InPlaceReuseError):
                comm.Allreduce(y, mpi.MPI_SUM)
            # Matching member of rank 1's final collective.
            return comm.Allreduce(jnp.ones(n), mpi.MPI_SUM)
        comm.Reduce_(jnp.ones(n), mpi.MPI_SUM, 0)
        # Rank 1 never consumed y; using the shared object must stay
        # legal even though rank 0 just consumed the same object.
        return comm.Allreduce(y, mpi.MPI_SUM)

    run_ranks(body, 2)


def test_fold_once_unsupported_op_raises_on_every_rank():
    # Unsupported reduction ops must keep the every-rank fold path above
    # the fold-once threshold, so each rank raises the informative error
    # (not a rank-0 death plus broken-barrier aborts elsewhere).
    from mpi4torch_tpu.ops import eager

    def body():
        with pytest.raises(NotImplementedError, match="MAXLOC"):
            comm.Allreduce(jnp.ones(eager._FOLD_ONCE_MIN), mpi.MPI_MAXLOC)

    run_ranks(body, 2)

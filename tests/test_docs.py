"""The docs must BUILD (VERDICT round 1: markdown only, no build system;
reference ships Sphinx + autodoc + RTD, doc/conf.py, .readthedocs.yaml).

`make docs` prefers Sphinx; this test exercises the environment-
independent fallback generator directly and checks the autodoc output
actually reflects the live API surface."""

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_docs_build_and_cover_api(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, str(REPO / "doc" / "build_docs.py")],
                       capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr

    out = REPO / "doc" / "html"
    pages = {p.name for p in out.glob("*.html")}
    for required in ["index.html", "basic_usage.html", "api_reference.html",
                     "parallelism.html", "api_autodoc.html"]:
        assert required in pages

    autodoc = (out / "api_autodoc.html").read_text()
    # Live-introspected names: facade ops, round-2 additions, and a
    # docstring fragment proving real docs (not just names) are in.
    for name in ["MPI_Communicator", "Allreduce", "JoinDummies",
                 "WaitHandle", "COMM_WORLD", "init_distributed",
                 "comm_from_mpi4py", "ragged_gather", "ragged_scatter",
                 "p2p_scope", "flash_attention", "run_spmd", "run_ranks"]:
        assert name in autodoc, f"autodoc missing {name}"
    assert "src/__init__.py" in autodoc     # reference citations survive

    index = (out / "index.html").read_text()
    assert "<nav>" in index and "api_autodoc.html" in index

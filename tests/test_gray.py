"""Gray-failure robustness (ISSUE 15): performance-fault kinds,
slow-rank detection, and the lock-step degraded-mode runtime.

Pins the tentpole contracts:

* the four performance-fault kinds (``slow_rank``/``jitter``/
  ``flaky_link``/``brownout``) are deterministic (seeded draws replay
  bit-for-bit), censused (brownout's throttle is proportional to the
  censused payload bytes, recorded in the fired ledger), and
  registry-sync guarded into BOTH matrices;
* ``comm.check_health`` distinguishes slow from dead: per-rank
  ``arrival_s`` latencies next to the ``missing`` set;
* the detector attributes the slow rank POSITIVELY off the
  ``duration - wait`` split of the CommEvent stream, counts detections
  in the metrics registry, and escalates to a typed, attributed
  ``SlowRankError`` with a flight-recorder postmortem;
* the degrade policies are a closed registry; transitions are
  epoch-fenced through the elastic consensus and fully reversible
  (``DegradeController.reset``); the per-rank wire census ranking the
  schedule failover is self-consistent (every candidate moves the same
  total wire — concentration, not volume, differs);
* the chaos matrix's fast subset runs in tier-1; the FULL matrix and
  the seeded storms ride the ``slow`` lane and ``make chaos-smoke``.
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import obs
from mpi4torch_tpu import resilience as rz
from mpi4torch_tpu.analyze.registry import degrade_problems
from mpi4torch_tpu.obs.events import payload_nbytes
from mpi4torch_tpu.resilience import chaos as rchaos
from mpi4torch_tpu.resilience import degrade as rdegrade
from mpi4torch_tpu.resilience import matrix as rmatrix
from mpi4torch_tpu.resilience.faults import _hash01

comm = mpi.COMM_WORLD


@pytest.fixture(autouse=True)
def _restore_config():
    yield
    mpi.config.set_comm_retries(0)
    mpi.config.set_comm_backoff(0.05)
    mpi.config.set_fault_plan(None)
    mpi.config.set_default_compression(None)
    mpi.config.set_default_algorithm(None)


def _run_traced(body, nranks, specs, retries=5, backoff=0.2,
                timeout=10.0):
    with rmatrix._knob(comm_retries=retries, comm_backoff=backoff), \
            rz.fault_scope(specs) as plan, obs.trace() as tracer:
        outs = mpi.run_ranks(body, nranks, timeout=timeout)
    return outs, plan, tracer


# =========================================================================
# The gray fault kinds
# =========================================================================

class TestGrayFaultKinds:
    def test_registered_with_matrix_rows(self):
        for kind in rchaos.GRAY_KINDS:
            assert kind in rz.FAULT_KINDS
            assert rz.FAULT_KINDS[kind].transient
            assert kind in rmatrix.COVERAGE

    def test_spec_validation(self):
        with pytest.raises(ValueError, match="p must be in"):
            rz.FaultSpec("flaky_link", p=1.5)
        with pytest.raises(ValueError, match="per_byte_s"):
            rz.FaultSpec("brownout", per_byte_s=-1.0)

    def test_seeded_draws_deterministic(self):
        draws = [_hash01(7, r, i) for r in range(3) for i in range(5)]
        again = [_hash01(7, r, i) for r in range(3) for i in range(5)]
        assert draws == again
        assert all(0.0 <= d < 1.0 for d in draws)
        # Different seeds give different storms.
        assert draws != [_hash01(8, r, i) for r in range(3)
                         for i in range(5)]

    def test_jitter_fires_with_recorded_sleep(self):
        spec = rz.FaultSpec("jitter", rank=1, op="Allreduce",
                            seconds=0.02, count=3, seed=5)

        def body(rank):
            out = None
            for _ in range(3):
                out = comm.Allreduce(jnp.arange(8.0) * (rank + 1),
                                     mpi.MPI_SUM)
            return np.asarray(out)

        _outs, plan, _t = _run_traced(body, 3, [spec])
        fires = [f for f in plan.fired if f.kind == "jitter"]
        assert len(fires) == 3
        want = [0.02 * _hash01(5, 1, i) for i in range(3)]
        assert [f.info["sleep_s"] for f in fires] == want

    def test_brownout_throttle_proportional_to_censused_bytes(self):
        spec = rz.FaultSpec("brownout", rank=0, op="Allreduce",
                            per_byte_s=1e-4, count=2)
        x_small = jnp.arange(16, dtype=jnp.float32)
        x_big = jnp.arange(256, dtype=jnp.float32)

        def body(rank):
            a = comm.Allreduce(x_small * (rank + 1), mpi.MPI_SUM)
            b = comm.Allreduce(x_big * (rank + 1), mpi.MPI_SUM)
            return np.asarray(a), np.asarray(b)

        _outs, plan, _t = _run_traced(body, 2, [spec])
        fires = [f for f in plan.fired if f.kind == "brownout"]
        assert len(fires) == 2
        assert fires[0].info["bytes"] == payload_nbytes(x_small)
        assert fires[1].info["bytes"] == payload_nbytes(x_big)
        for f in fires:
            assert f.info["sleep_s"] == pytest.approx(
                1e-4 * f.info["bytes"])

    def test_flaky_link_p0_never_fires_p1_always_drops(self):
        def body(rank):
            if rank == 0:
                comm.Wait(comm.Isend(jnp.arange(4.0), 1, 3))
                return None
            return np.asarray(comm.Wait(comm.Irecv(jnp.zeros(4), 0, 3)))

        never = rz.FaultSpec("flaky_link", rank=0, op="p2p", p=0.0,
                             count=10)
        outs, plan, _t = _run_traced(body, 2, [never])
        assert "flaky_link" not in plan.fired_kinds()
        np.testing.assert_array_equal(outs[1], np.arange(4.0))

        always = rz.FaultSpec("flaky_link", rank=0, op="p2p", p=1.0,
                              count=1)
        outs, plan, _t = _run_traced(body, 2, [always])
        assert "flaky_link" in plan.fired_kinds()   # dropped AND redelivered
        np.testing.assert_array_equal(outs[1], np.arange(4.0))

    def test_gray_matrix_cells_fast_subset(self):
        # One representative matrix cell per gray kind on (3,) — the
        # full sweep rides the slow lane via TestFaultMatrixFull.
        for kind, subsystem in [("slow_rank", "plain"),
                                ("jitter", "fused"),
                                ("brownout", "compressed"),
                                ("flaky_link", "overlap"),
                                ("flaky_link", "plain")]:
            rec = rmatrix.run_cell(kind, subsystem, nranks=3)
            assert rec["status"] == "ok", rec


# =========================================================================
# Registry-sync guards
# =========================================================================

class TestRegistryGuards:
    def test_degrade_guard_clean(self):
        assert degrade_problems() == []

    def test_unregistered_policy_fails(self):
        rdegrade.DEGRADE_POLICIES["ghost_policy"] = lambda c, r: {}
        try:
            problems = degrade_problems()
            assert problems and "ghost_policy" in " ".join(problems)
        finally:
            del rdegrade.DEGRADE_POLICIES["ghost_policy"]

    def test_gray_kind_without_chaos_row_fails(self):
        row = rchaos.CHAOS_COVERAGE.pop("jitter")
        try:
            problems = degrade_problems()
            assert problems and "jitter" in " ".join(problems)
        finally:
            rchaos.CHAOS_COVERAGE["jitter"] = row

    def test_standing_problems_includes_degrade(self):
        from mpi4torch_tpu.analyze.registry import standing_problems
        rdegrade.DEGRADE_POLICIES["ghost_policy"] = lambda c, r: {}
        try:
            assert any("degrade:" in p for p in standing_problems())
        finally:
            del rdegrade.DEGRADE_POLICIES["ghost_policy"]


# =========================================================================
# check_health: slow vs dead
# =========================================================================

class TestHealthArrivalLatency:
    def test_slow_rank_arrives_late_but_alive(self):
        def probe(rank):
            if rank == 2:
                time.sleep(0.2)
            return comm.check_health(timeout=5.0)

        reps = mpi.run_ranks(probe, 3, timeout=10.0)
        for rep in reps:
            assert rep.ok and not rep.missing
            assert set(rep.arrival_s) == {0, 1, 2}
            assert rep.arrival_s[2] >= 0.15
            assert rep.slow_ranks(0.1) == frozenset({2})
            assert rep.slow_ranks(10.0) == frozenset()

    def test_dead_rank_is_missing_not_slow(self):
        def probe(rank):
            if rank == 1:
                return None     # never probes: the dead/hung stand-in
            return comm.check_health(timeout=0.3)

        reps = mpi.run_ranks(probe, 3, timeout=5.0)
        for rank, rep in enumerate(reps):
            if rank == 1:
                continue
            assert not rep.ok
            assert rep.missing == frozenset({1})
            # The dead rank has NO arrival entry — slow and dead are
            # different answers now.
            assert 1 not in rep.arrival_s
            assert rep.slow_ranks(10.0) == frozenset()


# =========================================================================
# The detector
# =========================================================================

def _ev(rank, dur, wait, world=0, size=4, status="ok",
        channel="exchange"):
    from mpi4torch_tpu.obs.events import CommEvent

    return CommEvent(seq=0, rank=rank, world=world, world_size=size,
                     channel=channel, op="Allreduce",
                     duration_s=dur, wait_s=wait, status=status)


class TestDetector:
    def test_synthetic_positive_attribution(self):
        events = []
        for _ in range(4):
            events += [_ev(0, 0.1, 0.099), _ev(1, 0.1, 0.001),
                       _ev(2, 0.1, 0.098), _ev(3, 0.1, 0.097)]
        rep = rz.detect_slow_ranks(events, floor_s=0.01)
        assert rep.slow == frozenset({1})
        assert rep.stat(1).local_s > rep.stat(0).local_s
        assert rep.world_size == 4

    def test_quiet_world_flags_nobody(self):
        events = [_ev(r, 1e-4, 5e-5) for r in range(4)] * 3
        rep = rz.detect_slow_ranks(events, floor_s=0.01)
        assert rep.slow == frozenset()

    def test_failed_events_and_recv_channel_excluded(self):
        events = [_ev(0, 9.0, 0.0, status="DeadlockError"),
                  _ev(0, 9.0, 0.0, channel="p2p_recv"),
                  _ev(0, 1e-4, 0.0), _ev(0, 1e-4, 0.0),
                  _ev(1, 1e-4, 0.0), _ev(1, 1e-4, 0.0)]
        rep = rz.detect_slow_ranks(events, floor_s=0.01)
        assert rep.slow == frozenset()

    def test_world_selection_prefers_busiest(self):
        events = ([_ev(0, 0.2, 0.0, world=0, size=2),
                   _ev(1, 1e-4, 0.0, world=0, size=2)] * 3
                  + [_ev(0, 1e-4, 0.0, world=1, size=2)])
        rep = rz.detect_slow_ranks(events, floor_s=0.01)
        assert rep.world == 0 and rep.slow == frozenset({0})

    def test_no_tracer_reports_none(self):
        assert rz.GrayFailureDetector().report() is None

    def test_mode_b_end_to_end_detection_and_metrics(self):
        from mpi4torch_tpu.obs import metrics as ometrics

        def body(rank):
            out = None
            for _ in range(3):
                out = comm.Allreduce(jnp.arange(8.0) * (rank + 1),
                                     mpi.MPI_SUM)
            return np.asarray(out)

        spec = rz.FaultSpec("slow_rank", rank=1, op="Allreduce",
                            seconds=0.08, count=10)
        before = ometrics.snapshot()["counters"].get(
            "gray_failures_total", 0)
        _outs, plan, tracer = _run_traced(body, 4, [spec])
        rep = rz.GrayFailureDetector(tracer, floor_s=0.02).check()
        assert rep is not None and rep.slow == frozenset({1})
        assert "slow_rank" in plan.fired_kinds()
        after = ometrics.snapshot()["counters"]["gray_failures_total"]
        assert after == before + 1

    def test_escalation_typed_attributed_with_postmortem(self):
        def body(rank):
            for _ in range(3):
                comm.Allreduce(jnp.arange(8.0) * (rank + 1),
                               mpi.MPI_SUM)

        spec = rz.FaultSpec("slow_rank", rank=2, op="Allreduce",
                            seconds=0.08, count=10)
        _outs, _plan, tracer = _run_traced(body, 3, [spec])
        det = rz.GrayFailureDetector(tracer, floor_s=0.02)
        with pytest.raises(rz.SlowRankError) as ei:
            det.check(escalate=True)
        assert ei.value.ranks == frozenset({2})
        assert ei.value.report.slow == frozenset({2})
        pm = tracer.last_postmortem()
        assert pm is not None and pm["error"] == "SlowRankError"
        assert pm["failed_ranks"] == [2]

    def test_prometheus_exposition_of_gray_counters(self):
        from mpi4torch_tpu.obs import metrics as ometrics

        ometrics.inc("gray_failures_total",
                     help="slow ranks flagged")
        ometrics.inc('degrade_transitions_total{policy="codec_escalate"}',
                     help="degrade transitions")
        text = ometrics.prometheus_text()
        assert "mpi4torch_gray_failures_total " in text
        assert ('mpi4torch_degrade_transitions_total'
                '{policy="codec_escalate"}') in text
        # Label-carrying names keep bare-family TYPE headers.
        assert "# TYPE mpi4torch_degrade_transitions_total counter" \
            in text


# =========================================================================
# Degrade policies
# =========================================================================

class TestPerRankWireCensus:
    def test_totals_identical_across_candidates(self):
        # Same traffic, different concentration: every candidate's
        # TOTAL is 4(N-1)B (up to the per-rank integer rounding on
        # worlds that do not divide the payload).
        for n in (3, 4, 8):
            want = 4 * (n - 1) * (1 << 10)
            for algo in ("ring", "bidir", "tree"):
                t = rz.rank_wire_bytes(algo, n, 1 << 10)
                assert len(t) == n
                assert abs(sum(t) - want) <= n // 2, (algo, n)

    def test_tree_concentrates_on_root(self):
        t = rz.rank_wire_bytes("tree", 8, 1024, root=4)
        assert t[4] == 2 * 3 * 1024 * 2 // 2      # 2·log2(8)·B = 6144
        assert t[(4 + 1) % 8] == 2 * 1024          # odd-relative leaf
        assert max(t) == t[4]

    def test_one_rank_world_is_zero_wire(self):
        assert rz.rank_wire_bytes("ring", 1, 1024) == [0]

    def test_unknown_algorithm_typed(self):
        with pytest.raises(rz.DegradeError, match="no per-rank wire"):
            rz.rank_wire_bytes("warp", 4, 1024)

    def test_failover_unloads_slow_rank_deterministically(self):
        w1, table = rz.failover_schedule(3, 8, 1024)
        w2, _ = rz.failover_schedule(3, 8, 1024)
        assert w1 == w2
        assert table[w1][3] < table["ring"][3]
        # rhd only offered on power-of-two worlds.
        _w, table3 = rz.failover_schedule(0, 3, 1024)
        assert "rhd" not in table3


class TestDegradeController:
    def test_unknown_policy_typed(self):
        ctl = rz.DegradeController(n_ranks=2)
        with pytest.raises(rz.DegradeError, match="unknown degrade"):
            ctl.apply("warp_drive", consensus=False)

    def test_codec_escalate_epoch_fenced_and_reversible(self):
        ctl = rz.DegradeController(n_ranks=2)
        rep = rz.SlowRankReport(world=0, world_size=2, stats=(),
                                slow=frozenset({1}), baseline_s=0.0,
                                threshold=4.0, floor_s=0.01)
        tr = ctl.apply("codec_escalate", rep)
        assert tr.epoch == 1 == ctl.runtime.epoch
        assert getattr(mpi.config.default_compression(), "name",
                       None) == "q8"
        from mpi4torch_tpu.obs import metrics as ometrics
        counters = ometrics.snapshot()["counters"]
        assert counters[
            'degrade_transitions_total{policy="codec_escalate"}'] >= 1
        ctl.reset()
        assert mpi.config.default_compression() is None

    def test_schedule_failover_requires_report(self):
        ctl = rz.DegradeController(n_ranks=4)
        with pytest.raises(rz.DegradeError, match="SlowRankReport"):
            ctl.apply("schedule_failover", consensus=False)

    def test_spare_demote_without_spare_names_fallback(self):
        ctl = rz.DegradeController(n_ranks=2)
        rep = rz.SlowRankReport(world=0, world_size=2, stats=(),
                                slow=frozenset({1}), baseline_s=0.0,
                                threshold=4.0, floor_s=0.01)
        with pytest.raises(rz.DegradeError, match="planned elastic"):
            ctl.apply("spare_demote", rep, consensus=False, n_data=2)


# =========================================================================
# Chaos matrix: fast subset (tier-1) + full sweep (slow)
# =========================================================================

_FAST_CHAOS = [
    ("slow_rank", "plain"),      # degrade: schedule failover, lock-step
    ("jitter", "plain"),         # recover under the storm
    ("flaky_link", "overlap"),   # recover via redelivery
    ("flaky_link", "plain"),     # provably inert
]


class TestChaosFast:
    @pytest.mark.parametrize("kind,subsystem", _FAST_CHAOS)
    def test_cell(self, kind, subsystem):
        rec = rchaos.run_chaos_cell(kind, subsystem)
        assert rec["status"] == "ok", rec

    def test_storm_never_hangs(self):
        rec = rchaos.run_storm(1)
        assert rec["status"] == "ok", rec
        assert set(rec["fired"]) == set(rchaos.GRAY_KINDS)


@pytest.mark.slow
class TestChaosFull:
    @pytest.mark.parametrize("kind,subsystem",
                             list(rchaos.coverage_cells()))
    def test_cell(self, kind, subsystem):
        rec = rchaos.run_chaos_cell(kind, subsystem)
        assert rec["status"] == "ok", rec

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_storm(self, seed):
        rec = rchaos.run_storm(seed)
        assert rec["status"] == "ok", rec

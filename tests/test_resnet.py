"""ResNet-18 DP tests — BASELINE.md parity config #4.

Oracles, in the reference's test style (SURVEY.md §4 — analytic/single-rank
oracles + rank-conditional identity checks):

* eval-mode DP gradients == single-rank full-batch gradients (mean CE is
  linear in the batch partition once BN stats are frozen);
* lock-step: every rank's updated params are bit-identical after a step;
* the two DP recipes (per-param-grad Allreduce vs in-loss adjoint
  Allreduce) produce identical updates;
* training reduces the loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm
from mpi4torch_tpu.models import resnet as R

NR = 4
CFG = R.ResNetConfig(num_classes=10, stage_sizes=(1, 1), widths=(8, 16))
B_LOCAL = 2
B_GLOBAL = NR * B_LOCAL
HW = 8


def make_data(seed=0):
    rng = np.random.default_rng(seed)
    images = jnp.asarray(rng.standard_normal((B_GLOBAL, HW, HW, 3)))
    labels = jnp.asarray(rng.integers(0, CFG.num_classes, B_GLOBAL))
    return images, labels


def make_params():
    return R.init_resnet(jax.random.PRNGKey(0), CFG, dtype=jnp.float64)


def local_batch(images, labels, rank):
    start = jnp.asarray(rank) * B_LOCAL
    return (jax.lax.dynamic_slice_in_dim(images, start, B_LOCAL, 0),
            jax.lax.dynamic_slice_in_dim(labels, start, B_LOCAL, 0))


class TestForward:
    def test_shapes_and_state(self):
        params, state = make_params()
        images, _ = make_data()
        logits, new_state = R.forward(CFG, params, state, images, train=True)
        assert logits.shape == (B_GLOBAL, CFG.num_classes)
        # Train mode must move the running stats off their init.
        stem = new_state["stem"]["bn"]
        assert not np.allclose(np.asarray(stem["mean"]), 0.0)

    def test_eval_mode_uses_state(self):
        params, state = make_params()
        images, _ = make_data()
        logits, new_state = R.forward(CFG, params, state, images, train=False)
        chex_same = jax.tree.map(
            lambda a, b: np.array_equal(np.asarray(a), np.asarray(b)),
            state, new_state)
        assert all(jax.tree.leaves(chex_same))
        assert np.all(np.isfinite(np.asarray(logits)))

    def test_jit_compiles(self):
        params, state = make_params()
        images, _ = make_data()
        f = jax.jit(lambda p, s, x: R.forward(CFG, p, s, x, train=True))
        logits, _ = f(params, state, images)
        assert logits.shape == (B_GLOBAL, CFG.num_classes)


@pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
class TestDPGradParity:
    """Eval-mode BN makes mean-CE linear in the batch partition: the
    rank-averaged DP gradient must equal the single-rank full-batch
    gradient."""

    def _single_rank(self):
        params, state = make_params()
        images, labels = make_data()
        loss, grads = jax.value_and_grad(
            lambda p: R.local_loss(CFG, p, state, (images, labels),
                                   train=False)[0])(params)
        return params, state, images, labels, loss, grads

    def test_grad_recipe_matches_single_rank(self):
        params, state, images, labels, ref_loss, ref_grads = \
            self._single_rank()

        def body():
            batch = local_batch(images, labels, comm.rank)
            (loss, _), grads = jax.value_and_grad(
                lambda p: R.local_loss(CFG, p, state, batch, train=False),
                has_aux=True)(params)
            grads = jax.tree.map(
                lambda g: comm.Allreduce(g, mpi.MPI_SUM) / comm.size, grads)
            loss = comm.Allreduce(loss, mpi.MPI_SUM) / comm.size
            return loss, grads

        # run_spmd stacks outputs along a leading per-rank axis.
        loss, grads = mpi.run_spmd(body, nranks=NR)()
        np.testing.assert_allclose(np.asarray(loss), ref_loss, rtol=1e-12)
        for g, rg in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            got = np.asarray(g)
            for r in range(1, NR):  # allreduced grads are rank-identical
                np.testing.assert_array_equal(got[0], got[r])
            np.testing.assert_allclose(got[0], np.asarray(rg),
                                       rtol=1e-9, atol=1e-12)


@pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
class TestLockStep:
    def test_replicas_identical_and_recipes_agree(self):
        params, state = make_params()
        images, labels = make_data()

        def step_with(recipe):
            def body():
                batch = local_batch(images, labels, comm.rank)
                loss, new_p, new_s = recipe(comm, CFG, params, state, batch,
                                            lr=0.05)
                return loss, new_p
            return mpi.run_spmd(body, nranks=NR)()

        loss_g, params_g = step_with(R.dp_grad_train_step)
        loss_l, params_l = step_with(R.dp_loss_train_step)

        # run_spmd returns per-rank-stacked outputs; every rank identical.
        for leaf in jax.tree.leaves(params_g):
            arr = np.asarray(leaf)
            for r in range(1, NR):
                np.testing.assert_array_equal(arr[0], arr[r])

        np.testing.assert_allclose(np.asarray(loss_g), np.asarray(loss_l),
                                   rtol=1e-12)
        for a, b in zip(jax.tree.leaves(params_g), jax.tree.leaves(params_l)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-9, atol=1e-12)


@pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
class TestTraining:
    def test_loss_decreases(self):
        params, state = make_params()
        images, labels = make_data()

        def body():
            p, s = params, state
            losses = []
            for _ in range(4):
                batch = local_batch(images, labels, comm.rank)
                loss, p, s = R.dp_grad_train_step(comm, CFG, p, s, batch,
                                                  lr=0.05)
                losses.append(loss)
            return jnp.stack(losses)

        losses = np.asarray(mpi.run_spmd(body, nranks=NR)())
        first, last = losses[..., 0], losses[..., -1]
        assert np.all(last < first)

"""Fused dequant→accumulate→requant hop kernel tests (ops/quant_kernels).

The acceptance surface of the in-schedule quantization tentpole (ISSUE 6):
the Pallas TPU kernel and the pure-jnp fallback are BIT-equal in
interpret mode (every operand combination — deterministic and
stochastic rounding, with and without the residual output), the
power-of-two block-scale arithmetic is exact by construction, the shared
chunk/salt/key helpers are pure functions of the schedule, and the
``config.quant_hop_impl`` knob validates and participates in the
``run_spmd`` jit fingerprint so toggling it retraces.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu.ops import quant_kernels as qk

RNG = np.random.default_rng(17)


def _blocks(rows=300, block=256, scale=3.0):
    q = jnp.asarray(RNG.integers(-127, 128, (rows, block)), jnp.int8)
    s = qk.po2_scale(jnp.abs(jnp.asarray(
        RNG.standard_normal(rows), jnp.float32)) * 0.1 + 1e-3)
    m = jnp.asarray(RNG.standard_normal((rows, block)).astype(np.float32)
                    * scale)
    noise = jnp.asarray(RNG.random((rows, block), np.float32))
    return q, s, m, noise


class TestPo2Scale:
    def test_scale_is_power_of_two_and_brackets_amax(self):
        amax = jnp.abs(jnp.asarray(
            RNG.standard_normal(4096), jnp.float32)) * 100.0
        s = np.asarray(qk.po2_scale(amax), np.float64)
        a = np.asarray(amax, np.float64)
        assert (np.log2(s) == np.round(np.log2(s))).all()
        assert (127.0 * s >= a).all()
        nz = a > 127 * 2.0 ** -126
        assert (s[nz] <= 2.0 * a[nz] / 127.0).all()

    def test_zero_and_tiny_amax_clamp_to_smallest_normal(self):
        s = np.asarray(qk.po2_scale(jnp.asarray([0.0, 1e-40], jnp.float32)))
        assert (s == np.float32(2.0 ** -126)).all()

    def test_dequant_products_are_exact(self):
        # The whole point of the power-of-two scale: q * scale never
        # rounds, so FMA contraction cannot perturb the pipeline.
        q, s, _, _ = _blocks()
        prod32 = np.asarray(q, np.float32) * np.asarray(s)[:, None]
        prod64 = np.asarray(q, np.float64) * np.asarray(s, np.float64)[:, None]
        assert (prod32.astype(np.float64) == prod64).all()

    def test_requant_blocks_matches_codec_encode(self):
        # The hop_fused contract: requant_blocks on block-shaped data IS
        # BlockQ8Codec.encode, bit for bit.
        from mpi4torch_tpu.compress import get_codec

        codec = get_codec("q8")
        x = jnp.asarray(RNG.standard_normal((8, codec.block)), jnp.float32)
        q, s = qk.requant_blocks(x)
        payload, _ = codec.encode(x.reshape(-1))
        np.testing.assert_array_equal(np.asarray(q), np.asarray(payload["q"]))
        np.testing.assert_array_equal(np.asarray(s),
                                      np.asarray(payload["scale"]))

    def test_integer_blocks_roundtrip_exactly(self):
        x = jnp.asarray(RNG.integers(-60, 61, (4, 256)), jnp.float32)
        q, s = qk.requant_blocks(x)
        np.testing.assert_array_equal(
            np.asarray(q, np.float32) * np.asarray(s)[:, None],
            np.asarray(x))


class TestKernelVsFallback:
    @pytest.mark.parametrize("want_resid", [False, True])
    @pytest.mark.parametrize("stochastic", [False, True])
    def test_bit_equal_in_interpret_mode(self, want_resid, stochastic):
        # impl="pallas" off-TPU runs the kernel interpreted — the
        # equivalence surface the acceptance criteria name.
        q, s, m, noise = _blocks()
        nz = noise if stochastic else None
        a = qk.dequant_accum_requant(q, s, m, noise=nz,
                                     want_resid=want_resid, impl="jnp")
        b = qk.dequant_accum_requant(q, s, m, noise=nz,
                                     want_resid=want_resid, impl="pallas")
        for x, y in zip(a, b):
            if x is None:
                assert y is None
            else:
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_row_padding_is_inert(self):
        # 300 rows does not divide the 256-row tile: the kernel pads,
        # computes, slices — and non-tile row counts must not leak
        # padded rows into the outputs (shape + bit checks).
        q, s, m, _ = _blocks(rows=300)
        q2, s2, resid = qk.dequant_accum_requant(q, s, m, want_resid=True,
                                                 impl="pallas")
        assert q2.shape == (300, 256) and s2.shape == (300,)
        assert resid.shape == (300, 256)

    def test_non_tileable_block_takes_fallback(self):
        # Lane axis must tile to 128 for the kernel; other block sizes
        # fall back to jnp even under impl="pallas".
        assert not qk.hop_available(100)
        q = jnp.zeros((4, 100), jnp.int8)
        s = jnp.ones((4,), jnp.float32)
        m = jnp.ones((4, 100), jnp.float32)
        out = qk.dequant_accum_requant(q, s, m, impl="pallas")
        np.testing.assert_array_equal(
            np.asarray(out[0], np.float32) * np.asarray(out[1])[:, None],
            np.ones((4, 100), np.float32))

    def test_fused_hop_equals_decode_add_encode(self):
        # The fusion is an op-sequence identity, not an approximation:
        # one kernel pass == decode -> add -> encode through the codec.
        from mpi4torch_tpu.compress import get_codec

        codec = get_codec("q8")
        q, s, m, _ = _blocks(rows=8)
        q2, s2, _ = qk.dequant_accum_requant(q, s, m, impl="jnp")
        part = m + q.astype(jnp.float32) * s[:, None]
        payload, _ = codec.encode(part.reshape(-1))
        np.testing.assert_array_equal(np.asarray(q2),
                                      np.asarray(payload["q"]))
        np.testing.assert_array_equal(np.asarray(s2),
                                      np.asarray(payload["scale"]))


class TestScheduleHelpers:
    def test_schedule_key_is_pure_function_of_salt_hop_rank(self):
        a = qk.schedule_key(3, 2, 5)
        b = qk.schedule_key(3, 2, 5)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for other in (qk.schedule_key(4, 2, 5), qk.schedule_key(3, 1, 5),
                      qk.schedule_key(3, 2, 6)):
            assert not np.array_equal(np.asarray(a), np.asarray(other))

    def test_schedule_key_traced_rank_matches_python_rank(self):
        # The Mode A pipeline folds a traced lax.axis_index rank; the
        # eager oracle a Python int — same bits, or cross-mode parity
        # of q8_ef_hop would silently break.
        want = np.asarray(qk.hop_noise(qk.schedule_key(1, 2, 3), 4, 256))
        got = np.asarray(jax.jit(
            lambda r: qk.hop_noise(qk.schedule_key(1, 2, r), 4, 256))(3))
        np.testing.assert_array_equal(want, got)

    def test_chunk_blocks_layout(self):
        flat = jnp.arange(1000, dtype=jnp.float32)
        xcb, nb = qk.chunk_blocks(flat, 4, 256)
        assert xcb.shape == (4, nb, 256) and nb == 1
        np.testing.assert_array_equal(
            np.asarray(xcb).reshape(-1)[:1000], np.asarray(flat))
        assert (np.asarray(xcb).reshape(-1)[1000:] == 0).all()

    def test_ring_salt_distinct_per_round_and_channel(self):
        salts = {qk.ring_salt(r, k) for r in range(3) for k in range(2)}
        assert len(salts) == 6


class TestConfigKnob:
    def test_validates(self):
        with pytest.raises(ValueError, match="quant_hop_impl"):
            mpi.config.set_quant_hop_impl("nope")
        assert mpi.config.quant_hop_impl() == "auto"

    def test_knob_is_in_thresholds_fingerprint(self):
        # Part of the run_spmd jit cache key: toggling retraces instead
        # of silently reusing the other implementation's lowering.
        base = mpi.config.thresholds_fingerprint()
        mpi.config.set_quant_hop_impl("jnp")
        try:
            assert mpi.config.thresholds_fingerprint() != base
        finally:
            mpi.config.set_quant_hop_impl("auto")
        assert mpi.config.thresholds_fingerprint() == base

    def test_forced_impls_agree_end_to_end(self):
        # The full compressed allreduce under each forced implementation
        # produces identical bits (the interpret-mode kernel path runs
        # the real Pallas kernel body).
        data = jnp.asarray(RNG.standard_normal((4, 600)), jnp.float32)

        def fn(x):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(mpi.COMM_WORLD.rank + 0), 0, keepdims=False)
            return mpi.COMM_WORLD.Allreduce(t, mpi.MPI_SUM,
                                            compression="q8")

        outs = {}
        for impl in ("jnp", "pallas"):
            mpi.config.set_quant_hop_impl(impl)
            try:
                outs[impl] = np.asarray(
                    mpi.run_spmd(fn, nranks=4)(data))
            finally:
                mpi.config.set_quant_hop_impl("auto")
        np.testing.assert_array_equal(outs["jnp"], outs["pallas"])

"""Compressed-collectives subsystem tests (mpi4torch_tpu.compress).

Covers the acceptance surface of the subsystem: codec round-trip error
bounds, wire-byte accounting, bit-identical results across ranks, Mode A
(shard_map) vs Mode B (run_ranks) parity, AD transparency (``jax.grad``
through compressed Allreduce/Allgather on both backends), and
error-feedback convergence on the data-parallel regression recipe (the
shipped example's shape).  HLO-level evidence that the quantized path
emits int8-width transfers lives with the other census tests in
tests/test_hlo.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm, run_ranks
from mpi4torch_tpu.compress import (available_codecs, ef_allreduce, ef_init,
                                    get_codec)

NR = 8          # SPMD mesh width (conftest provides 8 virtual devices)
SIZES = [2, 5]  # eager rank counts (reference CI matrix subset)


@pytest.fixture(params=SIZES)
def nranks(request):
    return request.param


def _data(n, m=1000, seed=0, scale=3.0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, m)) * scale).astype(np.float32)


# =========================================================================
# Codec unit tests
# =========================================================================


class TestCodecs:
    def test_registry(self):
        assert {"q8", "q8_ef", "bf16", "bf16r"} <= set(available_codecs())
        assert get_codec(None) is None
        assert get_codec(False) is None
        assert get_codec("none") is None
        with pytest.raises(ValueError, match="available"):
            get_codec("no-such-codec")
        with pytest.raises(TypeError):
            get_codec(42)

    @pytest.mark.parametrize("name,bound", [("q8", 1e-2), ("bf16", 5e-3),
                                            ("bf16r", 1e-2)])
    def test_roundtrip_relative_error_bound(self, name, bound):
        codec = get_codec(name)
        x = jnp.asarray(_data(1, 4096)[0])
        rt = np.asarray(codec.roundtrip(x), np.float64)
        rel = np.linalg.norm(rt - np.asarray(x, np.float64)) \
            / np.linalg.norm(np.asarray(x, np.float64))
        assert rel <= bound, f"{name}: {rel}"

    def test_q8_per_block_error_bound(self):
        # Block-scaled contract: per-element error ≤ half an int8 step of
        # the block's absmax.
        codec = get_codec("q8")
        x = jnp.asarray(_data(1, 2048, seed=1)[0])
        rt = np.asarray(codec.roundtrip(x), np.float32)
        blocks = np.asarray(x).reshape(-1, codec.block)
        step = np.abs(blocks).max(axis=1) / 127.0
        err = np.abs(np.asarray(x) - rt).reshape(-1, codec.block)
        assert (err <= 0.5 * step[:, None] + 1e-7).all()

    @pytest.mark.parametrize("name", ["q8", "bf16", "bf16r", "q8_ef"])
    @pytest.mark.parametrize("shape", [(), (1,), (257,), (3, 5), (2, 3, 7)])
    def test_shapes_and_dtype_roundtrip(self, name, shape):
        codec = get_codec(name)
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal(shape), jnp.float64)
        rt = codec.roundtrip(x)
        assert rt.shape == x.shape
        assert rt.dtype == x.dtype
        np.testing.assert_allclose(np.asarray(rt), np.asarray(x),
                                   rtol=0, atol=0.05 * (1 + np.abs(
                                       np.asarray(x)).max()))

    def test_zeros_roundtrip_exact(self):
        for name in ("q8", "bf16"):
            rt = get_codec(name).roundtrip(jnp.zeros((300,)))
            assert (np.asarray(rt) == 0).all()

    def test_q8_wire_ratio_beats_3p5x(self):
        codec = get_codec("q8")
        shape = (1 << 18,)
        enc = codec.wire_bytes(shape, jnp.float32)
        assert (shape[0] * 4) / enc >= 3.5

    def test_bf16_wire_ratio_is_2x(self):
        codec = get_codec("bf16")
        assert codec.wire_bytes((4096,), jnp.float32) == 4096 * 2

    def test_bf16r_unbiased(self):
        # Stochastic rounding is unbiased: the mean over many keyed
        # roundtrips converges to x (round-to-nearest would not).
        codec = get_codec("bf16r")
        x = jnp.full((256,), 1.0 + 1.0 / 512.0, jnp.float32)  # mid-step
        acc = np.zeros(256, np.float64)
        n = 64
        for i in range(n):
            key = jax.random.PRNGKey(i)
            acc += np.asarray(codec.roundtrip(x, key), np.float64)
        bias = np.abs(acc / n - np.asarray(x, np.float64)).max()
        det_bias = np.abs(np.asarray(get_codec("bf16").roundtrip(x),
                                     np.float64) - np.asarray(
                                         x, np.float64)).max()
        assert bias < det_bias


# =========================================================================
# Mode B (eager thread-SPMD)
# =========================================================================


class TestEagerCompressed:
    def test_allreduce_value_and_bit_identity(self, nranks):
        data = _data(nranks)
        exact = data.sum(0)

        def body(rank):
            y = comm.Allreduce(jnp.asarray(data[rank]), mpi.MPI_SUM,
                               compression="q8")
            return np.asarray(y)

        res = run_ranks(body, nranks)
        for r in range(1, nranks):
            np.testing.assert_array_equal(res[r], res[0])
        rel = np.linalg.norm(res[0] - exact) / np.linalg.norm(exact)
        assert rel <= 1e-2

    def test_allreduce_grad(self, nranks):
        # AD transparency: the backward is a compressed Allreduce of the
        # cotangents; ones quantize exactly, so the gradient is exact.
        def body():
            x = jnp.asarray(_data(1)[0])
            g = jax.grad(lambda t: comm.Allreduce(
                t, mpi.MPI_SUM, compression="q8").sum())(x)
            assert (np.asarray(g) == comm.size).all()

        run_ranks(body, nranks)

    def test_q8_ef_tightens_error(self, nranks):
        data = _data(nranks, seed=3)
        exact = data.sum(0)

        def body(rank):
            x = jnp.asarray(data[rank])
            y = comm.Allreduce(x, mpi.MPI_SUM, compression="q8")
            y_ef = comm.Allreduce(x, mpi.MPI_SUM, compression="q8_ef")
            return np.asarray(y), np.asarray(y_ef)

        y, y_ef = run_ranks(body, nranks)[0]
        err = np.linalg.norm(y - exact)
        err_ef = np.linalg.norm(y_ef - exact)
        assert err_ef < 0.1 * err  # EF cancels the first-order error

    def test_non_sum_raises(self):
        def body():
            with pytest.raises(mpi.CommError, match="MPI_SUM only"):
                comm.Allreduce(jnp.ones(8), mpi.MPI_MAX, compression="q8")
            return True

        assert run_ranks(body, 2) == [True, True]

    def test_integer_tensors_fall_back_to_exact(self):
        # A scope-level codec must not corrupt integer payloads: the
        # facade degrades them to the exact path.
        def body():
            with mpi.config.compression_scope("q8"):
                y = comm.Allreduce(jnp.arange(8, dtype=jnp.int32),
                                   mpi.MPI_SUM)
            assert (np.asarray(y) == 2 * np.arange(8)).all()

        run_ranks(body, 2)

    def test_allgather_value_and_grad(self, nranks):
        data = _data(nranks, m=12, seed=4)

        def body(rank):
            x = jnp.asarray(data[rank])
            y = comm.Allgather(x, 0, compression="q8")
            g = jax.grad(lambda t: comm.Allgather(
                t, 0, compression="q8").sum())(x)
            return np.asarray(y), np.asarray(g)

        res = run_ranks(body, nranks)
        exact = np.concatenate(list(data))
        for y, g in res:
            assert y.shape == (nranks * 12,)
            assert np.linalg.norm(y - exact) <= 1e-2 * np.linalg.norm(exact)
            # adjoint of allgather with ones cotangents: every rank's
            # segment-sum = nranks (ones quantize exactly in q8)
            np.testing.assert_allclose(g, np.full(12, float(nranks)),
                                       atol=1e-6)

    def test_allgather_varying_lengths(self):
        # Eager compressed allgather keeps the per-rank-varying contract.
        def body(rank):
            x = jnp.ones((rank + 1,)) * (rank + 1.0)
            return np.asarray(comm.Allgather(x, 0, compression="bf16"))

        res = run_ranks(body, 3)
        expect = np.concatenate([np.full(r + 1, r + 1.0) for r in range(3)])
        np.testing.assert_allclose(res[0], expect, rtol=1e-2)

    def test_rejects_jit_like_exact_ops(self):
        def body():
            with pytest.raises(mpi.CommError, match="SPMD"):
                jax.jit(lambda t: comm.Allreduce(
                    t, mpi.MPI_SUM, compression="q8"))(jnp.ones(4))

        run_ranks(body, 2)


# =========================================================================
# Mode A (SPMD mesh)
# =========================================================================


class TestSpmdCompressed:
    def test_allreduce_value_and_bit_identity(self):
        data = _data(NR, seed=5)
        stacked = jnp.asarray(data)

        def fn(x):
            t = jax.lax.dynamic_index_in_dim(x, jnp.asarray(comm.rank + 0),
                                             0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM, compression="q8")

        out = np.asarray(mpi.run_spmd(fn, nranks=NR)(stacked))
        exact = data.sum(0)
        for r in range(1, NR):
            np.testing.assert_array_equal(out[r], out[0])
        rel = np.linalg.norm(out[0] - exact) / np.linalg.norm(exact)
        # The quantized ring re-encodes partial sums per hop, so the
        # single-round error grows ~sqrt(2n) of one codec step (q8_ef
        # cancels it — see test_q8_ef_cancels_ring_error).
        assert rel <= 2.5e-2

    @pytest.mark.parametrize("codec,bound", [("q8", 2.5e-2),
                                             ("q8_ef", 1e-3),
                                             ("bf16", 1e-2),
                                             ("bf16r", 1e-2)])
    def test_allreduce_codecs_close_to_exact(self, codec, bound):
        data = _data(1, seed=6)[0]

        def fn(x):
            return comm.Allreduce(x * (comm.rank + 1.0), mpi.MPI_SUM,
                                  compression=codec)

        out = np.asarray(mpi.run_spmd(fn, nranks=NR)(jnp.asarray(data)))
        exact = data * (NR * (NR + 1) / 2)
        rel = np.linalg.norm(out[0] - exact) / np.linalg.norm(exact)
        assert rel <= bound, f"{codec}: {rel}"

    def test_q8_ef_cancels_ring_error(self):
        # The EF round transfers the tracked per-hop residuals, whose
        # cross-rank sum is the single-round path's entire first-order
        # error — q8_ef must beat plain q8 by well over an order of
        # magnitude on the same data.
        data = _data(1, seed=13)[0]

        def fn(codec):
            return lambda x: comm.Allreduce(x * (comm.rank + 1.0),
                                            mpi.MPI_SUM, compression=codec)

        exact = data * (NR * (NR + 1) / 2)
        q8 = np.asarray(mpi.run_spmd(fn("q8"), nranks=NR)(
            jnp.asarray(data)))[0]
        ef = np.asarray(mpi.run_spmd(fn("q8_ef"), nranks=NR)(
            jnp.asarray(data)))[0]
        assert np.linalg.norm(ef - exact) < 0.1 * np.linalg.norm(q8 - exact)

    def test_allreduce_grad_end_to_end(self):
        def fn(x):
            return comm.Allreduce(x, mpi.MPI_SUM, compression="q8")

        g = jax.grad(lambda x: mpi.run_spmd(fn, nranks=NR)(x).sum())(
            jnp.ones(64))
        # ones cotangents quantize exactly; d(sum over ranks)/dx = NR^2
        assert (np.asarray(g) == NR * NR).all()

    def test_allreduce_grad_q8_ef(self):
        def fn(x):
            return comm.Allreduce(x, mpi.MPI_SUM, compression="q8_ef")

        g = jax.grad(lambda x: mpi.run_spmd(fn, nranks=4)(x).sum())(
            jnp.ones(32))
        # the EF residual round contributes f32-epsilon-level corrections
        np.testing.assert_allclose(np.asarray(g), 16.0, rtol=1e-6)

    def test_allgather_value_and_adjoint(self):
        data = _data(1, m=24, seed=7)[0]

        def fn(x):
            return comm.Allgather(x + comm.rank * 0.0, 0, compression="q8")

        out = np.asarray(mpi.run_spmd(fn, nranks=4)(jnp.asarray(data)))
        exact = np.concatenate([data] * 4)
        assert out.shape == (4, 96)
        assert np.linalg.norm(out[0] - exact) <= 1e-2 * np.linalg.norm(exact)

        g = jax.grad(lambda x: mpi.run_spmd(fn, nranks=4)(x).sum())(
            jnp.asarray(data))
        # adjoint: compressed reduce-scatter delivers each rank its
        # segment-sum of the ones cotangents (= nranks); the replicated
        # input then sums the per-rank grads: nranks * nranks = 16.
        np.testing.assert_allclose(np.asarray(g), 16 * np.ones(24),
                                   rtol=1e-5)

    def test_non_sum_raises_at_trace_time(self):
        def fn(x):
            return comm.Allreduce(x, mpi.MPI_MAX, compression="q8")

        with pytest.raises(mpi.CommError, match="MPI_SUM only"):
            mpi.run_spmd(fn, nranks=4)(jnp.ones(8))

    def test_compression_scope_applies_and_is_static_key(self):
        data = _data(1, seed=8)[0]

        def fn(x):
            return comm.Allreduce(x, mpi.MPI_SUM)

        runner = mpi.run_spmd(fn, nranks=4)
        exact = np.asarray(runner(jnp.asarray(data)))[0]
        with mpi.compression_scope("q8"):
            compressed = np.asarray(runner(jnp.asarray(data)))[0]
        after = np.asarray(runner(jnp.asarray(data)))[0]
        # The scope default is part of the jit cache key: toggling it
        # retraces instead of reusing the exact (or compressed) lowering.
        assert not np.array_equal(exact, compressed)
        np.testing.assert_array_equal(after, exact)
        assert np.linalg.norm(compressed - 4 * data) \
            <= 1e-2 * np.linalg.norm(4 * data)


# =========================================================================
# Mode A vs Mode B parity
# =========================================================================


class TestModeParity:
    @pytest.mark.parametrize("codec", ["q8", "q8_ef", "bf16"])
    def test_allreduce_parity(self, codec):
        n = 4
        data = _data(n, seed=9)
        exact = data.sum(0).astype(np.float64)

        def eager_body(rank):
            return np.asarray(comm.Allreduce(jnp.asarray(data[rank]),
                                             mpi.MPI_SUM,
                                             compression=codec))

        eager = run_ranks(eager_body, n)[0].astype(np.float64)

        stacked = jnp.asarray(data)

        def spmd_fn(x):
            t = jax.lax.dynamic_index_in_dim(x, jnp.asarray(comm.rank + 0),
                                             0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM, compression=codec)

        spmd = np.asarray(mpi.run_spmd(spmd_fn, nranks=n)(stacked))[0] \
            .astype(np.float64)

        norm = np.linalg.norm(exact)
        assert np.linalg.norm(eager - exact) <= 1e-2 * norm
        # Mode A's ring re-encodes partials per hop (~sqrt(2n) of one
        # codec step for single-round codecs; q8_ef cancels it), so
        # parity is within combined codec error, not bit equality.
        spmd_bound = 1e-3 if codec == "q8_ef" else 2e-2
        assert np.linalg.norm(spmd - exact) <= spmd_bound * norm
        assert np.linalg.norm(spmd - eager) <= 3e-2 * norm


# =========================================================================
# Error-feedback convergence (the acceptance-criteria training check)
# =========================================================================


def _dp_train(nranks, compression, steps=150, lr=0.1, stateful_ef=False):
    """Data-parallel polynomial regression (the shipped example's shape):
    returns the per-rank final global losses.  Noisy targets give a
    nonzero irreducible loss floor, so the fp32-vs-compressed comparison
    is a stable ratio rather than a race toward 0."""
    rng = np.random.default_rng(42)
    num = 512
    x_all = 2.0 * rng.random(num)
    gen = np.asarray([0.1, 1.0, -2.0])
    y_all = (gen[2] * x_all + gen[1]) * x_all + gen[0] \
        + 0.05 * rng.standard_normal(num)   # irreducible noise floor

    def body(rank):
        n = num // comm.size
        xs = jnp.asarray(x_all[rank * n:(rank + 1) * n])
        ys = jnp.asarray(y_all[rank * n:(rank + 1) * n])

        def local_loss(p):
            pred = (p[2] * xs + p[1]) * xs + p[0]
            return jnp.mean(jnp.square(ys - pred)) / comm.size

        params = jnp.zeros(3, jnp.float64)
        resid = ef_init(params)
        for _ in range(steps):
            g = jax.grad(local_loss)(params)
            if stateful_ef:
                g, resid = ef_allreduce(comm, g, resid,
                                        compression=compression)
            else:
                g = comm.Allreduce(g, mpi.MPI_SUM, compression=compression)
            params = params - lr * g
        return float(comm.Allreduce(local_loss(params), mpi.MPI_SUM))

    return run_ranks(body, nranks)


_FP32_BASELINE = {}


def _fp32_loss():
    # One fp32 training run shared by the comparison tests below.
    if "loss" not in _FP32_BASELINE:
        _FP32_BASELINE["loss"] = _dp_train(2, compression=False)[0]
    return _FP32_BASELINE["loss"]


class TestErrorFeedbackConvergence:
    def test_q8_ef_matches_fp32_within_2pct(self):
        fp32 = _fp32_loss()
        assert fp32 < 0.1  # the run actually converged to the noise floor
        ef = _dp_train(2, compression="q8_ef")[0]
        assert abs(ef - fp32) <= 0.02 * fp32

    def test_stateful_ef_matches_fp32_within_2pct(self):
        fp32 = _fp32_loss()
        ef = _dp_train(2, compression="q8", stateful_ef=True)[0]
        assert abs(ef - fp32) <= 0.02 * fp32

    def test_ef_init_zeros(self):
        tree = {"a": jnp.ones((3,)), "b": (jnp.ones((2, 2)),)}
        z = ef_init(tree)
        assert (np.asarray(z["a"]) == 0).all()
        assert (np.asarray(z["b"][0]) == 0).all()


class TestConfigSemantics:
    """Review-hardened config/facade contracts: process-wide defaults
    reach rank-threads, explicit misuse raises, internal exact-semantics
    collectives opt out of scope defaults, and ad-hoc codec objects work
    as defaults without registration."""

    def test_process_default_visible_in_rank_threads(self):
        data = _data(2, seed=20)
        exact = data.sum(0)

        def body(rank):
            return np.asarray(comm.Allreduce(jnp.asarray(data[rank]),
                                             mpi.MPI_SUM))

        mpi.config.set_default_compression("q8")
        try:
            res = run_ranks(body, 2)
        finally:
            mpi.config.set_default_compression(None)
        err = np.linalg.norm(res[0] - exact)
        assert 0 < err <= 1e-2 * np.linalg.norm(exact)  # lossy => engaged

    def test_scope_none_overrides_process_default(self):
        data = _data(2, seed=21)

        def body(rank):
            with mpi.compression_scope(None):
                return np.asarray(comm.Allreduce(jnp.asarray(data[rank]),
                                                 mpi.MPI_SUM))

        mpi.config.set_default_compression("q8")
        try:
            res = run_ranks(body, 2)
        finally:
            mpi.config.set_default_compression(None)
        np.testing.assert_array_equal(res[0], data.sum(0))  # exact path

    def test_non_sum_under_scope_degrades_to_exact(self):
        # A MAX reduction inside a gradient-compression scope never asked
        # for compression: it must run exactly, not raise (explicit
        # compression= on a non-sum op still raises in the backend).
        def body():
            t = jnp.ones(6) * (comm.rank + 1.0)
            with mpi.compression_scope("q8"):
                res = comm.Allreduce(t, mpi.MPI_MAX)
            assert (np.asarray(res) == comm.size).all()
            with pytest.raises(mpi.CommError, match="MPI_SUM only"):
                comm.Allreduce(t, mpi.MPI_MAX, compression="q8")
            return True

        assert run_ranks(body, 2) == [True, True]

    def test_ef_allreduce_stochastic_base_carries_zero_residual(self):
        def body(rank):
            x = jnp.asarray(_data(2, seed=24)[rank])
            y, r = ef_allreduce(comm, x, ef_init(x), compression="bf16r")
            return np.asarray(y), np.asarray(r)

        y, r = run_ranks(body, 2)[0]
        assert (r == 0).all()
        exact = _data(2, seed=24).sum(0)
        assert np.linalg.norm(y - exact) <= 1e-2 * np.linalg.norm(exact)

    def test_explicit_compression_on_ints_raises(self):
        def body():
            with pytest.raises(ValueError, match="floating"):
                comm.Allreduce(jnp.arange(8, dtype=jnp.int32), mpi.MPI_SUM,
                               compression="q8")
            return True

        assert run_ranks(body, 2) == [True, True]

    def test_packed_allgather_ignores_scope_and_rejects_explicit(self):
        def body(rank):
            x = jnp.zeros(4, jnp.float64).at[:rank + 1].set(rank + 1.0)
            with mpi.compression_scope("q8"):
                packed = comm.Allgather(x, 0, numelem=(1, 2))
            with pytest.raises(ValueError, match="packed"):
                comm.Allgather(x, 0, numelem=(1, 2), compression="q8")
            # no-compression spellings stay accepted on the packed path
            also = comm.Allgather(x, 0, numelem=(1, 2), compression="none")
            np.testing.assert_array_equal(np.asarray(also),
                                          np.asarray(packed))
            return np.asarray(packed)

        res = run_ranks(body, 2)
        # exact reassembly despite the active codec scope
        np.testing.assert_array_equal(res[0], [1.0, 2.0, 2.0])

    def test_adhoc_codec_object_as_scope_default(self):
        from mpi4torch_tpu.compress import BlockQ8Codec

        custom = BlockQ8Codec(name="my-q8", block=64)  # NOT registered
        data = _data(2, seed=22)

        def body(rank):
            with mpi.compression_scope(custom):
                return np.asarray(comm.Allreduce(jnp.asarray(data[rank]),
                                                 mpi.MPI_SUM))

        res = run_ranks(body, 2)
        exact = data.sum(0)
        err = np.linalg.norm(res[0] - exact)
        assert 0 < err <= 1e-2 * np.linalg.norm(exact)

    def test_bf16r_fresh_noise_per_call_eager(self):
        # The eager backend folds a per-rank call counter into the key:
        # two successive bf16r collectives on the same mid-step value
        # must round differently (a fixed key would repeat the error
        # and accumulate linear drift).
        x = jnp.full((512,), 1.0 + 1.0 / 512.0, jnp.float64)

        def body():
            a = comm.Allreduce(x, mpi.MPI_SUM, compression="bf16r")
            b = comm.Allreduce(x, mpi.MPI_SUM, compression="bf16r")
            return np.asarray(a), np.asarray(b)

        a, b = run_ranks(body, 2)[0]
        assert not np.array_equal(a, b)

    def test_eager_fold_once_path_value_and_identity(self, monkeypatch):
        # Above _FOLD_ONCE_MIN the compressed fold is computed once and
        # shared: values must match the every-rank fold path bit for bit
        # and stay identical across ranks.
        from mpi4torch_tpu.ops import eager as eager_mod

        data = _data(3, seed=25)
        exact = data.sum(0)

        def body(rank):
            return np.asarray(comm.Allreduce(jnp.asarray(data[rank]),
                                             mpi.MPI_SUM,
                                             compression="q8_ef"))

        lo = run_ranks(body, 3)           # every-rank fold (below gate)
        monkeypatch.setattr(eager_mod, "_FOLD_ONCE_MIN", 1)
        hi = run_ranks(body, 3)           # fold-once path
        for r in range(3):
            np.testing.assert_array_equal(hi[r], hi[0])
            np.testing.assert_array_equal(hi[r], lo[r])
        assert np.linalg.norm(hi[0] - exact) \
            <= 1e-3 * np.linalg.norm(exact)

    def test_allgather_ef_backward_not_downgraded(self):
        # The q8_ef Allgather adjoint must honor the EF round: its
        # gradient error on non-trivial cotangents is far below plain
        # q8's, in BOTH backends.
        data = _data(1, m=96, seed=26)[0]

        def spmd_grad(codec):
            def fn(x):
                return comm.Allgather(x, 0, compression=codec)
            return np.asarray(jax.grad(
                lambda x: jnp.sum(jnp.sin(3.0 * mpi.run_spmd(
                    fn, nranks=4)(x))))(jnp.asarray(data)))

        # exact adjoint of the same program for reference
        def exact_grad():
            def fn(x):
                return comm.Allgather(x, 0)
            return np.asarray(jax.grad(
                lambda x: jnp.sum(jnp.sin(3.0 * mpi.run_spmd(
                    fn, nranks=4)(x))))(jnp.asarray(data)))

        ref = exact_grad()
        err_q8 = np.linalg.norm(spmd_grad("q8") - ref)
        err_ef = np.linalg.norm(spmd_grad("q8_ef") - ref)
        assert err_ef < 0.2 * err_q8

        def eager_grad(codec):
            def body(rank):
                x = jnp.asarray(_data(2, m=24, seed=27)[rank])
                g = jax.grad(lambda t: jnp.sum(jnp.sin(3.0 * comm.Allgather(
                    t, 0, compression=codec))))(x)
                return np.asarray(g)
            return run_ranks(body, 2)[0]

        def eager_exact():
            def body(rank):
                x = jnp.asarray(_data(2, m=24, seed=27)[rank])
                g = jax.grad(lambda t: jnp.sum(jnp.sin(3.0 * comm.Allgather(
                    t, 0))))(x)
                return np.asarray(g)
            return run_ranks(body, 2)[0]

        ref_e = eager_exact()
        err_q8_e = np.linalg.norm(eager_grad("q8") - ref_e)
        err_ef_e = np.linalg.norm(eager_grad("q8_ef") - ref_e)
        assert err_ef_e < 0.2 * err_q8_e

    def test_ef_allreduce_uses_single_round_wire(self):
        # Cross-step EF replaces in-call EF: passing "q8_ef" must behave
        # exactly like "q8" inside ef_allreduce (same wire, same residual
        # accounting) — not transmit twice AND carry the full residual.
        data = _data(2, seed=23)

        def body(rank):
            x = jnp.asarray(data[rank])
            r0 = ef_init(x)
            y1, r1 = ef_allreduce(comm, x, r0, compression="q8")
            y2, r2 = ef_allreduce(comm, x, r0, compression="q8_ef")
            return np.asarray(y1), np.asarray(r1), np.asarray(y2), \
                np.asarray(r2)

        y1, r1, y2, r2 = run_ranks(body, 2)[0]
        np.testing.assert_array_equal(y1, y2)
        np.testing.assert_array_equal(r1, r2)

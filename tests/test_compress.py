"""Compressed-collectives subsystem tests (mpi4torch_tpu.compress).

Covers the acceptance surface of the subsystem: codec round-trip error
bounds, wire-byte accounting, bit-identical results across ranks, Mode A
(shard_map) vs Mode B (run_ranks) parity, AD transparency (``jax.grad``
through compressed Allreduce/Allgather on both backends), and
error-feedback convergence on the data-parallel regression recipe (the
shipped example's shape).  HLO-level evidence that the quantized path
emits int8-width transfers lives with the other census tests in
tests/test_hlo.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm, run_ranks
from mpi4torch_tpu.compress import (available_codecs, ef_allreduce, ef_init,
                                    get_codec)

NR = 8          # SPMD mesh width (conftest provides 8 virtual devices)
SIZES = [2, 5]  # eager rank counts (reference CI matrix subset)


@pytest.fixture(params=SIZES)
def nranks(request):
    return request.param


def _data(n, m=1000, seed=0, scale=3.0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, m)) * scale).astype(np.float32)


# =========================================================================
# Codec unit tests
# =========================================================================


class TestCodecs:
    def test_registry(self):
        assert {"q8", "q8_ef", "bf16", "bf16r"} <= set(available_codecs())
        assert get_codec(None) is None
        assert get_codec(False) is None
        assert get_codec("none") is None
        with pytest.raises(ValueError, match="available"):
            get_codec("no-such-codec")
        with pytest.raises(TypeError):
            get_codec(42)

    # q8's power-of-two block scales (block floating point — the price
    # of exact-by-construction dequantize arithmetic, see
    # ops/quant_kernels.po2_scale) widen the quantization step by up to
    # 2x vs the classic absmax/127 scale, hence the 1.6e-2 bound.
    @pytest.mark.parametrize("name,bound", [("q8", 1.6e-2), ("bf16", 5e-3),
                                            ("bf16r", 1e-2)])
    def test_roundtrip_relative_error_bound(self, name, bound):
        codec = get_codec(name)
        x = jnp.asarray(_data(1, 4096)[0])
        rt = np.asarray(codec.roundtrip(x), np.float64)
        rel = np.linalg.norm(rt - np.asarray(x, np.float64)) \
            / np.linalg.norm(np.asarray(x, np.float64))
        assert rel <= bound, f"{name}: {rel}"

    def test_q8_per_block_error_bound(self):
        # Block-floating-point contract: the scale is the smallest power
        # of two with 127*scale >= absmax (exact products, exact
        # division — ops/quant_kernels.po2_scale), so per-element error
        # is <= half that scale, which is at most one int8 step of the
        # block's absmax.
        codec = get_codec("q8")
        x = jnp.asarray(_data(1, 2048, seed=1)[0])
        payload, meta = codec.encode(x)
        scale = np.asarray(payload["scale"], np.float64)
        amax = np.abs(np.asarray(x)).reshape(-1, codec.block).max(axis=1)
        # the scale IS a power of two in (amax/127, 2*amax/127]
        assert (np.log2(scale) == np.round(np.log2(scale))).all()
        assert (127.0 * scale >= amax).all()
        assert (scale <= 2.0 * amax / 127.0 + 1e-12).all()
        rt = np.asarray(codec.decode(payload, meta), np.float32)
        err = np.abs(np.asarray(x) - rt).reshape(-1, codec.block)
        assert (err <= 0.5 * scale[:, None] + 1e-7).all()

    @pytest.mark.parametrize("name", ["q8", "bf16", "bf16r", "q8_ef"])
    @pytest.mark.parametrize("shape", [(), (1,), (257,), (3, 5), (2, 3, 7)])
    def test_shapes_and_dtype_roundtrip(self, name, shape):
        codec = get_codec(name)
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal(shape), jnp.float64)
        rt = codec.roundtrip(x)
        assert rt.shape == x.shape
        assert rt.dtype == x.dtype
        np.testing.assert_allclose(np.asarray(rt), np.asarray(x),
                                   rtol=0, atol=0.05 * (1 + np.abs(
                                       np.asarray(x)).max()))

    def test_zeros_roundtrip_exact(self):
        for name in ("q8", "bf16"):
            rt = get_codec(name).roundtrip(jnp.zeros((300,)))
            assert (np.asarray(rt) == 0).all()

    def test_q8_wire_ratio_beats_3p5x(self):
        codec = get_codec("q8")
        shape = (1 << 18,)
        enc = codec.wire_bytes(shape, jnp.float32)
        assert (shape[0] * 4) / enc >= 3.5

    def test_bf16_wire_ratio_is_2x(self):
        codec = get_codec("bf16")
        assert codec.wire_bytes((4096,), jnp.float32) == 4096 * 2

    def test_bf16r_unbiased(self):
        # Stochastic rounding is unbiased: the mean over many keyed
        # roundtrips converges to x (round-to-nearest would not).
        codec = get_codec("bf16r")
        x = jnp.full((256,), 1.0 + 1.0 / 512.0, jnp.float32)  # mid-step
        acc = np.zeros(256, np.float64)
        n = 64
        for i in range(n):
            key = jax.random.PRNGKey(i)
            acc += np.asarray(codec.roundtrip(x, key), np.float64)
        bias = np.abs(acc / n - np.asarray(x, np.float64)).max()
        det_bias = np.abs(np.asarray(get_codec("bf16").roundtrip(x),
                                     np.float64) - np.asarray(
                                         x, np.float64)).max()
        assert bias < det_bias


# =========================================================================
# Mode B (eager thread-SPMD)
# =========================================================================


class TestEagerCompressed:
    def test_allreduce_value_and_bit_identity(self, nranks):
        data = _data(nranks)
        exact = data.sum(0)

        def body(rank):
            y = comm.Allreduce(jnp.asarray(data[rank]), mpi.MPI_SUM,
                               compression="q8")
            return np.asarray(y)

        res = run_ranks(body, nranks)
        for r in range(1, nranks):
            np.testing.assert_array_equal(res[r], res[0])
        rel = np.linalg.norm(res[0] - exact) / np.linalg.norm(exact)
        # Mode B now folds through the quantized hop oracle
        # (constants.reduce_q8_hop) — BIT-identical to the Mode A
        # in-schedule pipeline, so it inherits that pipeline's per-hop
        # error compounding (~sqrt(2n) of one codec step) in exchange
        # for bitwise cross-mode parity.
        assert rel <= 2.5e-2

    def test_allreduce_grad(self, nranks):
        # AD transparency: the backward is a compressed Allreduce of the
        # cotangents; ones quantize exactly, so the gradient is exact.
        def body():
            x = jnp.asarray(_data(1)[0])
            g = jax.grad(lambda t: comm.Allreduce(
                t, mpi.MPI_SUM, compression="q8").sum())(x)
            assert (np.asarray(g) == comm.size).all()

        run_ranks(body, nranks)

    def test_q8_ef_tightens_error(self, nranks):
        data = _data(nranks, seed=3)
        exact = data.sum(0)

        def body(rank):
            x = jnp.asarray(data[rank])
            y = comm.Allreduce(x, mpi.MPI_SUM, compression="q8")
            y_ef = comm.Allreduce(x, mpi.MPI_SUM, compression="q8_ef")
            return np.asarray(y), np.asarray(y_ef)

        y, y_ef = run_ranks(body, nranks)[0]
        err = np.linalg.norm(y - exact)
        err_ef = np.linalg.norm(y_ef - exact)
        assert err_ef < 0.1 * err  # EF cancels the first-order error

    def test_non_sum_raises(self):
        def body():
            with pytest.raises(mpi.CommError, match="MPI_SUM only"):
                comm.Allreduce(jnp.ones(8), mpi.MPI_MAX, compression="q8")
            return True

        assert run_ranks(body, 2) == [True, True]

    def test_integer_tensors_fall_back_to_exact(self):
        # A scope-level codec must not corrupt integer payloads: the
        # facade degrades them to the exact path.
        def body():
            with mpi.config.compression_scope("q8"):
                y = comm.Allreduce(jnp.arange(8, dtype=jnp.int32),
                                   mpi.MPI_SUM)
            assert (np.asarray(y) == 2 * np.arange(8)).all()

        run_ranks(body, 2)

    def test_allgather_value_and_grad(self, nranks):
        data = _data(nranks, m=12, seed=4)

        def body(rank):
            x = jnp.asarray(data[rank])
            y = comm.Allgather(x, 0, compression="q8")
            g = jax.grad(lambda t: comm.Allgather(
                t, 0, compression="q8").sum())(x)
            return np.asarray(y), np.asarray(g)

        res = run_ranks(body, nranks)
        exact = np.concatenate(list(data))
        for y, g in res:
            assert y.shape == (nranks * 12,)
            assert np.linalg.norm(y - exact) <= 1e-2 * np.linalg.norm(exact)
            # adjoint of allgather with ones cotangents: every rank's
            # segment-sum = nranks (ones quantize exactly in q8)
            np.testing.assert_allclose(g, np.full(12, float(nranks)),
                                       atol=1e-6)

    def test_allgather_varying_lengths(self):
        # Eager compressed allgather keeps the per-rank-varying contract.
        def body(rank):
            x = jnp.ones((rank + 1,)) * (rank + 1.0)
            return np.asarray(comm.Allgather(x, 0, compression="bf16"))

        res = run_ranks(body, 3)
        expect = np.concatenate([np.full(r + 1, r + 1.0) for r in range(3)])
        np.testing.assert_allclose(res[0], expect, rtol=1e-2)

    def test_rejects_jit_like_exact_ops(self):
        def body():
            with pytest.raises(mpi.CommError, match="SPMD"):
                jax.jit(lambda t: comm.Allreduce(
                    t, mpi.MPI_SUM, compression="q8"))(jnp.ones(4))

        run_ranks(body, 2)


# =========================================================================
# Mode A (SPMD mesh)
# =========================================================================


class TestSpmdCompressed:
    def test_allreduce_value_and_bit_identity(self):
        data = _data(NR, seed=5)
        stacked = jnp.asarray(data)

        def fn(x):
            t = jax.lax.dynamic_index_in_dim(x, jnp.asarray(comm.rank + 0),
                                             0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM, compression="q8")

        out = np.asarray(mpi.run_spmd(fn, nranks=NR)(stacked))
        exact = data.sum(0)
        for r in range(1, NR):
            np.testing.assert_array_equal(out[r], out[0])
        rel = np.linalg.norm(out[0] - exact) / np.linalg.norm(exact)
        # The quantized ring re-encodes partial sums per hop, so the
        # single-round error grows ~sqrt(2n) of one codec step (q8_ef
        # cancels it — see test_q8_ef_cancels_ring_error).
        assert rel <= 2.5e-2

    @pytest.mark.parametrize("codec,bound", [("q8", 2.5e-2),
                                             ("q8_ef", 1e-3),
                                             ("bf16", 1e-2),
                                             ("bf16r", 1e-2)])
    def test_allreduce_codecs_close_to_exact(self, codec, bound):
        data = _data(1, seed=6)[0]

        def fn(x):
            return comm.Allreduce(x * (comm.rank + 1.0), mpi.MPI_SUM,
                                  compression=codec)

        out = np.asarray(mpi.run_spmd(fn, nranks=NR)(jnp.asarray(data)))
        exact = data * (NR * (NR + 1) / 2)
        rel = np.linalg.norm(out[0] - exact) / np.linalg.norm(exact)
        assert rel <= bound, f"{codec}: {rel}"

    def test_q8_ef_cancels_ring_error(self):
        # The EF round transfers the tracked per-hop residuals, whose
        # cross-rank sum is the single-round path's entire first-order
        # error — q8_ef must beat plain q8 by well over an order of
        # magnitude on the same data.
        data = _data(1, seed=13)[0]

        def fn(codec):
            return lambda x: comm.Allreduce(x * (comm.rank + 1.0),
                                            mpi.MPI_SUM, compression=codec)

        exact = data * (NR * (NR + 1) / 2)
        q8 = np.asarray(mpi.run_spmd(fn("q8"), nranks=NR)(
            jnp.asarray(data)))[0]
        ef = np.asarray(mpi.run_spmd(fn("q8_ef"), nranks=NR)(
            jnp.asarray(data)))[0]
        assert np.linalg.norm(ef - exact) < 0.1 * np.linalg.norm(q8 - exact)

    def test_allreduce_grad_end_to_end(self):
        def fn(x):
            return comm.Allreduce(x, mpi.MPI_SUM, compression="q8")

        g = jax.grad(lambda x: mpi.run_spmd(fn, nranks=NR)(x).sum())(
            jnp.ones(64))
        # ones cotangents quantize exactly; d(sum over ranks)/dx = NR^2
        assert (np.asarray(g) == NR * NR).all()

    def test_allreduce_grad_q8_ef(self):
        def fn(x):
            return comm.Allreduce(x, mpi.MPI_SUM, compression="q8_ef")

        g = jax.grad(lambda x: mpi.run_spmd(fn, nranks=4)(x).sum())(
            jnp.ones(32))
        # the EF residual round contributes f32-epsilon-level corrections
        np.testing.assert_allclose(np.asarray(g), 16.0, rtol=1e-6)

    def test_allgather_value_and_adjoint(self):
        data = _data(1, m=24, seed=7)[0]

        def fn(x):
            return comm.Allgather(x + comm.rank * 0.0, 0, compression="q8")

        out = np.asarray(mpi.run_spmd(fn, nranks=4)(jnp.asarray(data)))
        exact = np.concatenate([data] * 4)
        assert out.shape == (4, 96)
        assert np.linalg.norm(out[0] - exact) <= 1e-2 * np.linalg.norm(exact)

        g = jax.grad(lambda x: mpi.run_spmd(fn, nranks=4)(x).sum())(
            jnp.asarray(data))
        # adjoint: compressed reduce-scatter delivers each rank its
        # segment-sum of the ones cotangents (= nranks); the replicated
        # input then sums the per-rank grads: nranks * nranks = 16.
        np.testing.assert_allclose(np.asarray(g), 16 * np.ones(24),
                                   rtol=1e-5)

    def test_non_sum_raises_at_trace_time(self):
        def fn(x):
            return comm.Allreduce(x, mpi.MPI_MAX, compression="q8")

        with pytest.raises(mpi.CommError, match="MPI_SUM only"):
            mpi.run_spmd(fn, nranks=4)(jnp.ones(8))

    def test_compression_scope_applies_and_is_static_key(self):
        data = _data(1, seed=8)[0]

        def fn(x):
            return comm.Allreduce(x, mpi.MPI_SUM)

        runner = mpi.run_spmd(fn, nranks=4)
        exact = np.asarray(runner(jnp.asarray(data)))[0]
        with mpi.compression_scope("q8"):
            compressed = np.asarray(runner(jnp.asarray(data)))[0]
        after = np.asarray(runner(jnp.asarray(data)))[0]
        # The scope default is part of the jit cache key: toggling it
        # retraces instead of reusing the exact (or compressed) lowering.
        assert not np.array_equal(exact, compressed)
        np.testing.assert_array_equal(after, exact)
        assert np.linalg.norm(compressed - 4 * data) \
            <= 2.5e-2 * np.linalg.norm(4 * data)


# =========================================================================
# Mode A vs Mode B parity
# =========================================================================


class TestModeParity:
    @pytest.mark.parametrize("codec", ["q8", "q8_ef", "bf16"])
    def test_allreduce_parity(self, codec):
        n = 4
        data = _data(n, seed=9)
        exact = data.sum(0).astype(np.float64)

        def eager_body(rank):
            return np.asarray(comm.Allreduce(jnp.asarray(data[rank]),
                                             mpi.MPI_SUM,
                                             compression=codec))

        eager = run_ranks(eager_body, n)[0]

        stacked = jnp.asarray(data)

        def spmd_fn(x):
            t = jax.lax.dynamic_index_in_dim(x, jnp.asarray(comm.rank + 0),
                                             0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM, compression=codec)

        spmd = np.asarray(mpi.run_spmd(spmd_fn, nranks=n)(stacked))[0]

        norm = np.linalg.norm(exact)
        # The block-q8 family holds BITWISE cross-mode parity: Mode B
        # folds through constants.reduce_q8_hop, the bit-exact replica
        # of Mode A's in-schedule hop pipeline.  bf16 keeps the
        # rendezvous-codec fold (statistical parity — its pipeline
        # re-encodes per hop only in Mode A).
        if codec in ("q8", "q8_ef"):
            np.testing.assert_array_equal(spmd, eager)
        else:
            assert np.linalg.norm(spmd.astype(np.float64)
                                  - eager.astype(np.float64)) <= 3e-2 * norm
        spmd_bound = 1e-3 if codec == "q8_ef" else 2.5e-2
        assert np.linalg.norm(spmd.astype(np.float64) - exact) \
            <= spmd_bound * norm
        assert np.linalg.norm(eager.astype(np.float64) - exact) \
            <= 2.5e-2 * norm


# =========================================================================
# Error-feedback convergence (the acceptance-criteria training check)
# =========================================================================


def _dp_train(nranks, compression, steps=150, lr=0.1, stateful_ef=False):
    """Data-parallel polynomial regression (the shipped example's shape):
    returns the per-rank final global losses.  Noisy targets give a
    nonzero irreducible loss floor, so the fp32-vs-compressed comparison
    is a stable ratio rather than a race toward 0."""
    rng = np.random.default_rng(42)
    num = 512
    x_all = 2.0 * rng.random(num)
    gen = np.asarray([0.1, 1.0, -2.0])
    y_all = (gen[2] * x_all + gen[1]) * x_all + gen[0] \
        + 0.05 * rng.standard_normal(num)   # irreducible noise floor

    def body(rank):
        n = num // comm.size
        xs = jnp.asarray(x_all[rank * n:(rank + 1) * n])
        ys = jnp.asarray(y_all[rank * n:(rank + 1) * n])

        def local_loss(p):
            pred = (p[2] * xs + p[1]) * xs + p[0]
            return jnp.mean(jnp.square(ys - pred)) / comm.size

        params = jnp.zeros(3, jnp.float64)
        resid = ef_init(params)
        for _ in range(steps):
            g = jax.grad(local_loss)(params)
            if stateful_ef:
                g, resid = ef_allreduce(comm, g, resid,
                                        compression=compression)
            else:
                g = comm.Allreduce(g, mpi.MPI_SUM, compression=compression)
            params = params - lr * g
        return float(comm.Allreduce(local_loss(params), mpi.MPI_SUM))

    return run_ranks(body, nranks)


_FP32_BASELINE = {}


def _fp32_loss():
    # One fp32 training run shared by the comparison tests below.
    if "loss" not in _FP32_BASELINE:
        _FP32_BASELINE["loss"] = _dp_train(2, compression=False)[0]
    return _FP32_BASELINE["loss"]


class TestErrorFeedbackConvergence:
    def test_q8_ef_matches_fp32_within_2pct(self):
        fp32 = _fp32_loss()
        assert fp32 < 0.1  # the run actually converged to the noise floor
        ef = _dp_train(2, compression="q8_ef")[0]
        assert abs(ef - fp32) <= 0.02 * fp32

    def test_stateful_ef_matches_fp32_within_2pct(self):
        fp32 = _fp32_loss()
        ef = _dp_train(2, compression="q8", stateful_ef=True)[0]
        assert abs(ef - fp32) <= 0.02 * fp32

    def test_ef_init_zeros(self):
        tree = {"a": jnp.ones((3,)), "b": (jnp.ones((2, 2)),)}
        z = ef_init(tree)
        assert (np.asarray(z["a"]) == 0).all()
        assert (np.asarray(z["b"][0]) == 0).all()


class TestConfigSemantics:
    """Review-hardened config/facade contracts: process-wide defaults
    reach rank-threads, explicit misuse raises, internal exact-semantics
    collectives opt out of scope defaults, and ad-hoc codec objects work
    as defaults without registration."""

    def test_process_default_visible_in_rank_threads(self):
        data = _data(2, seed=20)
        exact = data.sum(0)

        def body(rank):
            return np.asarray(comm.Allreduce(jnp.asarray(data[rank]),
                                             mpi.MPI_SUM))

        mpi.config.set_default_compression("q8")
        try:
            res = run_ranks(body, 2)
        finally:
            mpi.config.set_default_compression(None)
        err = np.linalg.norm(res[0] - exact)
        # 2.5e-2: the Mode B hop oracle compounds per-hop error like the
        # Mode A pipeline (bitwise parity contract).
        assert 0 < err <= 2.5e-2 * np.linalg.norm(exact)  # lossy => engaged

    def test_scope_none_overrides_process_default(self):
        data = _data(2, seed=21)

        def body(rank):
            with mpi.compression_scope(None):
                return np.asarray(comm.Allreduce(jnp.asarray(data[rank]),
                                                 mpi.MPI_SUM))

        mpi.config.set_default_compression("q8")
        try:
            res = run_ranks(body, 2)
        finally:
            mpi.config.set_default_compression(None)
        np.testing.assert_array_equal(res[0], data.sum(0))  # exact path

    def test_non_sum_under_scope_degrades_to_exact(self):
        # A MAX reduction inside a gradient-compression scope never asked
        # for compression: it must run exactly, not raise (explicit
        # compression= on a non-sum op still raises in the backend).
        def body():
            t = jnp.ones(6) * (comm.rank + 1.0)
            with mpi.compression_scope("q8"):
                res = comm.Allreduce(t, mpi.MPI_MAX)
            assert (np.asarray(res) == comm.size).all()
            with pytest.raises(mpi.CommError, match="MPI_SUM only"):
                comm.Allreduce(t, mpi.MPI_MAX, compression="q8")
            return True

        assert run_ranks(body, 2) == [True, True]

    def test_ef_allreduce_stochastic_base_carries_zero_residual(self):
        def body(rank):
            x = jnp.asarray(_data(2, seed=24)[rank])
            y, r = ef_allreduce(comm, x, ef_init(x), compression="bf16r")
            return np.asarray(y), np.asarray(r)

        y, r = run_ranks(body, 2)[0]
        assert (r == 0).all()
        exact = _data(2, seed=24).sum(0)
        assert np.linalg.norm(y - exact) <= 1e-2 * np.linalg.norm(exact)

    def test_explicit_compression_on_ints_raises(self):
        def body():
            with pytest.raises(ValueError, match="floating"):
                comm.Allreduce(jnp.arange(8, dtype=jnp.int32), mpi.MPI_SUM,
                               compression="q8")
            return True

        assert run_ranks(body, 2) == [True, True]

    def test_packed_allgather_ignores_scope_and_rejects_explicit(self):
        def body(rank):
            x = jnp.zeros(4, jnp.float64).at[:rank + 1].set(rank + 1.0)
            with mpi.compression_scope("q8"):
                packed = comm.Allgather(x, 0, numelem=(1, 2))
            with pytest.raises(ValueError, match="packed"):
                comm.Allgather(x, 0, numelem=(1, 2), compression="q8")
            # no-compression spellings stay accepted on the packed path
            also = comm.Allgather(x, 0, numelem=(1, 2), compression="none")
            np.testing.assert_array_equal(np.asarray(also),
                                          np.asarray(packed))
            return np.asarray(packed)

        res = run_ranks(body, 2)
        # exact reassembly despite the active codec scope
        np.testing.assert_array_equal(res[0], [1.0, 2.0, 2.0])

    def test_adhoc_codec_object_as_scope_default(self):
        from mpi4torch_tpu.compress import BlockQ8Codec

        custom = BlockQ8Codec(name="my-q8", block=64)  # NOT registered
        data = _data(2, seed=22)

        def body(rank):
            with mpi.compression_scope(custom):
                return np.asarray(comm.Allreduce(jnp.asarray(data[rank]),
                                                 mpi.MPI_SUM))

        res = run_ranks(body, 2)
        exact = data.sum(0)
        err = np.linalg.norm(res[0] - exact)
        assert 0 < err <= 2.5e-2 * np.linalg.norm(exact)

    def test_bf16r_fresh_noise_per_call_eager(self):
        # The eager backend folds a per-rank call counter into the key:
        # two successive bf16r collectives on the same mid-step value
        # must round differently (a fixed key would repeat the error
        # and accumulate linear drift).
        x = jnp.full((512,), 1.0 + 1.0 / 512.0, jnp.float64)

        def body():
            a = comm.Allreduce(x, mpi.MPI_SUM, compression="bf16r")
            b = comm.Allreduce(x, mpi.MPI_SUM, compression="bf16r")
            return np.asarray(a), np.asarray(b)

        a, b = run_ranks(body, 2)[0]
        assert not np.array_equal(a, b)

    def test_eager_fold_once_path_value_and_identity(self, monkeypatch):
        # Above _FOLD_ONCE_MIN the compressed fold is computed once and
        # shared: values must match the every-rank fold path bit for bit
        # and stay identical across ranks.
        from mpi4torch_tpu.ops import eager as eager_mod

        data = _data(3, seed=25)
        exact = data.sum(0)

        def body(rank):
            return np.asarray(comm.Allreduce(jnp.asarray(data[rank]),
                                             mpi.MPI_SUM,
                                             compression="q8_ef"))

        lo = run_ranks(body, 3)           # every-rank fold (below gate)
        monkeypatch.setattr(eager_mod, "_FOLD_ONCE_MIN", 1)
        hi = run_ranks(body, 3)           # fold-once path
        for r in range(3):
            np.testing.assert_array_equal(hi[r], hi[0])
            np.testing.assert_array_equal(hi[r], lo[r])
        assert np.linalg.norm(hi[0] - exact) \
            <= 1e-3 * np.linalg.norm(exact)

    def test_allgather_ef_backward_not_downgraded(self):
        # The q8_ef Allgather adjoint must honor the EF round: its
        # gradient error on non-trivial cotangents is far below plain
        # q8's, in BOTH backends.
        data = _data(1, m=96, seed=26)[0]

        def spmd_grad(codec):
            def fn(x):
                return comm.Allgather(x, 0, compression=codec)
            return np.asarray(jax.grad(
                lambda x: jnp.sum(jnp.sin(3.0 * mpi.run_spmd(
                    fn, nranks=4)(x))))(jnp.asarray(data)))

        # exact adjoint of the same program for reference
        def exact_grad():
            def fn(x):
                return comm.Allgather(x, 0)
            return np.asarray(jax.grad(
                lambda x: jnp.sum(jnp.sin(3.0 * mpi.run_spmd(
                    fn, nranks=4)(x))))(jnp.asarray(data)))

        ref = exact_grad()
        err_q8 = np.linalg.norm(spmd_grad("q8") - ref)
        err_ef = np.linalg.norm(spmd_grad("q8_ef") - ref)
        assert err_ef < 0.2 * err_q8

        def eager_grad(codec):
            def body(rank):
                x = jnp.asarray(_data(2, m=24, seed=27)[rank])
                g = jax.grad(lambda t: jnp.sum(jnp.sin(3.0 * comm.Allgather(
                    t, 0, compression=codec))))(x)
                return np.asarray(g)
            return run_ranks(body, 2)[0]

        def eager_exact():
            def body(rank):
                x = jnp.asarray(_data(2, m=24, seed=27)[rank])
                g = jax.grad(lambda t: jnp.sum(jnp.sin(3.0 * comm.Allgather(
                    t, 0))))(x)
                return np.asarray(g)
            return run_ranks(body, 2)[0]

        ref_e = eager_exact()
        err_q8_e = np.linalg.norm(eager_grad("q8") - ref_e)
        err_ef_e = np.linalg.norm(eager_grad("q8_ef") - ref_e)
        assert err_ef_e < 0.2 * err_q8_e

    def test_ef_allreduce_uses_single_round_wire(self):
        # Cross-step EF replaces in-call EF: passing "q8_ef" must behave
        # exactly like "q8" inside ef_allreduce (same wire, same residual
        # accounting) — not transmit twice AND carry the full residual.
        data = _data(2, seed=23)

        def body(rank):
            x = jnp.asarray(data[rank])
            r0 = ef_init(x)
            y1, r1 = ef_allreduce(comm, x, r0, compression="q8")
            y2, r2 = ef_allreduce(comm, x, r0, compression="q8_ef")
            return np.asarray(y1), np.asarray(r1), np.asarray(y2), \
                np.asarray(r2)

        y1, r1, y2, r2 = run_ranks(body, 2)[0]
        np.testing.assert_array_equal(y1, y2)
        np.testing.assert_array_equal(r1, r2)


# =========================================================================
# In-schedule quantization on the multipath tier (ISSUE 6)
# =========================================================================


def _hop_codec_pairs():
    """Every (codec-capable algorithm × block-q8 codec) pair the
    registries compose — computed from the LIVE registries, so a new
    registration extends this matrix automatically (the registry-sync
    guard in test_tune.py asserts the enumeration rules)."""
    from mpi4torch_tpu import tune

    pairs = []
    for algo in tune.available_algorithms():
        if not tune.get_algorithm(algo).codec_capable:
            continue
        for name in available_codecs():
            codec = get_codec(name)
            if algo not in codec.algorithms:
                continue
            base = codec.base()
            if not getattr(base, "hop_fused", False):
                continue
            pairs.append((algo, name))
    return pairs


class TestInScheduleMultipath:
    """The tentpole contract: the block-q8 family rides ring/bidir/torus
    through the fused in-schedule pipeline, Mode A and Mode B are
    BIT-identical per (algorithm × codec) — values and gradients, every
    world shape the acceptance criteria name — and the eager oracle
    (constants.reduce_q8_hop) is the single source of Mode B's fold."""

    # (1,), (3,), (8,) flat worlds plus the (2,4) torus factorization
    # of 8 (config.hier_group_size pins inner=4 → grid (outer=2,
    # inner=4)).
    WORLDS = [(1, None), (3, None), (8, None), (8, 4)]

    @pytest.mark.parametrize("algo,codec", _hop_codec_pairs())
    @pytest.mark.parametrize("world,group", WORLDS)
    def test_mode_a_b_bitwise_values_and_grads(self, algo, codec, world,
                                               group):
        from mpi4torch_tpu.runtime import CommError

        if algo == "torus" and world == 3:
            pytest.skip("torus needs a factorable world")
        if group is not None and algo != "torus":
            # config.hier_group_size only enters the torus channel
            # striping — for ring/bidir the group-pinned world runs the
            # exact same computation as the unpinned (8,) cell above.
            pytest.skip("group pin is torus-only; cell duplicates "
                        "the unpinned world")
        data = _data(world, m=700, seed=31)
        stacked = jnp.asarray(data)

        def run(a=algo, c=codec):
            def spmd_fn(x):
                t = jax.lax.dynamic_index_in_dim(
                    x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
                y, g = jax.value_and_grad(lambda v: jnp.vdot(
                    comm.Allreduce(v, mpi.MPI_SUM, compression=c,
                                   algorithm=a), v))(t)
                return y, g

            ya, ga = mpi.run_spmd(spmd_fn, nranks=world)(stacked)

            def eager_body():
                t = jnp.asarray(data[comm.rank])
                y, g = jax.value_and_grad(lambda v: jnp.vdot(
                    comm.Allreduce(v, mpi.MPI_SUM, compression=c,
                                   algorithm=a), v))(t)
                return np.asarray(y), np.asarray(g)

            eb = run_ranks(eager_body, world)
            return np.asarray(ya), np.asarray(ga), eb

        if group is None:
            ya, ga, eb = run()
        else:
            mpi.config.set_hier_group_size(group)
            try:
                ya, ga, eb = run()
            finally:
                mpi.config.set_hier_group_size(None)
        for r in range(world):
            np.testing.assert_array_equal(ya[r], eb[r][0],
                                          err_msg=f"value rank {r}")
            np.testing.assert_array_equal(ga[r], eb[r][1],
                                          err_msg=f"grad rank {r}")

    @pytest.mark.parametrize("algo,codec", _hop_codec_pairs())
    def test_deterministic_mode_bitwise(self, algo, codec):
        # The acceptance criterion's "including deterministic_mode"
        # leg: the compressed pipeline is deterministic by construction
        # (fixed associations, schedule-keyed noise), so the parity
        # contract holds under the flag too.
        data = _data(4, m=500, seed=37)
        stacked = jnp.asarray(data)

        def spmd_fn(x):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM, compression=codec,
                                  algorithm=algo)

        with mpi.config.deterministic_mode(True):
            a_out = np.asarray(mpi.run_spmd(spmd_fn, nranks=4)(stacked))
        b_out = run_ranks(
            lambda: np.asarray(comm.Allreduce(
                jnp.asarray(data[comm.rank]), mpi.MPI_SUM,
                compression=codec, algorithm=algo)), 4)
        for r in range(4):
            np.testing.assert_array_equal(a_out[r], b_out[r])

    def test_oracle_is_the_mode_b_fold(self):
        # constants.reduce_q8_hop called directly reproduces the eager
        # backend's result — the oracle IS the fold, not a lookalike.
        from mpi4torch_tpu import constants as C

        data = _data(4, seed=41)
        want = np.asarray(C.reduce_q8_hop(
            [jnp.asarray(d) for d in data], block=256, algorithm="bidir"))
        got = run_ranks(
            lambda: np.asarray(comm.Allreduce(
                jnp.asarray(data[comm.rank]), mpi.MPI_SUM,
                compression="q8", algorithm="bidir")), 4)[0]
        np.testing.assert_array_equal(want, got)

    def test_values_close_to_exact_on_multipath(self):
        data = _data(NR, seed=43)
        exact = data.sum(0)
        stacked = jnp.asarray(data)
        for algo in ("bidir", "torus"):
            def spmd_fn(x, a=algo):
                t = jax.lax.dynamic_index_in_dim(
                    x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
                return comm.Allreduce(t, mpi.MPI_SUM, compression="q8",
                                      algorithm=a)

            out = np.asarray(mpi.run_spmd(spmd_fn, nranks=NR)(stacked))
            for r in range(1, NR):
                np.testing.assert_array_equal(out[r], out[0])
            rel = np.linalg.norm(out[0] - exact) / np.linalg.norm(exact)
            assert rel <= 2.5e-2, f"{algo}: {rel}"

    def test_explicit_bidir_q8_composes_and_tree_q8_raises(self):
        # The lifted pin: explicit (bidir, q8) now composes; an
        # explicitly incompatible pair still raises via the shared
        # reconcile path.
        data = jnp.ones((NR, 64), jnp.float32)

        def ok(x):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM, compression="q8",
                                  algorithm="bidir")

        out = np.asarray(mpi.run_spmd(ok, nranks=NR)(data))
        np.testing.assert_array_equal(out[0], np.full(64, float(NR)))

        def bad(x):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM, compression="q8",
                                  algorithm="tree")

        with pytest.raises(ValueError, match="cannot carry this codec"):
            mpi.run_spmd(bad, nranks=NR)(data)

    def test_scope_codec_with_explicit_tree_degrades_codec(self):
        # One-explicit-half degrade: scope codec yields to the explicit
        # non-composing algorithm (exact wire), mirroring the facade's
        # standard rule — no fork from _reconcile_codec_algorithm.
        data = jnp.ones((NR, 32), jnp.float32)

        def fn(x):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            with mpi.config.compression_scope("q8"):
                return comm.Allreduce(t, mpi.MPI_SUM, algorithm="tree")

        out = np.asarray(mpi.run_spmd(fn, nranks=NR)(data))
        np.testing.assert_array_equal(out[0], np.full(32, float(NR)))

    def test_explicit_torus_q8_on_prime_world_raises(self):
        data = jnp.ones((5, 16), jnp.float32)

        def fn(x):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM, compression="q8",
                                  algorithm="torus")

        with pytest.raises(mpi.CommError, match="factorization"):
            mpi.run_spmd(fn, nranks=5)(data)

    def test_auto_selection_picks_compressed_bidir_past_crossover(self):
        # The composed win: under an active compression scope, auto
        # algorithm selection reaches the bandwidth tier for the
        # compressed payload (codec-aware select_auto) — the two wire
        # wins multiply.
        from mpi4torch_tpu import tune

        data = _data(4, m=1 << 16, seed=47)  # 256 KiB of f32
        stacked = jnp.asarray(data)
        mpi.config.set_bandwidth_crossover_bytes(1 << 16)
        try:
            def fn(x):
                t = jax.lax.dynamic_index_in_dim(
                    x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
                return comm.Allreduce(t, mpi.MPI_SUM, compression="q8")

            auto = np.asarray(mpi.run_spmd(fn, nranks=4)(stacked))

            def pinned(x):
                t = jax.lax.dynamic_index_in_dim(
                    x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
                return comm.Allreduce(t, mpi.MPI_SUM, compression="q8",
                                      algorithm="bidir")

            want = np.asarray(mpi.run_spmd(pinned, nranks=4)(stacked))
            # Mode B resolves auto through the SAME codec-aware selector
            # (compress/eager._resolve_algorithm) — auto-selected
            # compressed traffic keeps the bitwise cross-mode contract,
            # not just explicitly-pinned algorithms.
            eager_auto = run_ranks(
                lambda: np.asarray(comm.Allreduce(
                    jnp.asarray(data[comm.rank]), mpi.MPI_SUM,
                    compression="q8")), 4)
        finally:
            mpi.config.set_bandwidth_crossover_bytes(None)
        np.testing.assert_array_equal(auto, want)
        for r in range(4):
            np.testing.assert_array_equal(eager_auto[r], auto[r])


class TestPerHopErrorFeedback:
    """q8_ef_hop: stochastic per-hop rounding + per-hop error feedback
    at single-round wire cost."""

    def test_wire_cost_is_single_round(self):
        codec = get_codec("q8_ef_hop")
        assert codec.ef_rounds == 1
        fp32 = (1 << 16) * 4
        assert fp32 / codec.wire_bytes((1 << 16,), jnp.float32) >= 3.5

    def test_unbiased_over_repeated_salts(self):
        # Stochastic rounding is unbiased: averaging the oracle's output
        # over many schedule salts converges to the exact sum, where
        # round-to-nearest q8 keeps a fixed deterministic bias.
        from mpi4torch_tpu import constants as C

        data = _data(4, m=512, seed=53)
        exact = data.sum(0).astype(np.float64)
        vals = [jnp.asarray(d) for d in data]
        acc = np.zeros(512, np.float64)
        trials = 24
        for salt in range(trials):
            out = C._sim_quant_ring(
                [jnp.asarray(v, jnp.float32) for v in vals], 256, None, 1,
                1000 + salt, True, True, False)[0]
            acc += np.asarray(out, np.float64)
        stoch_bias = np.abs(acc / trials - exact).mean()
        det = np.asarray(C.reduce_q8_hop(vals, block=256), np.float64)
        det_bias = np.abs(det - exact).mean()
        assert stoch_bias < det_bias

    @pytest.mark.slow
    def test_convergence_no_worse_than_one_shot_q8_ef(self):
        # The acceptance regression: the per-hop EF loss trajectory ends
        # no worse than the two-round q8_ef codec's (which pays 2x the
        # wire), and both land within 2% of fp32 — at HALF q8_ef's wire
        # cost for the hop variant.  (`slow`: two 150-step DP trainings;
        # runs in `make test` and the TPU-manual lane — the tier-1
        # budget keeps only the bitwise/census contracts.)
        fp32 = _fp32_loss()
        ef_hop = _dp_train(2, compression="q8_ef_hop")[0]
        ef = _dp_train(2, compression="q8_ef")[0]
        assert abs(ef_hop - fp32) <= max(abs(ef - fp32), 0.02 * fp32)

    @pytest.mark.slow
    def test_hop_ef_beats_plain_q8_on_training(self):
        fp32 = _fp32_loss()
        ef_hop = _dp_train(2, compression="q8_ef_hop")[0]
        q8 = _dp_train(2, compression="q8")[0]
        assert abs(ef_hop - fp32) <= abs(q8 - fp32) + 0.01 * fp32

"""mpi4torch_tpu.reshard (ISSUE 9): sharding -> sharding redistribution.

Pins the tentpole contracts: the planner picks the documented strategy
per transition shape and never auto-picks the gather baseline; every
planned transition is BITWISE equal to the gather-then-slice oracle (and
the numpy assemble-and-slice reference) on both backends, including
``deterministic_mode``; the VJP is the reverse plan (cotangents
redistribute spec' -> spec, replication adjoints sum); the censused peak
live bytes of planned lowerings sit strictly below the gather
baseline's; plans compose with the tune cache's transition dimension,
the resilience fault grammar, and the compress wide-hop codec; and the
step-kind registry stays in sync with both executors, the adjoint
closure, and this file's coverage (the PR 4/6/7 guard pattern).

The heavyweight cross-world transition matrix rides the slow lane and
`make reshard-smoke`; tier-1 keeps the representative cells.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import reshard as rs
from mpi4torch_tpu.reshard.executor import _EAGER_EXEC, _SPMD_EXEC
from mpi4torch_tpu.runtime import CommError

NR = 8
G = (16, 8)
FULL = np.random.default_rng(0).standard_normal(G)


def np_shard(lay, r, arr=None):
    return np.asarray(rs.slice_shard(FULL if arr is None else arr, lay, r))


L8 = rs.layout((8,), 0, None)
L24 = rs.layout((2, 4), 0, 1)
L42 = rs.layout((4, 2), 0, 1)

# (name, from, to, expected auto strategy)
CASES = [
    ("migrate", L8, L24, "alltoall"),
    ("migrate-T", L8, L42, "alltoall"),
    ("axis-move", L8, rs.layout((8,), None, 0), "alltoall"),
    ("coarsen", L8, rs.layout((2, 4), (0,), None), "allgather"),
    ("refine", rs.layout((2, 4), (0,), None), L8, "local"),
    ("relabel", L8, rs.layout((2, 4), (0, 1), None), "local"),
    ("block-permute", rs.layout((2, 4), (0, 1), None),
     rs.layout((2, 4), (1, 0), None), "permute"),
    ("replicate", L8, rs.layout((8,), None, None), "allgather"),
    ("slice", rs.layout((8,), None, None), L8, "local"),
    ("zero-to-tp", L8, rs.layout((2, 4), None, 1), "alltoall"),
]


class TestLayout:
    def test_block_maps_row_major(self):
        assert [L8.block(r) for r in range(3)] == [(0, 0), (1, 0), (2, 0)]
        # (2,4): rank 5 = coords (1, 1) -> row-half 1, col-quarter 1
        assert L24.block(5) == (1, 1)
        assert rs.layout((2, 4), (1, 0), None).block(5) == (3, 0)

    def test_shard_and_global_shapes_roundtrip(self):
        assert L24.shard_shape(G) == (8, 2)
        assert L24.global_shape((8, 2)) == G
        with pytest.raises(CommError, match="not divisible"):
            L8.shard_shape((15, 8))

    def test_validation(self):
        with pytest.raises(CommError, match="at most one"):
            rs.Layout((2, 4), ((0,), (0,)))
        with pytest.raises(CommError, match="mesh has"):
            rs.Layout((2,), ((3,),))
        with pytest.raises(CommError, match="Layout"):
            rs.executor.as_layout("nope")

    def test_replica_axes(self):
        assert rs.layout((2, 4), None, 1).replica_axes == (0,)
        assert L24.replica_axes == ()


class TestPlanner:
    @pytest.mark.parametrize("name,fl,tl,want",
                             [(c[0], c[1], c[2], c[3]) for c in CASES])
    def test_auto_strategy(self, name, fl, tl, want):
        plan = rs.plan_reshard(fl, tl, G, np.float64)
        assert plan.strategy == want, name
        assert plan.strategy != "gather"

    def test_identity_transition_is_empty_plan(self):
        plan = rs.plan_reshard(L8, L8, G, np.float64)
        assert plan.steps == () and plan.wire_bytes == 0

    def test_gather_is_explicit_only_and_costs_full_array(self):
        plan = rs.plan_reshard(L8, L24, G, np.float64, strategy="gather")
        assert plan.strategy == "gather"
        assert plan.peak_bytes >= NR * math.prod(L8.shard_shape(G)) * 8
        auto = rs.plan_reshard(L8, L24, G, np.float64)
        assert auto.peak_bytes < plan.peak_bytes
        assert auto.wire_bytes < plan.wire_bytes

    def test_explicit_inapplicable_strategy_raises(self):
        with pytest.raises(CommError, match="cannot serve"):
            rs.plan_reshard(L8, L24, G, np.float64, strategy="permute")

    def test_world_size_change_raises(self):
        with pytest.raises(CommError, match="world size"):
            rs.plan_reshard(L8, rs.layout((4,), 0, None), G, np.float64)

    def test_plans_cached_per_transition(self):
        a = rs.plan_reshard(L8, L24, G, np.float32)
        b = rs.plan_reshard(L8, L24, G, np.float32)
        assert a is b
        c = rs.plan_reshard(L8, L24, G, np.float64)
        assert c is not a

    def test_adjoint_is_reverse_program_in_grammar(self):
        plan = rs.plan_reshard(L8, L24, G, np.float64)
        adj = plan.adjoint()
        assert adj.in_shape == plan.out_shape
        assert adj.out_shape == plan.in_shape
        assert all(s.kind in rs.STEP_KINDS for s in adj.steps)
        # adjoint of adjoint restores the forward step kinds
        assert [s.kind for s in adj.adjoint().steps] == \
            [s.kind for s in plan.steps]

    def test_adjoint_kind_pairing(self):
        gplan = rs.plan_reshard(L8, L24, G, np.float64, strategy="gather")
        kinds = [s.kind for s in gplan.adjoint().steps]
        assert kinds == ["pad", "reduce_scatter"]

    def test_strategy_knob_and_validation(self):
        mpi.config.set_default_reshard_strategy("rounds")
        try:
            plan = rs.plan_reshard(L8, L24, G, np.float64)
            assert plan.strategy == "rounds"
            fp = mpi.config.thresholds_fingerprint()
            assert "rounds" in fp
        finally:
            mpi.config.set_default_reshard_strategy(None)
        assert rs.plan_reshard(L8, L24, G, np.float64).strategy == \
            "alltoall"
        with pytest.raises(ValueError, match="reshard strategy"):
            mpi.config.set_default_reshard_strategy("warp")

    def test_tune_cache_winner_overrides(self):
        # The autotuner cache key grows a transition dimension: a
        # recorded winner for THIS transition redirects auto selection
        # (to the gather baseline here — the only way gather is ever
        # auto-picked), without touching other transitions or the
        # collective-algorithm keys.
        from mpi4torch_tpu import tune

        plan = rs.plan_reshard(L8, L24, G, np.float64)
        nbytes = math.prod(plan.in_shape) * 8
        key = tune.make_key("reshard", np.float64, nbytes, NR,
                            transition=plan.transition)
        assert "transition=" in key
        assert key != tune.make_key("reshard", np.float64, nbytes, NR)
        tune.record("reshard", np.float64, nbytes, NR, "gather",
                    persist=False, transition=plan.transition)
        try:
            assert rs.plan_reshard(L8, L24, G,
                                   np.float64).strategy == "gather"
            # a different transition still auto-selects normally
            assert rs.plan_reshard(L8, L42, G,
                                   np.float64).strategy == "alltoall"
        finally:
            tune.clear()
        assert rs.plan_reshard(L8, L24, G, np.float64).strategy == \
            "alltoall"

    def test_recording_unknown_strategy_raises(self):
        from mpi4torch_tpu import tune

        with pytest.raises(ValueError, match="unknown reshard strategy"):
            tune.record("reshard", np.float64, 1024, NR, "warp",
                        persist=False, transition="x->y")


class TestRegistrySync:
    def test_step_kinds_match_executors_and_coverage(self):
        # The structural guard: a step kind is only real if BOTH
        # executors serve it, its adjoint stays in the grammar, and the
        # CASES table (fwd + adjoint + gather baseline) exercises it.
        kinds = set(rs.STEP_KINDS)
        assert set(_SPMD_EXEC) == kinds
        assert set(_EAGER_EXEC) == kinds
        exercised = set()
        for _, fl, tl, _w in CASES:
            for strat in (None, "gather"):
                plan = rs.plan_reshard(fl, tl, G, np.float64, strat)
                exercised |= {s.kind for s in plan.steps}
                exercised |= {s.kind for s in plan.adjoint().steps}
        plan = rs.plan_reshard(L8, L24, G, np.float64, "rounds")
        exercised |= {s.kind for s in plan.steps}
        exercised |= {s.kind for s in plan.adjoint().steps}
        assert exercised == kinds, (
            f"coverage drift: {sorted(exercised)} vs {sorted(kinds)}")


def eager_ranks(fn, n=NR):
    return mpi.run_ranks(fn, n)


class TestEagerParity:
    @pytest.mark.parametrize("name,fl,tl",
                             [(c[0], c[1], c[2]) for c in CASES])
    def test_bitwise_vs_oracles(self, name, fl, tl):
        def body():
            c = mpi.COMM_WORLD
            x = jnp.asarray(np_shard(fl, c.rank))
            return (c.Reshard(x, fl, tl),
                    rs.gather_then_slice(c, x, fl, tl))

        out = eager_ranks(body)
        for r in range(NR):
            want = np_shard(tl, r)
            got, oracle = out[r]
            assert np.array_equal(np.asarray(got), want), (name, r)
            assert np.array_equal(np.asarray(oracle), want), (name, r)

    def test_rounds_strategy_bitwise(self):
        def body():
            c = mpi.COMM_WORLD
            x = jnp.asarray(np_shard(L8, c.rank))
            return c.Reshard(x, L8, L24, strategy="rounds")

        out = eager_ranks(body)
        for r in range(NR):
            assert np.array_equal(np.asarray(out[r]), np_shard(L24, r))

    def test_deterministic_mode_bitwise(self):
        def body():
            c = mpi.COMM_WORLD
            with mpi.config.deterministic_mode(True):
                x = jnp.asarray(np_shard(L8, c.rank))
                return c.Reshard(x, L8, L24)

        out = eager_ranks(body)
        for r in range(NR):
            assert np.array_equal(np.asarray(out[r]), np_shard(L24, r))

    def test_pytree_and_rule_driven_specs(self):
        tree = {"w": FULL, "b": FULL[:, 0]}
        rules_from = [(r"w", L8), (r"b", rs.layout((8,), 0))]
        rules_to = [(r"w", L24), (r"b", rs.layout((2, 4), (0, 1)))]
        froms = rs.match_partition_rules(rules_from, tree)
        tos = rs.match_partition_rules(rules_to, tree)

        def body():
            c = mpi.COMM_WORLD
            shards = rs.shard_of(tree, froms, c.rank)
            return c.Reshard(shards, froms, tos)

        out = eager_ranks(body)
        for r in range(NR):
            assert np.array_equal(np.asarray(out[r]["w"]),
                                  np_shard(L24, r))
            assert np.array_equal(
                np.asarray(out[r]["b"]),
                np_shard(rs.layout((2, 4), (0, 1)), r,
                         arr=FULL[:, 0]))


class TestSpmdParity:
    def _spmd(self, fl, tl, strategy=None, det=False):
        shard = fl.shard_shape(G)
        starts = np.asarray([[b * s for b, s in zip(fl.block(r), shard)]
                             for r in range(NR)])

        def body():
            c = mpi.COMM_WORLD
            row = jnp.asarray(starts)[jnp.asarray(c.rank + 0)]
            x = jax.lax.dynamic_slice(
                jnp.asarray(FULL), (row[0], row[1]), shard)
            with mpi.config.deterministic_mode(det):
                return c.Reshard(x, fl, tl, strategy=strategy)

        return np.asarray(mpi.run_spmd(body, nranks=NR)())

    def test_migration_bitwise_all_ranks(self):
        out = self._spmd(L8, L24)
        for r in range(NR):
            assert np.array_equal(out[r], np_shard(L24, r))

    def test_deterministic_mode_migration(self):
        out = self._spmd(L8, L24, det=True)
        for r in range(NR):
            assert np.array_equal(out[r], np_shard(L24, r))

    @pytest.mark.slow
    @pytest.mark.parametrize("name,fl,tl",
                             [(c[0], c[1], c[2]) for c in CASES])
    def test_full_matrix_bitwise(self, name, fl, tl):
        out = self._spmd(fl, tl)
        for r in range(NR):
            assert np.array_equal(out[r], np_shard(tl, r)), (name, r)

    @pytest.mark.slow
    def test_rounds_strategy_spmd(self):
        out = self._spmd(L8, L24, strategy="rounds")
        for r in range(NR):
            assert np.array_equal(out[r], np_shard(L24, r))


class TestCensus:
    def _lowered(self, fl, tl, strategy=None, compression=None,
                 grad=False):
        from jax.sharding import Mesh, PartitionSpec as P

        from mpi4torch_tpu._compat import shard_map

        mesh = Mesh(np.asarray(jax.devices()[:NR]), ("w",))
        c = mpi.comm_from_mesh(mesh, "w")

        def f(a):
            out = c.Reshard(a, fl, tl, strategy=strategy,
                            compression=compression)
            return jnp.sum(out)

        # value_and_grad keeps the forward live (plain grad would DCE
        # it: sum's cotangent is primal-independent).
        prog = jax.value_and_grad(f) if grad else f
        fn = shard_map(prog, mesh=mesh, in_specs=P(), out_specs=P(),
                       check_vma=False)
        x = jnp.zeros(fl.shard_shape(G), jnp.float32)
        return jax.jit(fn).lower(x).as_text()

    def _counts(self, txt):
        return {k: txt.count(f"stablehlo.{k}")
                for k in ("all_to_all", "all_gather", "reduce_scatter",
                          "collective_permute", "all_reduce")}

    def test_alltoall_plan_is_one_all_to_all(self):
        got = self._counts(self._lowered(L8, L24))
        assert got["all_to_all"] == 1
        assert got["all_gather"] == 0 and got["all_reduce"] == 0

    def test_allgather_plan_is_one_all_gather(self):
        got = self._counts(self._lowered(
            L8, rs.layout((2, 4), (0,), None)))
        assert got["all_gather"] == 1 and got["all_to_all"] == 0

    def test_permute_plan_is_one_collective_permute(self):
        got = self._counts(self._lowered(
            rs.layout((2, 4), (0, 1), None),
            rs.layout((2, 4), (1, 0), None)))
        assert got["collective_permute"] == 1

    def test_local_plan_has_no_collectives(self):
        got = self._counts(self._lowered(
            rs.layout((2, 4), (0,), None), L8))
        assert all(v == 0 for v in got.values())

    def test_rounds_plan_is_chunk_permutes(self):
        txt = self._lowered(L8, L24, strategy="rounds")
        got = self._counts(txt)
        assert got["collective_permute"] >= 2
        assert got["all_to_all"] == 0

    def test_backward_adds_the_adjoint_exchange(self):
        got = self._counts(self._lowered(L8, L24, grad=True))
        assert got["all_to_all"] == 2        # forward + reverse plan

    def test_gather_adjoint_is_reduce_scatter(self):
        got = self._counts(self._lowered(L8, L24, strategy="gather",
                                         grad=True))
        assert got["all_gather"] == 1
        assert got["reduce_scatter"] == 1

    def test_peak_live_bytes_bounded_vs_gather(self):
        # THE acceptance inequality: the planned (8,)->(2,4) migration
        # must lower with strictly less peak live bytes than the
        # gather-everything baseline, by the same estimator.
        planned = rs.peak_live_bytes(self._lowered(L8, L24))
        gathered = rs.peak_live_bytes(self._lowered(L8, L24,
                                                    strategy="gather"))
        assert 0 < planned < gathered

    def test_named_scopes_in_lowering(self):
        from mpi4torch_tpu._compat import lowered_text
        from jax.sharding import Mesh, PartitionSpec as P

        from mpi4torch_tpu._compat import shard_map

        mesh = Mesh(np.asarray(jax.devices()[:NR]), ("w",))
        c = mpi.comm_from_mesh(mesh, "w")
        fn = shard_map(lambda a: c.Reshard(a, L8, L24), mesh=mesh,
                       in_specs=P(), out_specs=P(), check_vma=False)
        txt = lowered_text(
            jax.jit(fn).lower(jnp.zeros(L8.shard_shape(G), jnp.float32)),
            debug_info=True)
        assert "mpi4torch.Reshard" in txt
        assert "mpi4torch.Reshard.alltoall" in txt

    def test_compressed_wide_hop_ships_int8(self):
        import re

        txt = self._lowered(L8, L24, strategy="gather", compression="q8")
        assert re.search(r"all_gather.*xi8>", txt)

    def test_codec_without_wide_hop_raises(self):
        with pytest.raises(ValueError, match="wide full-world gather"):
            self._lowered(L8, L24, compression="q8")


class TestGrads:
    def test_vjp_redistributes_cotangents_bitwise(self):
        w = np.random.default_rng(1).standard_normal(
            (NR,) + L24.shard_shape(G))

        def body():
            c = mpi.COMM_WORLD
            x = jnp.asarray(np_shard(L8, c.rank))
            wr = jnp.asarray(w)[c.rank]
            return jax.grad(
                lambda v: jnp.vdot(c.Reshard(v, L8, L24), wr))(x)

        g = eager_ranks(body)
        wfull = np.zeros(G)
        sh = L24.shard_shape(G)
        for r in range(NR):
            blk = L24.block(r)
            wfull[tuple(slice(b * s, (b + 1) * s)
                        for b, s in zip(blk, sh))] = w[r]
        for r in range(NR):
            assert np.array_equal(np.asarray(g[r]),
                                  np_shard(L8, r, arr=wfull))

    def test_replication_adjoint_sums_cotangents(self):
        # sharded -> replicated: the adjoint reduce-scatters (sums) the
        # per-rank cotangents — grads-tested under deterministic_mode so
        # the fold order matches the eager oracle bitwise.
        tl = rs.layout((8,), None, None)
        w = np.random.default_rng(2).standard_normal((NR,) + G)

        def body():
            c = mpi.COMM_WORLD
            with mpi.config.deterministic_mode(True):
                x = jnp.asarray(np_shard(L8, c.rank))
                wr = jnp.asarray(w)[c.rank]
                return jax.grad(
                    lambda v: jnp.vdot(c.Reshard(v, L8, tl), wr))(x)

        g = eager_ranks(body)
        acc = w[0]
        for r in range(1, NR):
            acc = acc + w[r]
        for r in range(NR):
            assert np.array_equal(np.asarray(g[r]),
                                  np_shard(L8, r, arr=acc))

    def test_block_permutation_grads_ride_inverse(self):
        lay = rs.layout((8,), 0, None)
        perm = tuple(np.random.default_rng(3).permutation(16).tolist())

        def body():
            c = mpi.COMM_WORLD
            x = jnp.asarray(np_shard(lay, c.rank))
            wr = jnp.full_like(x, c.rank + 1.0)
            return jax.grad(lambda v: jnp.vdot(
                rs.reshard_blocks(c, v, lay, 0, perm), wr))(x)

        g = eager_ranks(body)
        wfull = np.concatenate(
            [np.full((2, G[1]), r + 1.0) for r in range(NR)])
        inv = np.empty(16, int)
        inv[list(perm)] = np.arange(16)
        for r in range(NR):
            assert np.array_equal(np.asarray(g[r]),
                                  wfull[inv][r * 2:(r + 1) * 2])


class TestScenarios:
    def test_zero3_to_tp_handoff(self):
        from mpi4torch_tpu.parallel import (zero3_shard_params,
                                            zero3_to_tp)

        params = {"w": jnp.asarray(FULL),
                  "v": jnp.asarray(FULL[:10, :6])}   # 10 rows: unaligned
        tp = {"w": rs.layout((2, 4), None, 1),
              "v": rs.layout((2, 4), 0, None)}

        def body():
            c = mpi.COMM_WORLD
            shards = zero3_shard_params(c, params)
            return zero3_to_tp(c, shards, params, tp)

        out = eager_ranks(body)
        for r in range(NR):
            for k in params:
                assert np.array_equal(
                    np.asarray(out[r][k]),
                    np_shard(tp[k], r, arr=np.asarray(params[k]))), (k, r)

    def test_moe_rebalance_and_assignment(self):
        from mpi4torch_tpu.parallel import (balanced_assignment,
                                            rebalance_experts)

        E = 16
        stack = np.random.default_rng(4).standard_normal((E, 4))
        loads = list(range(E))
        perm = balanced_assignment(loads, NR)
        assert sorted(perm) == list(range(E))
        totals = [sum(loads[e] for e in perm[r * 2:(r + 1) * 2])
                  for r in range(NR)]
        assert max(totals) - min(totals) <= max(loads) // 2 + 1

        def body():
            c = mpi.COMM_WORLD
            mine = jnp.asarray(stack[c.rank * 2:(c.rank + 1) * 2])
            return rebalance_experts(c, {"w": mine}, perm)

        out = eager_ranks(body)
        want = stack[list(perm)]
        for r in range(NR):
            assert np.array_equal(np.asarray(out[r]["w"]),
                                  want[r * 2:(r + 1) * 2])

        with pytest.raises(ValueError, match="not divisible"):
            balanced_assignment(list(range(9)), NR)


class TestRules:
    def test_paths_and_matching(self):
        tree = {"layer": {"w": np.zeros((8, 8)), "b": np.zeros((8,))},
                "step": np.zeros(())}
        paths = rs.tree_paths(tree)
        assert paths["layer"]["w"] == "layer/w"
        lays = rs.match_partition_rules(
            [(r"layer/w", L24), (r".*", rs.layout((2, 4), 0))], tree)
        assert lays["layer"]["w"] is L24
        assert lays["layer"]["b"].factors == (2,)
        # scalars never partition: replicated on the first rule's mesh
        assert lays["step"].spec == ()
        assert lays["step"].mesh == (2, 4)

    def test_no_match_and_ndim_mismatch_raise(self):
        with pytest.raises(CommError, match="no partition rule"):
            rs.match_partition_rules([(r"w", L24)],
                                     {"x": np.zeros((4, 4))})
        with pytest.raises(CommError, match="axis layout"):
            rs.match_partition_rules([(r".*", L24)],
                                     {"x": np.zeros((4, 4, 4))})


class TestErrors:
    def test_hier_comm_raises(self):
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:NR]).reshape(2, 4),
                    ("a", "b"))
        c = mpi.comm_from_mesh(mesh, ("a", "b"))
        with pytest.raises(CommError, match="flat communicator"):
            rs.execute_plan(c, rs.plan_reshard(L8, L24, G, np.float32),
                            jnp.zeros(L8.shard_shape(G)))

    def test_world_size_mismatch_raises(self):
        def body():
            c = mpi.COMM_WORLD
            x = jnp.zeros(rs.layout((4,), 0, None).shard_shape(G))
            return c.Reshard(x, rs.layout((4,), 0, None),
                             rs.layout((2, 2), 0, 1))

        with pytest.raises(CommError, match="spans 4 ranks"):
            eager_ranks(body, n=3)

    def test_wrong_shard_shape_raises(self):
        # Facade path: the implied global shape must divide under the
        # target layout.
        def body():
            return mpi.COMM_WORLD.Reshard(jnp.zeros((3, 3)), L8, L24)

        with pytest.raises(CommError, match="not divisible"):
            eager_ranks(body)
        # Executor path: a plan only serves shards of its own shape.
        plan = rs.plan_reshard(L8, L24, G, np.float32)
        with pytest.raises(CommError, match="expects"):
            def body2():
                return rs.execute_plan(mpi.COMM_WORLD, plan,
                                       jnp.zeros((3, 3), jnp.float32))

            eager_ranks(body2)

    def test_spec_tree_structure_mismatch(self):
        def body():
            c = mpi.COMM_WORLD
            return c.Reshard({"a": jnp.zeros((2, 8))}, {"b": L8}, L24)

        with pytest.raises(CommError, match="matching the state tree"):
            eager_ranks(body)


class TestFaultComposition:
    def test_rank_death_during_reshard_is_attributed(self):
        # The Mode B executor rides World.exchange — the resilience
        # chokepoint — so the PR 7 fault grammar covers reshard traffic
        # with zero reshard-specific hooks.
        from mpi4torch_tpu.resilience import FaultSpec, fault_scope

        with fault_scope([FaultSpec("rank_death", rank=2,
                                    op="Reshard")]):
            def body():
                c = mpi.COMM_WORLD
                x = jnp.asarray(np_shard(L8, c.rank))
                return c.Reshard(x, L8, L24)

            with pytest.raises(mpi.RankFailedError) as ei:
                mpi.run_ranks(body, NR, timeout=20.0)
        assert 2 in ei.value.ranks

    @pytest.mark.slow
    def test_delay_fault_recovers_with_retries(self):
        from mpi4torch_tpu.resilience import FaultSpec, fault_scope

        mpi.config.set_comm_retries(3)
        try:
            with fault_scope([FaultSpec("delay", rank=1, op="Reshard",
                                        seconds=0.2)]):
                def body():
                    c = mpi.COMM_WORLD
                    x = jnp.asarray(np_shard(L8, c.rank))
                    return c.Reshard(x, L8, L24)

                out = mpi.run_ranks(body, NR, timeout=5.0)
            for r in range(NR):
                assert np.array_equal(np.asarray(out[r]),
                                      np_shard(L24, r))
        finally:
            mpi.config.set_comm_retries(0)


@pytest.mark.slow
class TestCrossWorldMatrixSlow:
    """The heavyweight leg: the transition matrix on non-power-of-two
    and small worlds, both backends (the smoke lane covers the compiled
    sweep on 8)."""

    @pytest.mark.parametrize("n", [3, 6])
    def test_small_world_transitions(self, n):
        gs = (2 * n, n)
        full = np.random.default_rng(n).standard_normal(gs)
        fl = rs.layout((n,), 0, None)
        cases = [rs.layout((n,), None, 0),
                 rs.layout((n,), None, None)]
        if n == 6:
            cases += [rs.layout((2, 3), 0, 1),
                      rs.layout((2, 3), (0,), None)]
        for tl in cases:
            def body(tl=tl):
                c = mpi.COMM_WORLD
                x = jnp.asarray(np_shard(fl, c.rank, arr=full))
                return c.Reshard(x, fl, tl)

            out = mpi.run_ranks(body, n)
            for r in range(n):
                assert np.array_equal(
                    np.asarray(out[r]), np_shard(tl, r, arr=full)), \
                    (tl.describe(), r)

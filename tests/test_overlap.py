"""mpi4torch_tpu.overlap — split-phase nonblocking collectives + the
overlap scheduler (ISSUE 5).

Coverage per the acceptance criteria:

* HLO census: a split-phase collective's *start* (its phase-1
  collective op) precedes compute interleaved between start and Wait,
  and its *done* (the phase-2 collective / completion barrier) follows
  it, in ONE jitted computation; for a 3-bucket fused tree under the
  scheduler, bucket ``i+1``'s start precedes bucket ``i``'s done (>= 2
  collectives in flight); the backward chain is REVERSED (the last
  adjoint collective is the all-gather adjoint of the FIRST start);
* bitwise parity between the split-phase and blocking forms on (1,),
  (3,), (8,) and (2,4)-mesh worlds, and Mode A vs Mode B under
  ``deterministic_mode``;
* gradients through start/wait pairs and through the scheduler;
* misuse: double-Wait raises (both backends, including through a
  ``JoinDummiesHandle`` copy), an un-waited handle at SPMD trace exit
  raises;
* scheduler prefetch depth (the window width is visible in the lowered
  program) and the ZeRO prefetch/reduce-scatter windows;
* the scope/explicit degrade-vs-raise matrix for overlap x codec;
* a registry-style sync guard in the test_tune mold: every split-phase
  form the facade exposes must be listed in
  ``overlap.SPLIT_PHASE_FORMS`` AND have census coverage here, so a
  future ``*_start`` shipped without tests fails CI.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import mpi4torch_tpu as mpi
from mpi4torch_tpu import overlap
from mpi4torch_tpu._compat import shard_map

NR = 8
CENSUS_NR = 4
COLLECTIVES = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
               "collective_permute")

comm = mpi.COMM_WORLD

# The split-phase census matrix: every form in overlap.SPLIT_PHASE_FORMS
# must appear here with a dedicated start-precedes-compute /
# done-follows census test below (TestSplitPhaseCensus), mirroring
# test_tune's registry-sync guard.
SPLIT_CENSUS_COVERED = frozenset(
    {"Allreduce", "Reduce_scatter", "Allgather"})


@pytest.fixture(autouse=True)
def _isolated_overlap_state(tmp_path, monkeypatch):
    """Pristine knobs + private tune cache per test (the selector feeds
    the scheduler's per-bucket picks, so cross-test cache leakage would
    change which wire a bucket rides)."""
    monkeypatch.setenv("MPI4TORCH_TPU_TUNE_CACHE",
                       str(tmp_path / "tune_cache.json"))
    from mpi4torch_tpu import tune
    tune.clear()
    yield
    tune.clear()
    mpi.config.set_default_overlap(None)
    mpi.config.set_latency_crossover_bytes(None)
    mpi.config.set_bandwidth_crossover_bytes(None)


def test_split_phase_registry_sync_guard():
    """Every split-phase form the facade exposes (as ``<Form>_start``)
    must be registered in overlap.SPLIT_PHASE_FORMS and have census
    coverage in SPLIT_CENSUS_COVERED — adding a new *_start without
    extending both fails CI right here (the test_tune
    registry-sync-guard pattern; checker body shared via
    analyze.registry, messages unchanged — the coverage literal stays
    HERE, next to the census matrix it pins)."""
    from mpi4torch_tpu.analyze.registry import \
        overlap_split_phase_problems

    assert overlap_split_phase_problems(SPLIT_CENSUS_COVERED) == []


def _mesh_comm(nr=CENSUS_NR):
    mesh = Mesh(np.asarray(jax.devices()[:nr]), ("w",))
    return mesh, mpi.comm_from_mesh(mesh, "w")


def _lower_text(fn, *args, nr=CENSUS_NR):
    mesh, c = _mesh_comm(nr)
    wrapped = shard_map(lambda *a: fn(c, *a), mesh=mesh, in_specs=P(),
                       out_specs=P(), check_vma=False)
    return jax.jit(wrapped).lower(*args).as_text()


# ---------------------------------------------------------------------------
# HLO census: start precedes interleaved compute, done follows
# ---------------------------------------------------------------------------


class TestSplitPhaseCensus:
    def test_allreduce_start_straddles_compute(self):
        def body(c, x):
            h = c.Allreduce_start(x, mpi.MPI_SUM)
            mid = jnp.sin(x) * 2.0       # interleaved user compute
            return c.Wait(h) + mid

        txt = _lower_text(body, jnp.ones(64, jnp.float32))
        rs = txt.index("stablehlo.reduce_scatter")
        sin = txt.index("stablehlo.sine")
        ag = txt.index("stablehlo.all_gather")
        assert rs < sin < ag, (
            "split-phase Allreduce must put its reduce-scatter start "
            "before the interleaved compute and its all-gather done "
            "after it")

    def test_reduce_scatter_start_precedes_compute_done_follows(self):
        def body(c, x):
            h = c.Reduce_scatter_start(x.reshape(CENSUS_NR, -1),
                                       mpi.MPI_SUM, 0)
            mid = jnp.sin(x)
            return c.Wait(h).reshape(-1) + mid[:64 // CENSUS_NR]

        txt = _lower_text(body, jnp.ones(64, jnp.float32))
        rs = txt.index("stablehlo.reduce_scatter")
        sin = txt.index("stablehlo.sine")
        done = txt.rindex("stablehlo.optimization_barrier")
        assert rs < sin < done

    def test_allgather_start_precedes_compute_done_follows(self):
        def body(c, x):
            h = c.Allgather_start(x, 0)
            mid = jnp.sin(x)
            return c.Wait(h)[:16] + mid

        txt = _lower_text(body, jnp.ones(16, jnp.float32))
        ag = txt.index("stablehlo.all_gather")
        sin = txt.index("stablehlo.sine")
        done = txt.rindex("stablehlo.optimization_barrier")
        assert ag < sin < done

    def test_three_bucket_tree_keeps_window_in_flight(self):
        # The acceptance-criterion census: a 3-bucket fused tree with
        # split-phase enabled, ONE jitted computation — each bucket's
        # reduce-scatter start appears before the previous bucket's
        # all-gather done (>= 2 collectives in flight, vs the blocking
        # form's strict start_i..done_i..start_{i+1} nesting).
        tree = [jnp.ones(256, jnp.float32) * (i + 1) for i in range(3)]

        def body(c, t):
            return c.Allreduce_tree(t, mpi.MPI_SUM, bucket_bytes=1024,
                                    overlap=True)

        txt = _lower_text(body, tree)
        rs = [m.start() for m in re.finditer("stablehlo.reduce_scatter",
                                             txt)]
        ag = [m.start() for m in re.finditer("stablehlo.all_gather", txt)]
        assert len(rs) == 3 and len(ag) == 3
        # bucket order is trace order: rs[i]/ag[i] belong to bucket i.
        assert rs[0] < rs[1] < ag[0], \
            "bucket 1's start must precede bucket 0's done"
        assert rs[2] < ag[1], \
            "bucket 2's start must precede bucket 1's done"

    def test_scheduler_prefetch_depth_widens_window(self):
        # overlap=<int> sets the window depth: with depth 3 on a
        # 4-bucket tree, buckets 0..2 all start before bucket 0
        # completes; with the default depth 2, bucket 2's start comes
        # after bucket 0's done.
        tree = [jnp.ones(256, jnp.float32) * (i + 1) for i in range(4)]

        def body(depth):
            def f(c, t):
                return c.Allreduce_tree(t, mpi.MPI_SUM, bucket_bytes=1024,
                                        overlap=depth)
            return f

        txt2 = _lower_text(body(True), tree)
        txt3 = _lower_text(body(3), tree)
        for txt, depth in ((txt2, 2), (txt3, 3)):
            rs = [m.start() for m in
                  re.finditer("stablehlo.reduce_scatter", txt)]
            ag = [m.start() for m in re.finditer("stablehlo.all_gather",
                                                 txt)]
            assert len(rs) == 4 and len(ag) == 4
            in_flight_before_first_done = sum(1 for r in rs if r < ag[0])
            assert in_flight_before_first_done == depth, (
                f"window depth {depth}: expected {depth} starts before "
                f"the first done, saw {in_flight_before_first_done}")

    def test_backward_chain_is_reversed(self):
        # Two handles with DISTINCT payload sizes so forward and adjoint
        # collectives are identifiable by shape: forward order is
        # start_a, start_b, wait_a, wait_b; the transpose reverses the
        # wait chain, so the LAST collective in the lowered grad program
        # is the all-gather adjoint of start_a — the FIRST start.
        na, nb_ = 64, 32

        def body(c, x):
            a, b = x[:na], x[na:]
            ha = c.Allreduce_start(a, mpi.MPI_SUM)
            hb = c.Allreduce_start(b, mpi.MPI_SUM)
            ra = c.Wait(mpi.JoinDummiesHandle(ha, [hb.dummy]))
            rb = c.Wait(hb)
            return jnp.sum(ra) + jnp.sum(rb)

        def grad_body(c, x):
            return jax.grad(lambda v: body(c, v))(x)

        txt = _lower_text(grad_body, jnp.ones(na + nb_, jnp.float32))
        seg_a = na // CENSUS_NR
        ags = [m for m in re.finditer(
            r"stablehlo\.all_gather.*?tensor<1x(\d+)xf32>", txt)]
        assert ags, "no all_gather in the lowered grad program"
        # The final all_gather operates on bucket a's segment width —
        # start_a's adjoint runs LAST, i.e. the wait chain reversed.
        assert ags[-1].group(1) == str(seg_a), (
            f"expected the last adjoint all_gather on segment width "
            f"{seg_a} (the first start's), got {ags[-1].group(1)}")

    def test_zero_prefetch_forward_gathers_backward_scatters(self):
        # prefetch_allgather_tree: forward = one all_gather per shard
        # bucket (all issued ahead of their Waits); adjoint = the same
        # window of reduce-scatters in reverse.
        template = [jnp.ones(128, jnp.float32), jnp.ones(96, jnp.float32),
                    jnp.ones(64, jnp.float32)]

        def grad_body(c, shards):
            def loss(s):
                full = overlap.prefetch_allgather_tree(
                    c, s, template, bucket_bytes=256, depth=2)
                return sum(jnp.sum(f) for f in full)
            # value_and_grad keeps the forward gathers live (grad alone
            # would let XLA DCE them: the all_gather adjoint needs only
            # the cotangent).
            return jax.value_and_grad(loss)(shards)

        shards = [jnp.ones(128 // CENSUS_NR, jnp.float32),
                  jnp.ones(96 // CENSUS_NR, jnp.float32),
                  jnp.ones(64 // CENSUS_NR, jnp.float32)]
        txt = _lower_text(grad_body, shards)
        n_ag = txt.count("stablehlo.all_gather")
        n_rs = txt.count("stablehlo.reduce_scatter")
        assert n_ag >= 2 and n_rs == n_ag, (
            f"ZeRO prefetch adjoint must mirror gathers with scatters; "
            f"saw {n_ag} all_gather / {n_rs} reduce_scatter")


# ---------------------------------------------------------------------------
# Parity: split-phase vs blocking, Mode A vs Mode B
# ---------------------------------------------------------------------------


def _rank_slice(x):
    return jax.lax.dynamic_index_in_dim(
        x, jnp.asarray(comm.rank + 0), 0, keepdims=False)


class TestParity:
    @pytest.mark.parametrize("nr", [1, 3, 8])
    def test_bitwise_vs_blocking_deterministic(self, nr):
        rng = np.random.default_rng(17)
        data = jnp.asarray(rng.standard_normal((nr, 37)).astype(np.float32))

        def split(x):
            return comm.Wait(comm.Allreduce_start(_rank_slice(x),
                                                  mpi.MPI_SUM))

        def blocking(x):
            return comm.Allreduce(_rank_slice(x), mpi.MPI_SUM)

        with mpi.config.deterministic_mode(True):
            a = np.asarray(mpi.run_spmd(split, nranks=nr)(data))
            b = np.asarray(mpi.run_spmd(blocking, nranks=nr)(data))
        np.testing.assert_array_equal(a, b)

    @pytest.mark.parametrize("nr", [1, 3, 8])
    def test_bitwise_vs_blocking_exact_data(self, nr):
        # Outside deterministic mode the ring pair and the native psum
        # may associate differently; on exactly-representable data every
        # association gives identical bits — the standard exact-data
        # bitwise probe (test_tune uses it for the algorithm matrix).
        data = jnp.asarray(
            np.arange(nr * 23, dtype=np.float32).reshape(nr, 23))

        def split(x):
            return comm.Wait(comm.Allreduce_start(_rank_slice(x),
                                                  mpi.MPI_SUM))

        def blocking(x):
            return comm.Allreduce(_rank_slice(x), mpi.MPI_SUM)

        a = np.asarray(mpi.run_spmd(split, nranks=nr)(data))
        b = np.asarray(mpi.run_spmd(blocking, nranks=nr)(data))
        np.testing.assert_array_equal(a, b)

    def test_bitwise_on_2d_mesh_world(self):
        # (2,4)-mesh: the 2-axis hier communicator serves split-phase
        # through the generic compute-at-start handles — bit-identical
        # to its blocking Allreduce by construction.
        mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                    ("dp", "tp"))
        c = mpi.comm_from_mesh(mesh, ("dp", "tp"))
        rng = np.random.default_rng(23)
        x = jnp.asarray(rng.standard_normal(33).astype(np.float32))

        def split(v):
            return c.Wait(c.Allreduce_start(v, mpi.MPI_SUM))

        def blocking(v):
            return c.Allreduce(v, mpi.MPI_SUM)

        run = lambda f: np.asarray(jax.jit(shard_map(
            f, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False))(x))
        np.testing.assert_array_equal(run(split), run(blocking))

    def test_mode_a_vs_mode_b_bitwise_deterministic(self):
        rng = np.random.default_rng(29)
        data = jnp.asarray(rng.standard_normal((NR, 31)).astype(np.float32))

        def split(x):
            return comm.Wait(comm.Allreduce_start(_rank_slice(x),
                                                  mpi.MPI_SUM))

        with mpi.config.deterministic_mode(True):
            a = np.asarray(mpi.run_spmd(split)(data))
        b = mpi.run_ranks(
            lambda: np.asarray(comm.Wait(comm.Allreduce_start(
                data[comm.rank], mpi.MPI_SUM))), NR)
        for r in range(NR):
            np.testing.assert_array_equal(a[r], b[r], err_msg=f"rank {r}")

    def test_eager_split_phase_bitwise_vs_blocking(self):
        rng = np.random.default_rng(31)
        data = jnp.asarray(rng.standard_normal((4, 21)).astype(np.float32))

        def body():
            split = comm.Wait(comm.Allreduce_start(data[comm.rank],
                                                   mpi.MPI_SUM))
            blocking = comm.Allreduce(data[comm.rank], mpi.MPI_SUM)
            return bool(np.array_equal(np.asarray(split),
                                       np.asarray(blocking)))

        assert all(mpi.run_ranks(body, 4))

    def test_scheduler_tree_bitwise_vs_blocking_fused(self):
        rng = np.random.default_rng(37)
        tree = {"a": jnp.asarray(rng.standard_normal(300).astype(np.float32)),
                "b": jnp.asarray(rng.standard_normal(45).astype(np.float32)),
                "c": jnp.asarray(rng.integers(0, 9, 30).astype(np.int32))}

        def run(ov):
            return mpi.run_spmd(lambda t: comm.Allreduce_tree(
                t, mpi.MPI_SUM, bucket_bytes=512, overlap=ov,
                mean=False))(tree)

        a, b = run(True), run(None)
        for k in tree:
            np.testing.assert_array_equal(np.asarray(a[k]),
                                          np.asarray(b[k]), err_msg=k)

    def test_scheduler_tree_grads_match_blocking(self):
        rng = np.random.default_rng(41)
        tree = {"w": jnp.asarray(rng.standard_normal(130).astype(np.float32)),
                "v": jnp.asarray(rng.standard_normal(70).astype(np.float32))}

        def make(ov):
            def body(t):
                def loss(tr):
                    red = comm.Allreduce_tree(tr, mpi.MPI_SUM,
                                              bucket_bytes=256, overlap=ov,
                                              mean=True)
                    return sum(jnp.vdot(l, l)
                               for l in jax.tree.leaves(red))
                return jax.grad(loss)(t)
            return body

        a = mpi.run_spmd(make(2))(tree)
        b = mpi.run_spmd(make(None))(tree)
        for k in tree:
            np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                       rtol=1e-5, atol=1e-6, err_msg=k)

    def test_zero_step_overlap_bitwise(self):
        params = {"w": jnp.arange(600, dtype=jnp.float32).reshape(20, 30)
                  / 100, "b": jnp.ones(7, jnp.float32)}
        grads = jax.tree.map(lambda p: p * 0.5, params)

        class _Sgd:
            def init(self, p):
                return None

            def update(self, g, s, p):
                return jax.tree.map(lambda x: -0.1 * x, g), None

        from mpi4torch_tpu.parallel import zero as Z
        opt = _Sgd()

        def step(ov):
            def f():
                st = Z.zero_init(comm, opt, params)
                return Z.zero_step(comm, opt, params, grads, st,
                                   overlap=ov)[0]
            return mpi.run_spmd(f, nranks=NR)()

        a, b = step(True), step(None)
        for k in params:
            np.testing.assert_array_equal(np.asarray(a[k]),
                                          np.asarray(b[k]), err_msg=k)

    def test_zero3_params_prefetch_bitwise_and_scope(self):
        from mpi4torch_tpu.parallel import zero as Z
        template = {"w": jnp.arange(96, dtype=jnp.float32),
                    "v": jnp.ones((5, 5), jnp.float32)}

        def gather(ov, scoped=False):
            def f():
                shards = Z.zero3_shard_params(comm, template)
                if scoped:
                    with mpi.config.overlap_scope(ov):
                        return Z.zero3_params(comm, shards, template)
                return Z.zero3_params(comm, shards, template, overlap=ov)
            return mpi.run_spmd(f, nranks=4)()

        blocking = gather(None)
        for variant in (gather(True), gather(3), gather(True, scoped=True)):
            for k in template:
                np.testing.assert_array_equal(np.asarray(variant[k]),
                                              np.asarray(blocking[k]),
                                              err_msg=k)


# ---------------------------------------------------------------------------
# WaitHandle API parity with the eager path
# ---------------------------------------------------------------------------


class TestHandleApi:
    def test_handle_is_waithandle_with_dummy(self):
        def body(x):
            h = comm.Allreduce_start(x, mpi.MPI_SUM)
            assert isinstance(h, mpi.WaitHandle)
            assert isinstance(h, mpi.SpmdWaitHandle)
            # .dummy joins like the eager handle's
            y = mpi.JoinDummies(x * 2, [h.dummy])
            return comm.Wait(h) + 0 * y

        out = np.asarray(mpi.run_spmd(body, nranks=4)(jnp.ones(8)))
        np.testing.assert_allclose(out[0], 4.0)

    def test_join_dummies_handle_preserves_kind(self):
        def body(x):
            h = comm.Allreduce_start(x, mpi.MPI_SUM)
            h2 = mpi.JoinDummiesHandle(h, [x * 3])
            assert isinstance(h2, mpi.SpmdWaitHandle)
            return comm.Wait(h2)

        out = np.asarray(mpi.run_spmd(body, nranks=4)(jnp.ones(8)))
        np.testing.assert_allclose(out[0], 4.0)


# ---------------------------------------------------------------------------
# Misuse guards
# ---------------------------------------------------------------------------


class TestMisuse:
    def test_double_wait_raises_spmd(self):
        def body(x):
            h = comm.Allreduce_start(x, mpi.MPI_SUM)
            comm.Wait(h)
            return comm.Wait(h)

        with pytest.raises(mpi.BifurcationError, match="exactly once"):
            mpi.run_spmd(body, nranks=4)(jnp.ones(4))

    def test_double_wait_through_joined_copy_raises_spmd(self):
        def body(x):
            h = comm.Allreduce_start(x, mpi.MPI_SUM)
            h2 = mpi.JoinDummiesHandle(h, [x])
            comm.Wait(h2)
            return comm.Wait(h)

        with pytest.raises(mpi.BifurcationError, match="exactly once"):
            mpi.run_spmd(body, nranks=4)(jnp.ones(4))

    def test_unwaited_handle_at_trace_exit_raises(self):
        def body(x):
            comm.Allreduce_start(x, mpi.MPI_SUM)
            return x

        with pytest.raises(mpi.DeadlockError, match="un-waited"):
            mpi.run_spmd(body, nranks=4)(jnp.ones(4))

    def test_unwaited_reports_the_form(self):
        def body(x):
            comm.Allgather_start(x, 0)
            return x

        with pytest.raises(mpi.DeadlockError, match="Allgather_start"):
            mpi.run_spmd(body, nranks=4)(jnp.ones(4))

    def test_double_wait_raises_eager(self):
        def body():
            h = comm.Allreduce_start(jnp.ones(3), mpi.MPI_SUM)
            comm.Wait(h)
            try:
                comm.Wait(h)
                return False
            except mpi.BifurcationError:
                return True

        assert all(mpi.run_ranks(body, 2))

    def test_double_wait_through_joined_copy_raises_eager(self):
        def body():
            h = comm.Allreduce_start(jnp.ones(3), mpi.MPI_SUM)
            h2 = mpi.JoinDummiesHandle(h, [jnp.ones(1)])
            comm.Wait(h2)
            try:
                comm.Wait(h)
                return False
            except mpi.BifurcationError:
                return True

        assert all(mpi.run_ranks(body, 2))


# ---------------------------------------------------------------------------
# Scope / explicit degrade-vs-raise matrix
# ---------------------------------------------------------------------------


class TestOverlapCompositionMatrix:
    def test_explicit_overlap_plus_explicit_codec_raises(self):
        tree = {"a": jnp.ones(256, jnp.float32)}
        with pytest.raises(mpi.CommError, match="split-phase"):
            mpi.run_spmd(lambda t: comm.Allreduce_tree(
                t, mpi.MPI_SUM, overlap=True, compression="q8"))(tree)

    def test_allreduce_start_explicit_codec_raises(self):
        with pytest.raises(ValueError, match="split-phase"):
            mpi.run_spmd(lambda x: comm.Wait(comm.Allreduce_start(
                x, mpi.MPI_SUM, compression="q8")), nranks=4)(
                    jnp.ones(64, jnp.float32))

    def test_allreduce_start_scope_codec_degrades_to_exact(self):
        data = jnp.asarray(
            np.arange(NR * 16, dtype=np.float32).reshape(NR, 16))

        def split(x):
            with mpi.config.compression_scope("q8"):
                return comm.Wait(comm.Allreduce_start(_rank_slice(x),
                                                      mpi.MPI_SUM))

        def exact(x):
            return comm.Wait(comm.Allreduce_start(_rank_slice(x),
                                                  mpi.MPI_SUM))

        a = np.asarray(mpi.run_spmd(split)(data))
        b = np.asarray(mpi.run_spmd(exact)(data))
        np.testing.assert_array_equal(a, b)

    def test_explicit_overlap_scope_codec_yields_to_exact_window(self):
        # Explicit overlap + scope codec: exactly one explicit half —
        # the scope codec yields, buckets ride the exact split wire.
        tree = {"a": jnp.asarray(np.arange(256, dtype=np.float32))}

        def body(t):
            with mpi.config.compression_scope("q8"):
                return comm.Allreduce_tree(t, mpi.MPI_SUM,
                                           bucket_bytes=512, overlap=True)

        def exact(t):
            return comm.Allreduce_tree(t, mpi.MPI_SUM, bucket_bytes=512,
                                       overlap=True)

        a = mpi.run_spmd(body)(tree)
        b = mpi.run_spmd(exact)(tree)
        np.testing.assert_array_equal(np.asarray(a["a"]),
                                      np.asarray(b["a"]))

    def test_scope_overlap_explicit_codec_keeps_codec_blocking(self):
        # Scope overlap + explicit codec: the codec is the explicit
        # half — honored; the scope overlap degrades per bucket to the
        # blocking codec pipeline.  Result matches the plain compressed
        # blocking tree exactly.
        rng = np.random.default_rng(43)
        tree = {"a": jnp.asarray(
            rng.standard_normal(256).astype(np.float32))}

        def scoped(t):
            with mpi.config.overlap_scope(True):
                return comm.Allreduce_tree(t, mpi.MPI_SUM,
                                           bucket_bytes=512,
                                           compression="q8")

        def blocking(t):
            return comm.Allreduce_tree(t, mpi.MPI_SUM, bucket_bytes=512,
                                       compression="q8")

        a = mpi.run_spmd(scoped)(tree)
        b = mpi.run_spmd(blocking)(tree)
        np.testing.assert_array_equal(np.asarray(a["a"]),
                                      np.asarray(b["a"]))

    def test_scope_overlap_mixed_dtypes_splits_exact_compresses_float(self):
        # Per-bucket composition under scope defaults: inside overlap +
        # compression scopes, the float bucket rides the blocking q8
        # pipeline while the int bucket rides the exact split wire.
        tree = {"f": jnp.asarray(np.arange(128, dtype=np.float32)),
                "i": jnp.asarray(np.arange(64, dtype=np.int32))}

        def scoped(t):
            with mpi.config.overlap_scope(True), \
                    mpi.config.compression_scope("q8"):
                return comm.Allreduce_tree(t, mpi.MPI_SUM,
                                           bucket_bytes=512)

        def blocking(t):
            with mpi.config.compression_scope("q8"):
                return comm.Allreduce_tree(t, mpi.MPI_SUM,
                                           bucket_bytes=512)

        a = mpi.run_spmd(scoped, nranks=4)(tree)
        b = mpi.run_spmd(blocking, nranks=4)(tree)
        for k in tree:
            np.testing.assert_array_equal(np.asarray(a[k]),
                                          np.asarray(b[k]), err_msg=k)

    def test_eager_scope_overlap_nonsum_degrades(self):
        # A scope/process overlap default must not break a MAX tree on
        # the eager backend — it degrades to the blocking rendezvous
        # (the explicit overlap=True raise is regression-tested in
        # test_fuse).
        data = jnp.asarray(np.arange(8, dtype=np.float32))

        def body():
            with mpi.config.overlap_scope(True):
                out = comm.Allreduce_tree({"a": data * (comm.rank + 1)},
                                          mpi.MPI_MAX)
            return np.asarray(out["a"])

        outs = mpi.run_ranks(body, 4)
        np.testing.assert_array_equal(outs[0], np.asarray(data) * 4)

    def test_eager_pipeline_honors_window_depth(self, monkeypatch):
        # An integer overlap value must reach the eager Isend/Irecv
        # pipeline as its window depth (it was silently pinned to the
        # default of 2), and the result stays bitwise at any depth.
        from mpi4torch_tpu.fuse import collectives as fc

        seen = []
        orig = fc._pipeline_allreduce

        def spy(comm_, buckets, op, *, depth=2):
            seen.append(depth)
            return orig(comm_, buckets, op, depth=depth)

        monkeypatch.setattr(fc, "_pipeline_allreduce", spy)
        tree = [jnp.asarray(np.arange(512, dtype=np.float32))
                for _ in range(3)]

        def body(ov):
            def run():
                out = comm.Allreduce_tree(
                    [t * (comm.rank + 1) for t in tree], mpi.MPI_SUM,
                    bucket_bytes=1024, overlap=ov)
                return [np.asarray(t) for t in out]
            return mpi.run_ranks(run, 2)

        deep = body(4)
        assert seen and all(d == 4 for d in seen)
        seen.clear()
        shallow = body(1)
        assert seen and all(d == 1 for d in seen)
        for a, b in zip(deep[0], shallow[0]):
            np.testing.assert_array_equal(a, b)

    def test_eager_explicit_overlap_nonsum_still_raises(self):
        def body():
            try:
                comm.Allreduce_tree({"a": jnp.ones(4)}, mpi.MPI_MAX,
                                    overlap=True)
                return False
            except mpi.CommError:
                return True

        assert all(mpi.run_ranks(body, 2))

    def test_overlap_validation(self):
        with pytest.raises(ValueError, match="overlap"):
            mpi.config.set_default_overlap(0)
        with pytest.raises(ValueError, match="overlap"):
            mpi.config.set_default_overlap(-2)
        with pytest.raises(ValueError, match="overlap"):
            mpi.config.set_default_overlap("deep")
        with mpi.config.overlap_scope(4):
            assert mpi.config.default_overlap() == 4
        assert mpi.config.default_overlap() is None

    def test_run_spmd_jit_cache_keys_on_overlap_default(self):
        # Toggling the overlap default between calls must retrace: the
        # same run_spmd callable lowers the blocking form, then the
        # split-phase window.
        tree = [jnp.ones(256, jnp.float32) for _ in range(2)]

        def body(t):
            return comm.Allreduce_tree(t, mpi.MPI_SUM, bucket_bytes=1024)

        step = mpi.run_spmd(body, nranks=4)
        blocking = step(tree)
        mpi.config.set_default_overlap(True)
        try:
            overlapped = step(tree)
        finally:
            mpi.config.set_default_overlap(None)
        for a, b in zip(jax.tree.leaves(blocking),
                        jax.tree.leaves(overlapped)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# 1F1B double-buffered pipeline
# ---------------------------------------------------------------------------


class Test1F1BOverlap:
    def _run(self, overlap, n=4, n_mb=6, tag=0):
        from mpi4torch_tpu.parallel import pp

        def body():
            rank = comm.rank
            params = {"w": jnp.eye(4) * (0.5 + 0.1 * rank)}
            mbs = [jnp.ones((2, 4)) * (i + 1) for i in range(n_mb)]

            def apply_stage(p, x):
                return jnp.tanh(x @ p["w"])

            def loss_fn(y, i):
                return jnp.sum(y) / (i + 1)

            loss, grads = pp.pipeline_step_1f1b(
                comm, apply_stage, params, mbs, loss_fn,
                recv_like=jnp.zeros((2, 4)), tag=tag, overlap=overlap)
            return np.asarray(loss), np.asarray(grads["w"])

        return mpi.run_ranks(body, n)

    def test_overlap_bitwise_matches_blocking(self):
        blocking = self._run(None, tag=0)
        buffered = self._run(2, tag=10_000)
        for (l0, g0), (l1, g1) in zip(blocking, buffered):
            np.testing.assert_array_equal(l0, l1)
            np.testing.assert_array_equal(g0, g1)

    def test_deeper_window_identical(self):
        blocking = self._run(None, tag=0)
        deep = self._run(4, tag=20_000)
        for (l0, g0), (l1, g1) in zip(blocking, deep):
            np.testing.assert_array_equal(l0, l1)
            np.testing.assert_array_equal(g0, g1)


# ---------------------------------------------------------------------------
# Profiling span kinds
# ---------------------------------------------------------------------------


class TestProfilingSpans:
    def test_bucket_scope_phase_suffix(self):
        from mpi4torch_tpu.utils.profiling import bucket_scope
        with bucket_scope("Allreduce_tree", 0, 3, phase="start"):
            pass
        with bucket_scope("Allreduce_tree", 0, 3, phase="wait"):
            pass
        with pytest.raises(ValueError, match="start"):
            bucket_scope("Allreduce_tree", 0, 3, phase="middle")

    def test_split_phase_spans_reach_lowered_program(self):
        # The start/wait spans must be visible in the lowered program's
        # location metadata, so traces can attribute exposed vs hidden
        # communication per bucket.
        tree = [jnp.ones(256, jnp.float32) for _ in range(2)]

        from mpi4torch_tpu._compat import lowered_text

        def body(c, t):
            return c.Allreduce_tree(t, mpi.MPI_SUM, bucket_bytes=1024,
                                    overlap=True)

        mesh, c = _mesh_comm()
        wrapped = shard_map(lambda t: body(c, t), mesh=mesh, in_specs=P(),
                            out_specs=P(), check_vma=False)
        txt = lowered_text(jax.jit(wrapped).lower(tree), debug_info=True)
        assert "bucket0of2.start" in txt
        assert "bucket0of2.wait" in txt


# ---------------------------------------------------------------------------
# Scheduled-exposure census (overlap.census): the quantitative fold of
# the ordering censuses above — bench._bench_overlap_zero's smoke-path
# exposed-comm fraction.
# ---------------------------------------------------------------------------


class TestScheduledExposure:
    def _tree_lowered(self, overlap_arg, nb=3):
        tree = [jnp.ones(1024, jnp.float32) for _ in range(nb)]
        mesh, c = _mesh_comm()
        wrapped = shard_map(
            lambda t: c.Allreduce_tree(t, mpi.MPI_SUM, bucket_bytes=4096,
                                       overlap=overlap_arg),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)
        return jax.jit(wrapped).lower(tree)

    def test_blocking_program_is_fully_exposed(self):
        out = overlap.scheduled_exposure(self._tree_lowered(False))
        assert out["n_buckets"] == 3
        assert out["exposed_fraction"] == 1.0
        assert all(not b["split_phase"]
                   for b in out["buckets"].values())

    def test_windowed_program_is_strictly_lower(self):
        blocking = overlap.scheduled_exposure(self._tree_lowered(False))
        windowed = overlap.scheduled_exposure(self._tree_lowered(True))
        assert windowed["n_buckets"] == blocking["n_buckets"] == 3
        assert all(b["split_phase"]
                   for b in windowed["buckets"].values())
        # At most the window's trailing drain bucket is exposed (it can
        # census hidden too: the previous bucket's all-gather is wire in
        # flight inside its start->wait span).
        assert windowed["exposed_fraction"] < blocking["exposed_fraction"]
        assert windowed["n_exposed"] <= 1

    def test_census_accepts_debug_text(self):
        from mpi4torch_tpu._compat import lowered_text
        txt = lowered_text(self._tree_lowered(True), debug_info=True)
        from_text = overlap.scheduled_exposure(txt)
        from_lowered = overlap.scheduled_exposure(self._tree_lowered(True))
        assert from_text == from_lowered

    def test_census_without_buckets_is_none(self):
        mesh, c = _mesh_comm()
        wrapped = shard_map(
            lambda x: c.Allreduce(x, mpi.MPI_SUM),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)
        out = overlap.scheduled_exposure(
            jax.jit(wrapped).lower(jnp.ones(64, jnp.float32)))
        assert out["n_buckets"] == 0
        assert out["exposed_fraction"] is None

    def test_zero_step_census_matches_bench_claim(self):
        # The bench stanza's acceptance bar, in miniature: the blocking
        # ZeRO step censuses fully exposed, the windowed split-phase
        # step strictly lower, on the same model.
        from mpi4torch_tpu.parallel import zero as Z

        params = {"w": jnp.ones((32, 24), jnp.float32),
                  "b": jnp.ones(41, jnp.float32)}
        grads = jax.tree.map(lambda p: p * 0.01, params)

        class _Sgd:
            def init(self, p):
                return None

            def update(self, g, s, p):
                return jax.tree.map(lambda x: -0.1 * x, g), None

        opt = _Sgd()

        def lower(ov):
            def f(g):
                with mpi.config.fusion_scope(1024):
                    st = Z.zero_init(comm, opt, params)
                    return Z.zero_step(comm, opt, params, g, st,
                                       overlap=ov)[0]
            return jax.jit(mpi.run_spmd(f)).lower(grads)

        blocking = overlap.scheduled_exposure(lower(False))
        windowed = overlap.scheduled_exposure(lower(True))
        assert blocking["n_buckets"] > 2
        assert blocking["exposed_fraction"] == 1.0
        assert windowed["exposed_fraction"] < 1.0

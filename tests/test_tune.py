"""mpi4torch_tpu.tune — size/topology-aware algorithms + autotuner
(ISSUE 3), plus the bandwidth tier (ISSUE 4).

Coverage per the acceptance criteria:

* value + gradient parity of every algorithm
  (``rhd``/``tree``/``hier``/``bidir``/``torus``) against ``ring``, on
  power-of-two and non-power-of-two worlds;
* bitwise parity: Mode A (SPMD schedule) vs Mode B (rendezvous fold of
  the matching association) per algorithm under ``deterministic_mode``,
  and all algorithms vs ring on exactly-representable data;
* HLO census proving each algorithm emits its distinct schedule in
  forward AND backward (ring: one all_reduce; rhd: 2·log2 N shrinking
  collective_permutes; tree: 2·log2 N full-width permutes; hier: one
  reduce_scatter + all_reduce + all_gather triple; bidir: two
  concurrent counter-rotating collective_permute chains over
  half-payloads with no dependency between them; torus: one grouped
  channel per (virtual or real) mesh axis), and the phase-pipelined
  deterministic ring fold dropping the trailing broadcast hops;
* a registry-sync guard: every registered ``AlgorithmSpec`` name must
  appear in the parity/grads and census matrices here, so a future
  algorithm registered without tests fails CI;
* selector determinism, three-tier auto selection (latency below the
  crossover, ring in the middle, multipath at/above the bandwidth
  crossover), the degrade/raise rule, and codec restrictions (q8 is
  ring-only);
* autotuner cache round-trip: persisted winners reload in a fresh
  table, corrupt/stale/wrong-version cache files fall back to defaults
  without crashing; concurrent saves union rather than lose entries;
  the ``python -m mpi4torch_tpu.tune`` inspection CLI;
* ``hier``/``torus`` on a 2D mesh: single-axis grouped forms and the
  two-axis ``comm_from_mesh(mesh, (outer, inner))`` communicator;
* fused per-bucket picks: small tail buckets take the latency
  algorithm below the measured crossover while body buckets keep the
  ring pair — or the multipath algorithm past the bandwidth crossover.
"""

import json
import math
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import mpi4torch_tpu as mpi
from mpi4torch_tpu import tune
from mpi4torch_tpu._compat import shard_map

NR = 8
CENSUS_NR = 4
ALGOS = ("ring", "rhd", "tree", "hier", "bidir", "torus")
# Algorithms with a dedicated forward+backward HLO census below.  The
# registry-sync guard asserts this set — and ALGOS — equals the
# registry, so registering an algorithm without census coverage fails
# here rather than shipping untested.
CENSUS_COVERED = frozenset(ALGOS)
# The codec-capable side of the registry (AlgorithmSpec.codec_capable):
# the ring-shaped schedules whose channels host the in-schedule
# quantized pipeline.  The guard asserts this literal equals the
# registry AND that every registered codec declares only names from it,
# so the (algorithm × codec) census matrix below — computed from the
# live registries — provably enumerates every combination a wire can
# carry.  Same structural pattern as SPLIT_PHASE_FORMS in
# test_nonblocking.py.
CODEC_CAPABLE = ("ring", "bidir", "torus")
COLLECTIVES = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
               "collective_permute")

comm = mpi.COMM_WORLD


def _codec_algorithm_pairs():
    """Every (codec-capable algorithm × codec declaring it) pair, from
    the LIVE registries — parametrizes the per-pair census test, so a
    newly registered codec or codec-capable algorithm gets census
    coverage automatically (and the guard below fails if the
    enumeration rules themselves drift)."""
    from mpi4torch_tpu.compress import available_codecs, get_codec

    pairs = []
    for algo in tune.available_algorithms():
        if not tune.get_algorithm(algo).codec_capable:
            continue
        for name in available_codecs():
            if algo in get_codec(name).algorithms:
                pairs.append((algo, name))
    return pairs


def test_registry_sync_guard():
    """Every registered AlgorithmSpec name must be exercised by the
    parity/grads matrix (ALGOS — parametrizes TestAlgorithmParity and
    TestBitwiseDeterministicParity) AND the HLO census matrix
    (CENSUS_COVERED); the codec-capable subset must match
    CODEC_CAPABLE, and every registered codec must declare only
    codec-capable algorithms — which makes the computed
    (algorithm × codec) matrix (_codec_algorithm_pairs, parametrizing
    TestCodecAlgorithmCensus) a complete enumeration.  A future
    algorithm or codec registered without census coverage fails CI
    right here.  The checker body lives in the shared registry-guard
    home (analyze.registry.tune_problems, messages unchanged); the
    coverage literals stay HERE, next to the matrices they pin."""
    from mpi4torch_tpu.analyze.registry import tune_problems

    assert tune_problems(ALGOS, CENSUS_COVERED, CODEC_CAPABLE) == []
    pairs = _codec_algorithm_pairs()
    assert pairs and len(pairs) == len(set(pairs))
    assert ("bidir", "q8") in pairs and ("torus", "q8_ef_hop") in pairs


@pytest.fixture(autouse=True)
def _isolated_tune_state(tmp_path, monkeypatch):
    """Every test gets its own cache file and pristine knobs — the
    autotuner's persistence must never leak between tests (or into the
    rest of the suite)."""
    monkeypatch.setenv("MPI4TORCH_TPU_TUNE_CACHE",
                       str(tmp_path / "tune_cache.json"))
    tune.clear()
    yield
    tune.clear()
    mpi.config.set_latency_crossover_bytes(None)
    mpi.config.set_bandwidth_crossover_bytes(None)
    mpi.config.set_phase_pipelined_ring(True)
    mpi.config.set_hier_group_size(None)
    mpi.config.set_default_algorithm(None)
    mpi.config.set_chain_unroll_max(mpi.config.DEFAULT_CHAIN_UNROLL_MAX)


def census(fn, *args, nr=CENSUS_NR, mesh_axes=None):
    """collective-op name -> count in the lowered StableHLO (and the
    text itself, for shape-level assertions)."""
    if mesh_axes is None:
        mesh = Mesh(np.asarray(jax.devices()[:nr]), ("w",))
        c = mpi.comm_from_mesh(mesh, "w")
    else:
        mesh, c = mesh_axes
    wrapped = shard_map(lambda *a: fn(c, *a), mesh=mesh, in_specs=P(),
                        out_specs=P(), check_vma=False)
    txt = jax.jit(wrapped).lower(*args).as_text()
    return {k: txt.count(f"stablehlo.{k}") for k in COLLECTIVES}, txt


def only(**expected):
    out = {k: 0 for k in COLLECTIVES}
    out.update(expected)
    return out


# ---------------------------------------------------------------------------
# Parity + gradients
# ---------------------------------------------------------------------------


class TestAlgorithmParity:
    @pytest.mark.parametrize("algo", ALGOS)
    def test_values_and_grads_match_ring(self, algo):
        rng = np.random.default_rng(3)
        data = jnp.asarray(rng.standard_normal((NR, 37)).astype(np.float32))

        def body(x, a):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            y, g = jax.value_and_grad(lambda v: jnp.vdot(
                comm.Allreduce(v, mpi.MPI_SUM, algorithm=a), v))(t)
            return y, g

        want_y, want_g = mpi.run_spmd(lambda x: body(x, "ring"))(data)
        got_y, got_g = mpi.run_spmd(lambda x: body(x, algo))(data)
        np.testing.assert_allclose(np.asarray(got_y), np.asarray(want_y),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(got_g), np.asarray(want_g),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("nr,algo", [(3, "tree"), (6, "tree"),
                                         (6, "hier"), (3, "bidir"),
                                         (6, "bidir"), (6, "torus")])
    def test_non_power_of_two_worlds(self, nr, algo):
        rng = np.random.default_rng(5)
        data = jnp.asarray(rng.standard_normal((nr, 19)).astype(np.float32))

        def body(x, a):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM, algorithm=a)

        want = np.asarray(mpi.run_spmd(lambda x: body(x, "ring"),
                                       nranks=nr)(data))
        got = np.asarray(mpi.run_spmd(lambda x: body(x, algo),
                                      nranks=nr)(data))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_max_reduction_on_explicit_algorithms(self):
        rng = np.random.default_rng(7)
        data = jnp.asarray(rng.standard_normal((NR, 23)).astype(np.float32))

        def body(x, a):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_MAX, algorithm=a)

        want = np.asarray(mpi.run_spmd(lambda x: body(x, "ring"))(data))
        for algo in ("rhd", "tree", "bidir", "torus"):
            got = np.asarray(mpi.run_spmd(lambda x, a=algo: body(x, a))(data))
            np.testing.assert_array_equal(got, want, err_msg=algo)


class TestBitwiseDeterministicParity:
    @pytest.mark.parametrize("algo", ALGOS)
    def test_mode_a_vs_mode_b_bitwise(self, algo):
        # GENERAL float data: each algorithm's fixed association must
        # produce identical bits on the compiled schedule (Mode A) and
        # the rendezvous fold (Mode B) — the ISSUE 3 A/B contract.
        rng = np.random.default_rng(11)
        data = jnp.asarray(rng.standard_normal((NR, 33)).astype(np.float32))

        def det_body(x, a=algo):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM, algorithm=a)

        with mpi.config.deterministic_mode(True):
            a_out = np.asarray(mpi.run_spmd(det_body)(data))
        b_out = mpi.run_ranks(
            lambda: np.asarray(comm.Allreduce(
                data[comm.rank], mpi.MPI_SUM, algorithm=algo)), NR)
        for r in range(NR):
            np.testing.assert_array_equal(a_out[r], b_out[r],
                                          err_msg=f"{algo} rank {r}")

    @pytest.mark.parametrize("nr,root", [(3, 1), (6, 4), (8, 2)])
    def test_reduce_tree_nonzero_root_mode_a_vs_b_bitwise(self, nr, root):
        # The SPMD tree reduce relabels ranks relative to the ROOT
        # (rel = (idx - root) % n); the eager fold must rotate the
        # value list the same way or the associations — and the bits —
        # diverge for root != 0 (caught in review; regression).
        rng = np.random.default_rng(19)
        data = jnp.asarray(rng.standard_normal((nr, 27)).astype(np.float32))

        def det_body(x):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            return comm.Reduce_(t, mpi.MPI_SUM, root=root,
                                algorithm="tree")

        with mpi.config.deterministic_mode(True):
            a_out = np.asarray(mpi.run_spmd(det_body, nranks=nr)(data))
        b_out = mpi.run_ranks(
            lambda: np.asarray(comm.Reduce_(
                data[comm.rank], mpi.MPI_SUM, root=root,
                algorithm="tree")), nr)
        for r in range(nr):
            np.testing.assert_array_equal(a_out[r], b_out[r],
                                          err_msg=f"rank {r}")

    def test_all_algorithms_bitwise_vs_ring_on_exact_data(self):
        # Small-integer floats sum exactly under ANY association, so
        # bitwise equality across algorithms is well-defined — the
        # acceptance criterion's parity-against-ring form.
        rng = np.random.default_rng(13)
        data = jnp.asarray(
            rng.integers(-8, 8, (NR, 29)).astype(np.float32))

        def det_body(x, a):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM, algorithm=a)

        with mpi.config.deterministic_mode(True):
            want = np.asarray(
                mpi.run_spmd(lambda x: det_body(x, "ring"))(data))
            for algo in ("rhd", "tree", "hier", "bidir", "torus"):
                got = np.asarray(
                    mpi.run_spmd(lambda x, a=algo: det_body(x, a))(data))
                np.testing.assert_array_equal(got, want, err_msg=algo)


# ---------------------------------------------------------------------------
# HLO census: each algorithm's distinct schedule, forward and backward
# ---------------------------------------------------------------------------


class TestAlgorithmCensus:
    X = jnp.ones((16,))   # f64 under the x64 harness

    def _fwd(self, algo):
        got, txt = census(
            lambda c, x: c.Allreduce(x, mpi.MPI_SUM, algorithm=algo),
            self.X)
        return got, txt

    def _fwd_bwd(self, algo):
        got, txt = census(
            lambda c, x: jax.value_and_grad(lambda v: jnp.vdot(
                c.Allreduce(v, mpi.MPI_SUM, algorithm=algo), v))(x),
            self.X)
        return got, txt

    def test_ring_is_one_all_reduce(self):
        got, _ = self._fwd("ring")
        assert got == only(all_reduce=1)

    def test_rhd_is_log_permutes_of_shrinking_width(self):
        logn = int(math.log2(CENSUS_NR))
        got, txt = self._fwd("rhd")
        assert got == only(collective_permute=2 * logn), got
        # The butterfly never moves the full payload: halving ships
        # 8- then 4-element halves (16 elems / 4 ranks), doubling the
        # reverse — no full-width (16-element) permute anywhere.  (The
        # operand type follows the attribute dict — match `: (tensor<…`,
        # not the source_target_pairs attribute's own tensor type.)
        widths = re.findall(
            r"collective_permute.*?:\s*\(tensor<(\d+)x", txt)
        assert widths and all(int(w) < 16 for w in widths), widths
        assert {int(w) for w in widths} == {8, 4}, widths

    def test_tree_is_log_permutes_full_width(self):
        logn = int(math.ceil(math.log2(CENSUS_NR)))
        got, txt = self._fwd("tree")
        assert got == only(collective_permute=2 * logn), got
        widths = re.findall(
            r"collective_permute.*?:\s*\(tensor<(\d+)x", txt)
        assert widths and all(int(w) == 16 for w in widths), widths

    def test_hier_is_rs_ar_ag_triple(self):
        got, _ = self._fwd("hier")
        assert got == only(reduce_scatter=1, all_reduce=1, all_gather=1)

    # The two counter-rotating ring directions of the bidir dual-ring,
    # as collective_permute source_target_pairs attribute payloads.
    _FWD_RING = "[[0, 1], [1, 2], [2, 3], [3, 0]]"
    _REV_RING = "[[0, 3], [1, 0], [2, 1], [3, 2]]"

    def _permute_pair_tables(self, txt):
        return re.findall(
            r"collective_permute.*?source_target_pairs = dense<(\[\[.*?\]\])>",
            txt)

    def test_bidir_is_two_counter_rotating_half_payload_chains(self):
        # The ISSUE 4 multipath criterion: two CONCURRENT
        # counter-rotating collective_permute chains over half-payloads
        # with no serialization barrier between them — each chain is an
        # explicit ring reduce-scatter + all-gather, 2(N-1) hops.
        got, txt = self._fwd("bidir")
        assert got == only(collective_permute=4 * (CENSUS_NR - 1)), got
        tables = self._permute_pair_tables(txt)
        # exactly half the permutes ride each direction
        assert tables.count(self._FWD_RING) == 2 * (CENSUS_NR - 1), tables
        assert tables.count(self._REV_RING) == 2 * (CENSUS_NR - 1), tables
        # every permute moves a SEGMENT of a half-payload (16 elems ->
        # 8-elem halves -> 2-elem ring segments), never the full tensor
        widths = re.findall(
            r"collective_permute.*?:\s*\(tensor<(\d+)x", txt)
        assert widths and all(
            int(w) == 16 // 2 // CENSUS_NR for w in widths), widths
        # no serialization barrier between the chains: neither chain's
        # permutes consume the other's values, so no optimization_barrier
        # op separates them in the lowered module
        assert "optimization_barrier" not in txt

    def test_bidir_backward_rides_swapped_channels(self):
        # The adjoint of a ring segment is a ring segment in the reverse
        # direction: backward = the same dual-ring machinery, so fwd+bwd
        # shows exactly twice the chains, still evenly split between the
        # two rotations (the swap flips which half rides which).
        got, txt = self._fwd_bwd("bidir")
        assert got == only(collective_permute=8 * (CENSUS_NR - 1)), got
        tables = self._permute_pair_tables(txt)
        assert tables.count(self._FWD_RING) == 4 * (CENSUS_NR - 1), tables
        assert tables.count(self._REV_RING) == 4 * (CENSUS_NR - 1), tables

    def test_torus_is_one_grouped_channel_per_axis(self):
        # Flat-axis torus: the hier factorization viewed as a virtual 2D
        # torus with the payload STRIPED across the two tiers — one
        # grouped reduce-scatter/all-reduce/all-gather channel per
        # (virtual) axis, concurrent because the halves share no values.
        got, txt = self._fwd("torus")
        assert got == only(reduce_scatter=2, all_reduce=2,
                           all_gather=2), got
        # the two channels' first-stage reduce_scatters ride DIFFERENT
        # axes of the factorization: consecutive inner groups for one,
        # strided outer groups for the other (4 ranks -> 2x2)
        groups = set(re.findall(
            r"reduce_scatter.*?replica_groups = dense<(\[\[.*?\]\])>",
            txt))
        assert groups == {"[[0, 1], [2, 3]]", "[[0, 2], [1, 3]]"}, groups

    def test_torus_backward_census_doubles(self):
        got, _ = self._fwd_bwd("torus")
        assert got == only(reduce_scatter=4, all_reduce=4, all_gather=4)

    def test_backward_census_matches_forward_per_algorithm(self):
        logn = int(math.log2(CENSUS_NR))
        got, _ = self._fwd_bwd("ring")
        assert got == only(all_reduce=2)
        got, _ = self._fwd_bwd("rhd")
        assert got == only(collective_permute=4 * logn), got
        got, _ = self._fwd_bwd("tree")
        assert got == only(collective_permute=4 * logn), got
        got, _ = self._fwd_bwd("hier")
        assert got == only(reduce_scatter=2, all_reduce=2, all_gather=2)

    def test_phase_pipelined_ring_fold_drops_broadcast_steps(self):
        # ISSUE 4: the deterministic chunked ring fold's all-gather head
        # overlaps the reduce-scatter tail — completed chunks relay
        # around the ring inside the SAME fused scan, so the trailing
        # full-payload tree-broadcast hops (ceil(log2 N) sequential
        # whole-tensor permutes AFTER the fold loop in the baseline)
        # disappear: fewer sequential permute steps than the two-phase
        # baseline, and every permute is chunk-sized and lives in the
        # loop.
        saved = (mpi.config.ordered_fold_gather_max_bytes(),
                 mpi.config.ordered_ring_chunk_bytes())
        mpi.config.set_ordered_fold_gather_max_bytes(0)  # force ring fold
        mpi.config.set_ordered_ring_chunk_bytes(64)      # 16 f64 -> 2 chunks
        try:
            with mpi.config.deterministic_mode(True):
                mpi.config.set_phase_pipelined_ring(False)
                base, btxt = census(
                    lambda c, v: c.Allreduce(v, mpi.MPI_SUM), self.X)
                mpi.config.set_phase_pipelined_ring(True)
                pipe, ptxt = census(
                    lambda c, v: c.Allreduce(v, mpi.MPI_SUM), self.X)
        finally:
            mpi.config.set_ordered_fold_gather_max_bytes(saved[0])
            mpi.config.set_ordered_ring_chunk_bytes(saved[1])
            mpi.config.set_phase_pipelined_ring(True)
        # baseline: 1 in-loop fold permute + ceil(log2 N) tree hops
        logn = int(math.ceil(math.log2(CENSUS_NR)))
        assert base == only(collective_permute=1 + logn), base
        # pipelined: fold + relay lanes, both inside the one scan — no
        # trailing broadcast permutes at all
        assert pipe == only(collective_permute=2), pipe
        assert pipe["collective_permute"] < base["collective_permute"]
        # the baseline's extra hops are FULL-payload (16 elems); the
        # pipelined program never permutes more than one chunk (8 elems)
        def widths(txt):
            return {int(w) for w in re.findall(
                r"collective_permute.*?:\s*\(tensor<(\d+)x", txt)}
        assert 16 in widths(btxt), widths(btxt)
        assert max(widths(ptxt)) <= 8, widths(ptxt)

    def test_phase_pipelined_ring_fold_bits_identical(self):
        # Pipelining must not touch the fold association: both forms are
        # bit-identical to each other and to the eager oracle.
        rng = np.random.default_rng(29)
        data = jnp.asarray(
            rng.standard_normal((NR, 3000)).astype(np.float32))

        def det_body(x):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM)

        saved = (mpi.config.ordered_fold_gather_max_bytes(),
                 mpi.config.ordered_ring_chunk_bytes())
        mpi.config.set_ordered_fold_gather_max_bytes(0)
        mpi.config.set_ordered_ring_chunk_bytes(1024)
        try:
            with mpi.config.deterministic_mode(True):
                mpi.config.set_phase_pipelined_ring(False)
                base = np.asarray(mpi.run_spmd(det_body)(data))
                mpi.config.set_phase_pipelined_ring(True)
                pipe = np.asarray(mpi.run_spmd(det_body)(data))
        finally:
            mpi.config.set_ordered_fold_gather_max_bytes(saved[0])
            mpi.config.set_ordered_ring_chunk_bytes(saved[1])
            mpi.config.set_phase_pipelined_ring(True)
        np.testing.assert_array_equal(base, pipe)
        oracle = mpi.run_ranks(
            lambda: np.asarray(comm.Allreduce(
                data[comm.rank], mpi.MPI_SUM)), NR)
        for r in range(NR):
            np.testing.assert_array_equal(pipe[r], oracle[r])

    def test_reduce_tree_is_log_permutes(self):
        got, _ = census(
            lambda c, x: c.Reduce_(x, mpi.MPI_SUM, root=0,
                                   algorithm="tree"), self.X)
        assert got == only(
            collective_permute=int(math.ceil(math.log2(CENSUS_NR))))

    def test_reduce_tree_fwd_bwd_adds_tree_bcast(self):
        logn = int(math.ceil(math.log2(CENSUS_NR)))
        got, _ = census(
            lambda c, x: jax.value_and_grad(lambda v: jnp.sum(
                c.Reduce_(v, mpi.MPI_SUM, root=0,
                          algorithm="tree")))(x), self.X)
        # adjoint of the tree reduce is the tree bcast: logn more hops
        assert got == only(collective_permute=2 * logn), got

    def test_bcast_algorithm_override(self):
        # Explicit "ring" pins the masked psum even at tree-regime size;
        # explicit "tree" pins the tree even above the threshold.
        got, _ = census(lambda c, x: c.Bcast_(x, root=1,
                                              algorithm="ring"), self.X)
        assert got == only(all_reduce=1)
        big = jnp.ones((mpi.config.bcast_tree_max_bytes() // 8 + 512,))
        got, _ = census(lambda c, x: c.Bcast_(x, root=1,
                                              algorithm="tree"), big)
        assert got == only(
            collective_permute=int(math.ceil(math.log2(CENSUS_NR))))


# ---------------------------------------------------------------------------
# Selector
# ---------------------------------------------------------------------------


class TestSelector:
    def test_auto_is_ring_without_evidence(self):
        for nbytes in (64, 1 << 20):
            assert tune.select_auto(nbytes=nbytes, dtype=jnp.float32,
                                    nranks=NR) == "ring"

    def test_selection_is_deterministic(self):
        mpi.config.set_latency_crossover_bytes(4096)
        picks = {tune.select_auto(nbytes=512, dtype=jnp.float32,
                                  nranks=NR) for _ in range(5)}
        assert len(picks) == 1

    def test_measured_crossover_drives_latency_pick(self):
        mpi.config.set_latency_crossover_bytes(4096)
        assert tune.select_auto(nbytes=512, dtype=jnp.float32,
                                nranks=NR) == "rhd"
        # non-power-of-two world: tree is the latency fallback
        assert tune.select_auto(nbytes=512, dtype=jnp.float32,
                                nranks=6) == "tree"
        assert tune.select_auto(nbytes=1 << 20, dtype=jnp.float32,
                                nranks=NR) == "ring"

    def test_cached_winner_wins(self):
        tune.record("allreduce", jnp.float32, 512, NR, "tree")
        assert tune.select_auto(nbytes=512, dtype=jnp.float32,
                                nranks=NR) == "tree"
        # a different size bucket is unaffected
        assert tune.select_auto(nbytes=1 << 22, dtype=jnp.float32,
                                nranks=NR) == "ring"

    def test_bandwidth_winner_not_applied_below_latency_crossover(self):
        """ISSUE 10 satellite: decode-sized messages (a few KiB) share
        power-of-two nbytes buckets with training tail buckets, so a
        bandwidth-tier winner cached under such a key must never be
        applied below the measured latency crossover — per-token serving
        traffic stays on the latency tier."""
        tune.record("allreduce", jnp.float32, 2048, NR, "bidir")
        # Without a measured crossover the cached winner is honored.
        assert tune.select_auto(nbytes=2048, dtype=jnp.float32,
                                nranks=NR) == "bidir"
        # With the crossover above it, the bandwidth winner is voided
        # and the latency tier decides.
        mpi.config.set_latency_crossover_bytes(4096)
        assert tune.select_auto(nbytes=2048, dtype=jnp.float32,
                                nranks=NR) == "rhd"
        # A latency-optimal cached winner below the crossover is still
        # honored as recorded (the guard voids bandwidth winners only)…
        tune.record("allreduce", jnp.float32, 2048, NR, "tree")
        assert tune.select_auto(nbytes=2048, dtype=jnp.float32,
                                nranks=NR) == "tree"
        # …and above the crossover a bandwidth winner applies normally.
        tune.record("allreduce", jnp.float32, 1 << 20, NR, "bidir")
        assert tune.select_auto(nbytes=1 << 20, dtype=jnp.float32,
                                nranks=NR) == "bidir"

    def test_tier_guard_exempts_codec_keyed_winners(self):
        """Compressed traffic never shares keys with decode payloads
        (decode is always exact), so the latency-tier guard must honor
        a codec-keyed bandwidth winner below the crossover — voiding it
        would strand the message on ring (the latency algorithms fail
        the codec's declared-algorithm gate)."""
        from mpi4torch_tpu.compress import get_codec

        q8 = get_codec("q8")
        tune.record("allreduce", jnp.float32, 2048, NR, "bidir",
                    codec=q8)
        mpi.config.set_latency_crossover_bytes(4096)
        assert tune.select_auto(nbytes=2048, dtype=jnp.float32,
                                nranks=NR, codec=q8) == "bidir"

    def test_bucket_nbytes_public_rule(self):
        assert tune.bucket_nbytes(1) == 1
        assert tune.bucket_nbytes(3000) == 4096
        assert tune.bucket_nbytes(4096) == 4096

    def test_deterministic_mode_pins_ring(self):
        mpi.config.set_latency_crossover_bytes(4096)
        assert tune.select_auto(nbytes=512, dtype=jnp.float32, nranks=NR,
                                deterministic=True) == "ring"

    def test_codec_restricts_candidates(self):
        from mpi4torch_tpu.compress import get_codec
        mpi.config.set_latency_crossover_bytes(4096)
        assert tune.select_auto(nbytes=512, dtype=jnp.float32, nranks=NR,
                                codec=get_codec("q8")) == "ring"

    def test_codec_applicable_algorithm_leg(self):
        from mpi4torch_tpu.compress import codec_applicable, get_codec
        q8 = get_codec("q8")
        assert codec_applicable(q8, jnp.float32)
        assert codec_applicable(q8, jnp.float32, algorithm="ring")
        assert not codec_applicable(q8, jnp.float32, algorithm="rhd")

    def test_explicit_rhd_non_power_of_two_raises(self):
        with pytest.raises(mpi.CommError, match="power-of-two"):
            mpi.run_spmd(lambda: comm.Allreduce(
                jnp.ones(4), mpi.MPI_SUM, algorithm="rhd"), nranks=6)()
        # same rule on the eager backend
        with pytest.raises(mpi.CommError, match="power-of-two"):
            mpi.run_ranks(lambda: comm.Allreduce(
                jnp.ones(4), mpi.MPI_SUM, algorithm="rhd"), 6)

    def test_scope_rhd_degrades_on_non_power_of_two(self):
        with mpi.config.algorithm_scope("rhd"):
            out = np.asarray(mpi.run_spmd(
                lambda: comm.Allreduce(jnp.ones(4), mpi.MPI_SUM),
                nranks=6)())
        np.testing.assert_allclose(out, 6.0)

    def test_allreduce_scope_leaves_bcast_size_dispatch_alone(self):
        # An allreduce-oriented scope ("rhd" serves allreduce only)
        # must VOID for Bcast_ — back to the tree/psum size dispatch —
        # not pin the masked-psum form (degrade is to auto, not to a
        # literal "ring").
        logn = int(math.ceil(math.log2(CENSUS_NR)))
        with mpi.config.algorithm_scope("rhd"):
            got, _ = census(lambda c, x: c.Bcast_(x, root=1),
                            jnp.ones((16,)))
        assert got == only(collective_permute=logn), got

    def test_bandwidth_crossover_drives_multipath_pick(self):
        # The third tier: latency algorithm below the latency crossover,
        # ring in the middle, the multipath dual-ring at/above the
        # bandwidth crossover.
        mpi.config.set_latency_crossover_bytes(4096)
        mpi.config.set_bandwidth_crossover_bytes(1 << 20)
        assert tune.select_auto(nbytes=512, dtype=jnp.float32,
                                nranks=NR) == "rhd"
        assert tune.select_auto(nbytes=64 * 1024, dtype=jnp.float32,
                                nranks=NR) == "ring"
        assert tune.select_auto(nbytes=4 << 20, dtype=jnp.float32,
                                nranks=NR) == "bidir"
        # any-world: bidir needs no factorization or power of two
        assert tune.select_auto(nbytes=4 << 20, dtype=jnp.float32,
                                nranks=5) == "bidir"

    def test_bandwidth_tier_respects_determinism_and_codecs(self):
        from mpi4torch_tpu.compress import get_codec
        mpi.config.set_bandwidth_crossover_bytes(1 << 20)
        # deterministic mode pins the bit-exact ring fold
        assert tune.select_auto(nbytes=4 << 20, dtype=jnp.float32,
                                nranks=NR, deterministic=True) == "ring"
        # the block-q8 family declares the bandwidth tier: past the
        # crossover, compressed traffic composes with the dual ring (the
        # in-schedule quantized pipeline on both rotations) — the two
        # biggest wire wins multiply instead of excluding each other
        assert tune.select_auto(nbytes=4 << 20, dtype=jnp.float32,
                                nranks=NR, codec=get_codec("q8")) == "bidir"
        # a ring-only codec (bf16: generic encoded-ring pipeline) still
        # keeps large compressed payloads on the ring
        assert tune.select_auto(nbytes=4 << 20, dtype=jnp.float32,
                                nranks=NR,
                                codec=get_codec("bf16")) == "ring"

    def test_cached_multipath_winner_wins(self):
        tune.record("allreduce", jnp.float32, 8 << 20, NR, "torus")
        assert tune.select_auto(nbytes=8 << 20, dtype=jnp.float32,
                                nranks=NR) == "torus"
        # a cached torus winner cannot serve a prime world: auto falls
        # back (never returns an algorithm the backend would reject)
        tune.record("allreduce", jnp.float32, 8 << 20, 5, "torus")
        assert tune.select_auto(nbytes=8 << 20, dtype=jnp.float32,
                                nranks=5) == "ring"

    def test_explicit_hier_on_prime_world_raises(self):
        with pytest.raises(mpi.CommError, match="factorization"):
            mpi.run_spmd(lambda: comm.Allreduce(
                jnp.ones(4), mpi.MPI_SUM, algorithm="hier"), nranks=5)()

    def test_explicit_torus_on_prime_world_raises_scope_degrades(self):
        with pytest.raises(mpi.CommError, match="factorization"):
            mpi.run_spmd(lambda: comm.Allreduce(
                jnp.ones(4), mpi.MPI_SUM, algorithm="torus"), nranks=5)()
        # same rule on the eager backend
        with pytest.raises(mpi.CommError, match="factorization"):
            mpi.run_ranks(lambda: comm.Allreduce(
                jnp.ones(4), mpi.MPI_SUM, algorithm="torus"), 5)
        with mpi.config.algorithm_scope("torus"):
            out = np.asarray(mpi.run_spmd(
                lambda: comm.Allreduce(jnp.ones(4), mpi.MPI_SUM),
                nranks=5)())
            np.testing.assert_allclose(out, 5.0)

    def test_explicit_bidir_works_on_any_world(self):
        for nr in (2, 5):
            out = np.asarray(mpi.run_spmd(
                lambda: comm.Allreduce(jnp.ones(7), mpi.MPI_SUM,
                                       algorithm="bidir"),
                nranks=nr)())
            np.testing.assert_allclose(out, float(nr))

    def test_bidir_scan_form_bitwise_matches_unrolled(self):
        # Past config.chain_unroll_max() ranks each chain phase rolls
        # into a lax.scan (O(1) program size on big pods); the wire
        # schedule — and therefore the bits — must be identical to the
        # unrolled census form.  Force the scan form on the 8-rank
        # world via the promoted config knob (ISSUE 5 satellite; the
        # autouse fixture restores the default).
        rng = np.random.default_rng(31)
        data = jnp.asarray(rng.standard_normal((NR, 37)).astype(np.float32))

        def body(x):
            t = jax.lax.dynamic_index_in_dim(
                x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
            y, g = jax.value_and_grad(lambda v: jnp.vdot(
                comm.Allreduce(v, mpi.MPI_SUM, algorithm="bidir"), v))(t)
            return y, g

        uy, ug = mpi.run_spmd(body)(data)
        mpi.config.set_chain_unroll_max(2)
        sy, sg = mpi.run_spmd(body)(data)
        np.testing.assert_array_equal(np.asarray(uy), np.asarray(sy))
        np.testing.assert_array_equal(np.asarray(ug), np.asarray(sg))

    def test_chain_unroll_max_validated_and_fingerprinted(self):
        # The ISSUE 3 threshold-promotion contract: validated setter +
        # run_spmd jit-cache fingerprint coverage.
        before = mpi.config.thresholds_fingerprint()
        mpi.config.set_chain_unroll_max(7)
        assert mpi.config.chain_unroll_max() == 7
        assert mpi.config.thresholds_fingerprint() != before
        with pytest.raises(ValueError, match="chain_unroll_max"):
            mpi.config.set_chain_unroll_max(0)
        with pytest.raises(ValueError, match="chain_unroll_max"):
            mpi.config.set_chain_unroll_max("many")

    def test_unknown_algorithm_raises(self):
        with pytest.raises(ValueError, match="unknown collective"):
            comm.Allreduce(jnp.ones(4), mpi.MPI_SUM, algorithm="warp9")

    def test_explicit_codec_plus_algorithm_conflict_raises(self):
        with pytest.raises(ValueError, match="ring"):
            mpi.run_spmd(lambda: comm.Allreduce(
                jnp.ones(64, jnp.float32), mpi.MPI_SUM,
                compression="q8", algorithm="rhd"), nranks=NR)()

    def test_rhd_not_valid_for_bcast(self):
        with pytest.raises(mpi.CommError, match="serves"):
            mpi.run_spmd(lambda: comm.Bcast_(
                jnp.ones(4), 0, algorithm="rhd"), nranks=NR)()


# ---------------------------------------------------------------------------
# Cache round-trip
# ---------------------------------------------------------------------------


class TestCacheRoundTrip:
    KEY = dict(collective="allreduce", dtype="float32", nbytes=512,
               nranks=8)

    def test_record_persists_and_reloads(self):
        tune.record("allreduce", "float32", 512, 8, "rhd",
                    measurements={"ring": 1e-3, "rhd": 5e-4})
        path = tune.cache_path()
        with open(path) as f:
            data = json.load(f)
        from mpi4torch_tpu.tune.autotuner import CACHE_VERSION
        assert data["version"] == CACHE_VERSION
        assert any(v["algorithm"] == "rhd" for v in data["entries"].values())
        # fresh in-process table: the entry comes back from disk
        tune.clear()
        assert tune.lookup_algorithm(**self.KEY) == "rhd"
        assert tune.entry_from_disk(**self.KEY)

    def test_corrupt_cache_falls_back_without_crashing(self):
        with open(tune.cache_path(), "w") as f:
            f.write("{ not json ][")
        tune.clear()
        assert tune.lookup(**self.KEY) is None
        assert tune.select_auto(nbytes=512, dtype=jnp.float32,
                                nranks=8) == "ring"
        # and the file is recoverable by the next record
        tune.record("allreduce", "float32", 512, 8, "tree")
        tune.clear()
        assert tune.lookup_algorithm(**self.KEY) == "tree"

    def test_wrong_version_ignored(self):
        with open(tune.cache_path(), "w") as f:
            json.dump({"version": 999, "entries": {
                tune.make_key("allreduce", "float32", 512, 8):
                    {"algorithm": "rhd"}}}, f)
        tune.clear()
        assert tune.lookup(**self.KEY) is None

    def test_stale_algorithm_name_ignored(self):
        # A cache written by a future/older build naming an algorithm
        # this build does not register must not crash or mis-select.
        with open(tune.cache_path(), "w") as f:
            json.dump({"version": 1, "entries": {
                tune.make_key("allreduce", "float32", 512, 8):
                    {"algorithm": "warp9"}}}, f)
        tune.clear()
        assert tune.lookup(**self.KEY) is None
        assert tune.select_auto(nbytes=512, dtype=jnp.float32,
                                nranks=8) == "ring"

    def test_clear_remove_file_resets_to_defaults(self):
        tune.record("allreduce", "float32", 512, 8, "tree")
        tune.clear(remove_file=True)
        assert tune.lookup(**self.KEY) is None

    def test_generation_bumps_on_mutation(self):
        g0 = tune.generation()
        tune.record("allreduce", "float32", 512, 8, "tree")
        assert tune.generation() > g0

    def test_concurrent_saves_union_instead_of_losing_work(self):
        # Two processes tuning simultaneously: each write goes through a
        # UNIQUE tempfile + os.replace (readers never see a torn file)
        # and merges entries the other process persisted meanwhile —
        # last-writer-wins only per key, never whole-file.
        import os
        tune.record("allreduce", "float32", 512, 8, "rhd")
        # simulate the OTHER process persisting its own winner between
        # our record() calls: inject a foreign key directly on disk
        with open(tune.cache_path()) as f:
            data = json.load(f)
        foreign = tune.make_key("allreduce", "float32", 1 << 20, 16,
                                platform="cpu")
        data["entries"][foreign] = {"algorithm": "bidir"}
        with open(tune.cache_path(), "w") as f:
            json.dump(data, f)
        tune.record("allreduce", "float32", 2048, 8, "tree")
        with open(tune.cache_path()) as f:
            final = json.load(f)
        assert final["entries"][foreign]["algorithm"] == "bidir"
        algos = {e["algorithm"] for e in final["entries"].values()}
        assert algos == {"rhd", "tree", "bidir"}
        # no staging litter left behind in the cache directory
        cache_dir = os.path.dirname(tune.cache_path())
        assert not [p for p in os.listdir(cache_dir)
                    if p.endswith(".tmp")]

    def test_unwritable_cache_dir_degrades_in_process(self, monkeypatch,
                                                      tmp_path):
        # The save is best-effort: a cache path whose directory cannot
        # be created degrades to in-process-only tuning, never an error.
        blocker = tmp_path / "blocker"
        blocker.write_text("not a directory")
        monkeypatch.setenv("MPI4TORCH_TPU_TUNE_CACHE",
                           str(blocker / "tune_cache.json"))
        tune.clear()
        tune.record("allreduce", "float32", 512, 8, "tree")
        assert tune.lookup_algorithm("allreduce", "float32", 512,
                                     8) == "tree"


class TestCacheCli:
    """`python -m mpi4torch_tpu.tune --show/--clear` (ISSUE 4
    satellite): the winners table without reading raw JSON."""

    def _run(self, *argv):
        from mpi4torch_tpu.tune.__main__ import _main
        return _main(list(argv))

    def test_show_prints_winners_table(self, capsys):
        tune.record("allreduce", "float32", 512, 8, "rhd",
                    platform="cpu",
                    measurements={"ring": 1e-3, "rhd": 5e-4})
        tune.record("allreduce", "float32", 4 << 20, 8, "bidir",
                    platform="cpu")
        assert self._run("--show") == 0
        out = capsys.readouterr().out
        # one row per key: collective, dtype, size bucket, nranks,
        # platform -> algorithm
        # one row per key; flat (untied) entries show "-" in the tiers
        # column
        assert re.search(r"allreduce\s+float32\s+512\s+8\s+cpu\s+-\s+rhd",
                         out)
        assert re.search(
            r"allreduce\s+float32\s+4194304\s+8\s+cpu\s+-\s+bidir", out)
        assert "2 cached winner(s)" in out

    def test_show_empty_and_missing_cache(self, capsys):
        assert self._run() == 0
        assert "no cache" in capsys.readouterr().out

    def test_clear_removes_file(self, capsys):
        tune.record("allreduce", "float32", 512, 8, "tree")
        assert self._run("--clear") == 0
        tune.clear()
        assert tune.lookup("allreduce", "float32", 512, 8) is None
        assert self._run("--clear") == 0   # idempotent
        assert "no cache file" in capsys.readouterr().out

    def test_json_dump(self, capsys):
        tune.record("allreduce", "float32", 512, 8, "tree")
        assert self._run("--json") == 0
        data = json.loads(capsys.readouterr().out)
        assert any(e["algorithm"] == "tree"
                   for e in data["entries"].values())


class TestAutotunerMeasurement:
    def test_measure_then_serve_from_cache(self):
        sizes = (256, 2048)
        rep = tune.autotune_allreduce(sizes=sizes, nranks=4, iters=1)
        assert rep["tuned_from_cache"] is False
        assert set(rep["entries"]) == {"256", "2048"}
        for ent in rep["entries"].values():
            assert ent["winner"] in ALGOS
            assert set(ent["algorithms"]) >= {"ring", "tree"}
        # The persisted winners serve a second (fresh-table) run with
        # zero measurement — the bench's tuned_from_cache evidence.
        tune.clear()
        rep2 = tune.ensure_tuned_allreduce(sizes=sizes, nranks=4, iters=1)
        assert rep2["tuned_from_cache"] is True
        assert rep2["from_disk"] is True   # table was cleared: real file
        assert {k: v["winner"] for k, v in rep2["entries"].items()} == \
            {k: v["winner"] for k, v in rep["entries"].items()}
        assert "crossover_bytes" in rep2


# ---------------------------------------------------------------------------
# hier on a 2D mesh
# ---------------------------------------------------------------------------


class TestHier2DMesh:
    def _mesh2d(self):
        return mpi.device_mesh({"g": 2, "l": 4})

    def test_single_axis_hier_inside_2d_mesh(self):
        # hier over one axis of a 2D mesh: the grouped schedule must
        # compose with an unrelated second mesh axis in scope.
        mesh = self._mesh2d()
        c = mpi.comm_from_mesh(mesh, "l")
        got, _ = census(
            lambda cc, x: cc.Allreduce(x, mpi.MPI_SUM, algorithm="hier"),
            jnp.arange(12.0), mesh_axes=(mesh, c))
        assert got == only(reduce_scatter=1, all_reduce=1, all_gather=1)
        f = jax.jit(shard_map(
            lambda x: c.Allreduce(x, mpi.MPI_SUM, algorithm="hier"),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))
        x = jnp.arange(12.0)
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x) * 4)

    def test_two_axis_hier_comm_values_and_grads(self):
        mesh = self._mesh2d()
        hc = mpi.comm_from_mesh(mesh, ("g", "l"))
        assert hc.size == 8
        x = jnp.arange(13.0, dtype=jnp.float32)
        f = jax.jit(shard_map(lambda v: hc.Allreduce(v, mpi.MPI_SUM),
                              mesh=mesh, in_specs=P(), out_specs=P(),
                              check_vma=False))
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x) * 8)
        g = jax.jit(shard_map(
            lambda v: jax.grad(lambda y: jnp.vdot(
                hc.Allreduce(y, mpi.MPI_SUM), y))(v),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))(x)
        # adjoint of a sum-allreduce: allreduce of 2x, itself summed
        np.testing.assert_allclose(np.asarray(g), np.asarray(x) * 16)

    def test_two_axis_hier_census_is_rs_ar_ag(self):
        mesh = self._mesh2d()
        hc = mpi.comm_from_mesh(mesh, ("g", "l"))
        got, _ = census(lambda cc, x: cc.Allreduce(x, mpi.MPI_SUM),
                        jnp.arange(12.0), mesh_axes=(mesh, hc))
        assert got == only(reduce_scatter=1, all_reduce=1, all_gather=1)

    def test_two_axis_deterministic_matches_eager_grouped_bitwise(self):
        mesh = self._mesh2d()
        hc = mpi.comm_from_mesh(mesh, ("g", "l"))
        rng = np.random.default_rng(17)
        data = jnp.asarray(rng.standard_normal((8, 21)).astype(np.float32))

        def det_body(x):
            t = jax.lax.dynamic_index_in_dim(
                x, hc.rank, 0, keepdims=False)
            return hc.Allreduce(t, mpi.MPI_SUM)

        with mpi.config.deterministic_mode(True):
            f = jax.jit(shard_map(det_body, mesh=mesh, in_specs=P(),
                                  out_specs=P(("g", "l")),
                                  check_vma=False))
            a_out = np.asarray(f(data)).reshape(8, -1)
        # the 2-axis group is the inner axis extent (4 consecutive
        # ranks); the eager hier fold with the same group matches bitwise
        mpi.config.set_hier_group_size(4)
        try:
            b_out = mpi.run_ranks(
                lambda: np.asarray(comm.Allreduce(
                    data[comm.rank], mpi.MPI_SUM, algorithm="hier")), 8)
        finally:
            mpi.config.set_hier_group_size(None)
        for r in range(8):
            np.testing.assert_array_equal(a_out[0], b_out[r])

    def test_two_axis_torus_census_one_channel_per_axis(self):
        # The ISSUE 4 acceptance criterion: torus on a 2D mesh lowers to
        # one ring channel per axis — the halves' first-stage grouped
        # reduce_scatters ride the inner ("l") and outer ("g") mesh axes
        # respectively (distinct replica_groups), with no dependency
        # between the halves.
        mesh = self._mesh2d()
        hc = mpi.comm_from_mesh(mesh, ("g", "l"))
        got, txt = census(
            lambda cc, x: cc.Allreduce(x, mpi.MPI_SUM, algorithm="torus"),
            jnp.arange(12.0), mesh_axes=(mesh, hc))
        assert got == only(reduce_scatter=2, all_reduce=2, all_gather=2)
        groups = set(re.findall(
            r"reduce_scatter.*?replica_groups = dense<(\[\[.*?\]\])>",
            txt))
        assert groups == {"[[0, 1, 2, 3], [4, 5, 6, 7]]",
                          "[[0, 4], [1, 5], [2, 6], [3, 7]]"}, groups

    def test_two_axis_torus_values_and_grads(self):
        mesh = self._mesh2d()
        hc = mpi.comm_from_mesh(mesh, ("g", "l"))
        x = jnp.arange(13.0, dtype=jnp.float32)
        f = jax.jit(shard_map(
            lambda v: hc.Allreduce(v, mpi.MPI_SUM, algorithm="torus"),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x) * 8)
        g = jax.jit(shard_map(
            lambda v: jax.grad(lambda y: jnp.vdot(
                hc.Allreduce(y, mpi.MPI_SUM, algorithm="torus"), y))(v),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))(x)
        np.testing.assert_allclose(np.asarray(g), np.asarray(x) * 16)

    def test_two_axis_torus_deterministic_matches_eager_bitwise(self):
        # Mode A (2-axis torus schedule, deterministic grouped-halves
        # fold) vs Mode B (constants.reduce_torus with inner = the
        # inner-axis extent): the ISSUE 4 A/B contract on a 2D-mesh
        # world.
        mesh = self._mesh2d()
        hc = mpi.comm_from_mesh(mesh, ("g", "l"))
        rng = np.random.default_rng(23)
        data = jnp.asarray(rng.standard_normal((8, 21)).astype(np.float32))

        def det_body(x):
            t = jax.lax.dynamic_index_in_dim(
                x, hc.rank, 0, keepdims=False)
            return hc.Allreduce(t, mpi.MPI_SUM, algorithm="torus")

        with mpi.config.deterministic_mode(True):
            f = jax.jit(shard_map(det_body, mesh=mesh, in_specs=P(),
                                  out_specs=P(("g", "l")),
                                  check_vma=False))
            a_out = np.asarray(f(data)).reshape(8, -1)
        mpi.config.set_hier_group_size(4)
        try:
            b_out = mpi.run_ranks(
                lambda: np.asarray(comm.Allreduce(
                    data[comm.rank], mpi.MPI_SUM, algorithm="torus")), 8)
        finally:
            mpi.config.set_hier_group_size(None)
        for r in range(8):
            np.testing.assert_array_equal(a_out[0], b_out[r])

    def test_two_axis_auto_picks_torus_past_bandwidth_crossover(self):
        # The 2-axis backend grows the bandwidth tier too: auto = the
        # staged hier schedule below the measured crossover, the
        # multipath torus striping at/above it.
        mesh = self._mesh2d()
        hc = mpi.comm_from_mesh(mesh, ("g", "l"))
        mpi.config.set_bandwidth_crossover_bytes(1 << 10)
        big = jnp.ones((512,))    # 4 KiB f64 >= crossover
        got, _ = census(lambda cc, x: cc.Allreduce(x, mpi.MPI_SUM),
                        big, mesh_axes=(mesh, hc))
        assert got == only(reduce_scatter=2, all_reduce=2, all_gather=2)
        small = jnp.ones((16,))   # 128 B < crossover: staged hier
        got, _ = census(lambda cc, x: cc.Allreduce(x, mpi.MPI_SUM),
                        small, mesh_axes=(mesh, hc))
        assert got == only(reduce_scatter=1, all_reduce=1, all_gather=1)

    def test_two_axis_comm_rejects_other_ops_and_algorithms(self):
        mesh = self._mesh2d()
        hc = mpi.comm_from_mesh(mesh, ("g", "l"))
        with pytest.raises(mpi.CommError, match="Allreduce only"):
            jax.jit(shard_map(lambda x: hc.Bcast_(x, 0), mesh=mesh,
                              in_specs=P(), out_specs=P(),
                              check_vma=False)).lower(jnp.ones(4))
        with pytest.raises(mpi.CommError, match="single-axis"):
            jax.jit(shard_map(
                lambda x: hc.Allreduce(x, mpi.MPI_SUM, algorithm="rhd"),
                mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False)).lower(jnp.ones(4))
        # bidir needs a single ring axis too: explicit raises, scope
        # yields to the native schedule
        with pytest.raises(mpi.CommError, match="single-axis"):
            jax.jit(shard_map(
                lambda x: hc.Allreduce(x, mpi.MPI_SUM,
                                       algorithm="bidir"),
                mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False)).lower(jnp.ones(4))
        with mpi.config.algorithm_scope("bidir"):
            got, _ = census(lambda cc, x: cc.Allreduce(x, mpi.MPI_SUM),
                            jnp.ones(16), mesh_axes=(mesh, hc))
        assert got == only(reduce_scatter=1, all_reduce=1, all_gather=1)

    def test_invalid_config_group_raises(self):
        mpi.config.set_hier_group_size(3)   # does not divide 8
        try:
            with pytest.raises(mpi.CommError, match="hier_group_size"):
                mpi.run_spmd(lambda: comm.Allreduce(
                    jnp.ones(4), mpi.MPI_SUM, algorithm="hier"),
                    nranks=NR)()
        finally:
            mpi.config.set_hier_group_size(None)

    def test_scope_hier_with_invalid_config_group_degrades(self):
        # Same misconfiguration, but as a SCOPE default: degrade to
        # ring (the facade's degrade/raise rule reaches backend-side
        # validation too), on both backends.
        mpi.config.set_hier_group_size(3)   # does not divide 8
        try:
            with mpi.config.algorithm_scope("hier"):
                out = np.asarray(mpi.run_spmd(
                    lambda: comm.Allreduce(jnp.ones(4), mpi.MPI_SUM),
                    nranks=NR)())
                np.testing.assert_allclose(out, float(NR))
                res = mpi.run_ranks(lambda: np.asarray(
                    comm.Allreduce(jnp.ones(4), mpi.MPI_SUM)), NR)
                np.testing.assert_allclose(res[0], float(NR))
        finally:
            mpi.config.set_hier_group_size(None)

    def test_explicit_hier_on_degenerate_two_axis_mesh(self):
        # The flat-world registry gate (group factorization of the rank
        # PRODUCT) must not veto an explicit "hier" on a 2-axis comm —
        # the tiers are the mesh axes themselves, so even a product
        # with no nontrivial divisor lowers fine.
        mesh = mpi.device_mesh({"g": 2, "l": 1},
                               devices=jax.devices()[:2])
        hc = mpi.comm_from_mesh(mesh, ("g", "l"))
        x = jnp.arange(5.0)
        f = jax.jit(shard_map(
            lambda v: hc.Allreduce(v, mpi.MPI_SUM, algorithm="hier"),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x) * 2)

    def test_scope_algorithm_degrades_on_two_axis_comm(self):
        # A scope default the 2-axis backend cannot lower must yield to
        # its native hier schedule, not raise (only explicit rhd/tree
        # raise — covered above).
        mesh = self._mesh2d()
        hc = mpi.comm_from_mesh(mesh, ("g", "l"))
        x = jnp.arange(9.0)
        with mpi.config.algorithm_scope("tree"):
            f = jax.jit(shard_map(
                lambda v: hc.Allreduce(v, mpi.MPI_SUM), mesh=mesh,
                in_specs=P(), out_specs=P(), check_vma=False))
            np.testing.assert_allclose(np.asarray(f(x)),
                                       np.asarray(x) * 8)

    def test_scope_codec_degrades_on_two_axis_comm(self):
        # No compressed pipeline on the 2-axis backend: a scope codec
        # degrades to the exact wire; an explicit one raises.
        mesh = self._mesh2d()
        hc = mpi.comm_from_mesh(mesh, ("g", "l"))
        x = jnp.arange(9.0, dtype=jnp.float32)
        with mpi.config.compression_scope("q8"):
            f = jax.jit(shard_map(
                lambda v: hc.Allreduce(v, mpi.MPI_SUM), mesh=mesh,
                in_specs=P(), out_specs=P(), check_vma=False))
            np.testing.assert_allclose(np.asarray(f(x)),
                                       np.asarray(x) * 8)
        with pytest.raises(ValueError, match="compressed pipeline"):
            jax.jit(shard_map(
                lambda v: hc.Allreduce(v, mpi.MPI_SUM,
                                       compression="q8"),
                mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False)).lower(x)

    def test_two_axis_comm_scope_and_fused_paths(self):
        # Scope defaults the 2-axis backend cannot lower must yield to
        # its native schedule through EVERY entry point — including the
        # fused tree, whose per-bucket facade calls forward resolved
        # names as explicit; and algorithm=False must force auto (hier)
        # even inside a scope.
        mesh = self._mesh2d()
        hc = mpi.comm_from_mesh(mesh, ("g", "l"))
        x = {"a": jnp.arange(7.0), "b": jnp.ones((5,))}
        with mpi.config.algorithm_scope("rhd"):
            f = jax.jit(shard_map(
                lambda t: hc.Allreduce_tree(t, mpi.MPI_SUM),
                mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False))
            out = f(x)
            np.testing.assert_allclose(np.asarray(out["a"]),
                                       np.asarray(x["a"]) * 8)
        with mpi.config.algorithm_scope("ring"):
            got, _ = census(
                lambda cc, v: cc.Allreduce(v, mpi.MPI_SUM,
                                           algorithm=False),
                jnp.arange(12.0), mesh_axes=(mesh, hc))
        # False overrides the ring scope: auto = the native 2-level
        # schedule, not the flat psum
        assert got == only(reduce_scatter=1, all_reduce=1, all_gather=1)

    def test_backend_attribute_protocol_intact(self):
        # __getattr__ must stay protocol-correct: hasattr/getattr with
        # a default return normally for non-collective names; only the
        # known unsupported ops get the informative CommError.
        from mpi4torch_tpu.ops.spmd import HierMeshBackend
        hb = HierMeshBackend(("g", "l"), (2, 4))
        assert not hasattr(hb, "no_such_attribute")
        assert getattr(hb, "also_missing", None) is None
        with pytest.raises(mpi.CommError, match="Allreduce only"):
            hb.gather


# ---------------------------------------------------------------------------
# Fused per-bucket algorithm picks
# ---------------------------------------------------------------------------


class TestFusePerBucket:
    TREE = {"big": jnp.ones((3000,), jnp.float32),
            "small": jnp.ones((10,), jnp.float32)}

    def test_small_tail_bucket_takes_latency_algorithm(self):
        logn = int(math.log2(CENSUS_NR))
        mpi.config.set_latency_crossover_bytes(1024)
        got, _ = census(
            lambda c, t: c.Allreduce_tree(t, mpi.MPI_SUM,
                                          bucket_bytes=8192), self.TREE)
        # body bucket: the ring reduce-scatter + all-gather pair; tail
        # bucket (40 B < crossover): the rhd butterfly
        assert got == only(reduce_scatter=1, all_gather=1,
                           collective_permute=2 * logn), got

    def test_body_bucket_takes_bidir_past_bandwidth_crossover(self):
        # Three-tier fused picks (ISSUE 4): the body bucket (12000 B,
        # past the bandwidth crossover) rides the multipath dual-ring —
        # two counter-rotating chains — while the 40 B tail bucket keeps
        # the latency algorithm; no ring pair remains.
        logn = int(math.log2(CENSUS_NR))
        mpi.config.set_latency_crossover_bytes(1024)
        mpi.config.set_bandwidth_crossover_bytes(8192)
        got, _ = census(
            lambda c, t: c.Allreduce_tree(t, mpi.MPI_SUM,
                                          bucket_bytes=8192), self.TREE)
        assert got == only(
            collective_permute=4 * (CENSUS_NR - 1) + 2 * logn), got

    def test_without_crossover_all_buckets_keep_ring_pair(self):
        got, _ = census(
            lambda c, t: c.Allreduce_tree(t, mpi.MPI_SUM,
                                          bucket_bytes=8192), self.TREE)
        assert got == only(reduce_scatter=2, all_gather=2), got

    def test_explicit_algorithm_pins_every_bucket(self):
        logn = int(math.ceil(math.log2(CENSUS_NR)))
        got, _ = census(
            lambda c, t: c.Allreduce_tree(t, mpi.MPI_SUM,
                                          bucket_bytes=8192,
                                          algorithm="tree"), self.TREE)
        assert got == only(collective_permute=2 * 2 * logn), got

    def test_compressed_buckets_stay_on_ring(self):
        mpi.config.set_latency_crossover_bytes(1024)
        _, txt = census(
            lambda c, t: c.Allreduce_tree(t, mpi.MPI_SUM,
                                          compression="q8",
                                          bucket_bytes=8192), self.TREE)
        # every bucket rides the quantized ring (int8 permutes); the
        # latency pick must not hijack a compressed bucket
        assert re.search(r"collective_permute.*xi8>", txt)

    def test_scope_hier_with_invalid_group_degrades_in_fused_path(self):
        # The fused path forwards per-bucket picks to comm.Allreduce as
        # explicit; backend-side applicability (config.hier_group_size
        # not dividing the comm) must still follow the scope-default
        # degrade rule — same observable as the bare facade call.
        mpi.config.set_hier_group_size(3)   # does not divide 8
        try:
            with mpi.config.algorithm_scope("hier"):
                out = mpi.run_spmd(lambda: comm.Allreduce_tree(
                    self.TREE, mpi.MPI_SUM, bucket_bytes=8192),
                    nranks=NR)()
            np.testing.assert_allclose(np.asarray(out["small"][0]),
                                       float(NR))
        finally:
            mpi.config.set_hier_group_size(None)

    def test_conflict_exception_type_matches_facade(self):
        # The same user error must raise the same exception type
        # through both entry points (one shared reconcile helper).
        with pytest.raises(ValueError, match="ring"):
            mpi.run_spmd(lambda: comm.Allreduce_tree(
                self.TREE, mpi.MPI_SUM, compression="q8",
                algorithm="rhd"), nranks=NR)()

    def test_int_buckets_keep_scope_algorithm_under_codec_scope(self):
        # A non-float bucket drops the scope codec (dtype degrade) and
        # must then honor the scope algorithm — matching what the
        # per-tensor facade does on the bare tensor (reconciliation is
        # per bucket, not tree-wide).
        logn = int(math.ceil(math.log2(CENSUS_NR)))
        itree = {"i": jnp.ones((64,), jnp.int32)}
        with mpi.config.compression_scope("q8"), \
                mpi.config.algorithm_scope("tree"):
            got, _ = census(
                lambda c, t: c.Allreduce_tree(t, mpi.MPI_SUM,
                                              bucket_bytes=8192), itree)
        assert got == only(collective_permute=2 * logn), got

    def test_fused_values_match_per_leaf(self):
        mpi.config.set_latency_crossover_bytes(1024)

        def body():
            return comm.Allreduce_tree(self.TREE, mpi.MPI_SUM,
                                       bucket_bytes=8192, mean=True)

        out = mpi.run_spmd(body, nranks=NR)()
        np.testing.assert_allclose(np.asarray(out["big"][0]), 1.0)
        np.testing.assert_allclose(np.asarray(out["small"][0]), 1.0)


# ---------------------------------------------------------------------------
# Config knobs
# ---------------------------------------------------------------------------


class TestConfigKnobs:
    def test_threshold_validation(self):
        with pytest.raises(ValueError):
            mpi.config.set_ordered_ring_chunk_bytes(0)
        with pytest.raises(ValueError):
            mpi.config.set_bcast_tree_max_bytes(-1)
        with pytest.raises(ValueError):
            mpi.config.set_latency_crossover_bytes("lots")
        with pytest.raises(ValueError):
            mpi.config.set_hier_group_size(1)
        with pytest.raises(ValueError):
            mpi.config.set_default_algorithm("warp9")

    def test_threshold_roundtrip_and_fingerprint(self):
        saved = mpi.config.bcast_tree_max_bytes()
        fp0 = mpi.config.thresholds_fingerprint()
        try:
            mpi.config.set_bcast_tree_max_bytes(12345)
            assert mpi.config.bcast_tree_max_bytes() == 12345
            assert mpi.config.thresholds_fingerprint() != fp0
        finally:
            mpi.config.set_bcast_tree_max_bytes(saved)
        assert mpi.config.thresholds_fingerprint() == fp0

    def test_algorithm_scope_nesting(self):
        assert mpi.config.default_algorithm() is None
        with mpi.config.algorithm_scope("tree"):
            assert mpi.config.default_algorithm() == "tree"
            with mpi.config.algorithm_scope(None):
                assert mpi.config.default_algorithm() is None
            assert mpi.config.default_algorithm() == "tree"
        assert mpi.config.default_algorithm() is None

    def test_autotuner_can_override_promoted_thresholds(self):
        # The promoted thresholds accept measured overrides (the
        # autotuner writes latency_crossover; bench_tradeoffs feeds the
        # other three) — the setters are the override surface.
        saved = (mpi.config.ordered_fold_gather_max_bytes(),
                 mpi.config.ordered_ring_chunk_bytes())
        try:
            mpi.config.set_ordered_fold_gather_max_bytes(1 << 16)
            mpi.config.set_ordered_ring_chunk_bytes(1 << 12)
            assert mpi.config.ordered_fold_gather_max_bytes() == 1 << 16
            assert mpi.config.ordered_ring_chunk_bytes() == 1 << 12
        finally:
            mpi.config.set_ordered_fold_gather_max_bytes(saved[0])
            mpi.config.set_ordered_ring_chunk_bytes(saved[1])


# ---------------------------------------------------------------------------
# (algorithm × codec) census: every pair the registries compose, guarded
# ---------------------------------------------------------------------------


class TestCodecAlgorithmCensus:
    """One forward HLO census per (codec-capable algorithm × codec)
    pair — parametrized from the LIVE registries
    (_codec_algorithm_pairs), so an unguarded combination cannot exist:
    registering one makes a census test appear, and the registry-sync
    guard pins the enumeration rules.  The expected collective counts
    are STRUCTURAL: per error-feedback round and per multipath channel,
    a quantized ring is (n-1) permute hops of the payload leaves plus
    one encoded all-gather of each leaf."""

    # big enough that both multipath halves are non-empty and span
    # multiple q8 blocks per chunk
    X = jnp.ones((4096,), jnp.float32)

    @pytest.mark.parametrize("algo,codec", _codec_algorithm_pairs())
    def test_pair_census(self, algo, codec):
        from mpi4torch_tpu.compress import get_codec

        cobj = get_codec(codec)
        leaves = len(jax.tree_util.tree_leaves(
            cobj.base().encode(jnp.ones(64, jnp.float32))[0]))
        channels = 2 if algo in ("bidir", "torus") else 1
        rounds = cobj.ef_rounds
        got, txt = census(
            lambda c, x: c.Allreduce(x, mpi.MPI_SUM, compression=codec,
                                     algorithm=algo), self.X)
        n = CENSUS_NR
        assert got["all_reduce"] == 0, (algo, codec, got)
        assert got["collective_permute"] == \
            rounds * channels * (n - 1) * leaves, (algo, codec, got)
        assert got["all_gather"] == rounds * channels * leaves, \
            (algo, codec, got)
        if cobj.base().hop_fused:
            # the quantized payload rides int8 end-to-end
            assert re.search(r"collective_permute.*xi8>", txt)
            assert re.search(r"all_gather.*xi8>", txt)

    @pytest.mark.parametrize("codec", ["q8", "q8_ef_hop"])
    def test_bidir_int8_permutes_on_both_rotations(self, codec):
        # The tentpole's census criterion: int8 collective_permutes on
        # BOTH source_target_pairs rotations of the dual ring.
        from mpi4torch_tpu.compress import int8_rotation_census

        _, txt = census(
            lambda c, x: c.Allreduce(x, mpi.MPI_SUM, compression=codec,
                                     algorithm="bidir"), self.X)
        norm, fwd, bwd = int8_rotation_census(txt, CENSUS_NR)
        assert fwd in norm and bwd in norm, (
            f"int8 permutes must ride both rotations; saw {sorted(norm)}")

    def test_bidir_fwd_bwd_census_doubles_with_swapped_rotations(self):
        # AD transparency on the multipath wire: the backward is the
        # same dual-ring schedule with channel directions swapped, so
        # the fwd+bwd program has exactly 2x the quantized collectives.
        got, txt = census(
            lambda c, x: jax.value_and_grad(lambda v: jnp.vdot(
                c.Allreduce(v, mpi.MPI_SUM, compression="q8",
                            algorithm="bidir"), v))(x), self.X)
        n = CENSUS_NR
        assert got["collective_permute"] == 2 * 2 * 2 * (n - 1)
        assert got["all_gather"] == 2 * 2 * 2
        assert got["all_reduce"] == 0

    def test_codec_keyed_cache_dimension(self):
        # The tune cache's codec dimension: compressed winners live
        # under their own keys and cannot hijack exact traffic.
        key_exact = tune.make_key("allreduce", jnp.float32, 1 << 20, NR,
                                  platform="cpu")
        key_q8 = tune.make_key("allreduce", jnp.float32, 1 << 20, NR,
                               platform="cpu", codec="q8")
        assert key_exact != key_q8 and key_q8.endswith("codec=q8")
        tune.record("allreduce", jnp.float32, 1 << 20, NR, "torus",
                    codec="q8")
        from mpi4torch_tpu.compress import get_codec
        assert tune.select_auto(nbytes=1 << 20, dtype=jnp.float32,
                                nranks=NR, codec=get_codec("q8")) == "torus"
        # exact traffic is untouched by the compressed winner
        assert tune.select_auto(nbytes=1 << 20, dtype=jnp.float32,
                                nranks=NR) == "ring"

    def test_autotune_sweep_codec_dimension(self):
        # The sweep's codec leg records winners under codec keys and
        # restricts candidates to what the codec declares.
        report = tune.autotune_allreduce(
            sizes=(1 << 12,), nranks=4, iters=1, persist=False,
            codecs=(None, "q8"))
        ent = report["entries"][str(1 << 12)]
        assert "winner" in ent                      # exact sweep intact
        q8_ent = ent["codecs"]["q8"]
        assert set(q8_ent["algorithms"]) <= set(CODEC_CAPABLE)
        assert "winner" in q8_ent
        assert tune.lookup_algorithm("allreduce", jnp.float32, 1 << 12, 4,
                                     codec="q8") == q8_ent["winner"]


class TestMeasurementRobustness:
    """ISSUE 7 satellite: per-size measurement is min-of-k, so a single
    preempted/slow sample cannot poison a persisted cache winner."""

    def test_time_step_is_outlier_immune(self):
        # 3 of the 5 timed samples are hit by a simulated preemption
        # pause — the OLD median-of-k would report >= the pause; the
        # min-of-k estimate must stay at the true (fast) step cost.
        import time as _time

        from mpi4torch_tpu.tune import autotuner as at

        calls = {"n": 0}

        def step(x):
            calls["n"] += 1
            # calls 1-2 are warmup; timed samples are calls 3..7 — hit
            # the 2nd, 3rd and 4th timed samples (median territory).
            if calls["n"] in (4, 5, 6):
                _time.sleep(0.12)
            return (x,)

        dt = at._time_step(step, jnp.ones((8,), jnp.float32), iters=5)
        assert dt < 0.06, (
            f"min-of-k must shrug off one-sided outliers, got {dt}")

    def test_outlier_cannot_flip_a_winner(self):
        # The decision-level regression: with the measurement rule
        # applied to two candidates' raw sample sets, a preemption hit
        # on the TRUE winner must not hand the cache key to the loser.
        # (Median-of-5 flips here: 3 of ring's 5 samples are hit.)
        from mpi4torch_tpu.tune import autotuner as at

        ring_samples = [0.001, 0.50, 0.48, 0.52, 0.001]   # true 1ms
        tree_samples = [0.002] * 5                        # true 2ms

        def measure(samples):
            # Drive _time_step's clock: each timed step() call advances
            # a fake perf_counter by its scripted duration (warmups: 0).
            import time as _time

            real = _time.perf_counter
            acc = {"t": 0.0}
            calls = {"n": 0}

            def step(x):
                calls["n"] += 1
                if calls["n"] > 2:   # calls 1-2 are warmup
                    acc["t"] += samples[calls["n"] - 3]
                return (x,)

            _time.perf_counter = lambda: acc["t"]
            try:
                return at._time_step(step, jnp.ones((4,), jnp.float32),
                                     iters=len(samples))
            finally:
                _time.perf_counter = real

        assert measure(ring_samples) < measure(tree_samples), (
            "the outlier-hit true winner must still measure fastest")

    def test_cache_version_keys_in_the_min_rule(self):
        # Winners measured under the old median rule must be discarded:
        # the measurement-rule change rides the cache version.
        from mpi4torch_tpu.tune import autotuner as at

        assert at.CACHE_VERSION >= 2

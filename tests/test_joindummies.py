"""Port of the reference JoinDummies gradient-semantics test
(reference: tests/test_joindummies.py:1-18): dummies receive zero gradients,
the loop-through receives the real gradient."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm, run_ranks


@pytest.mark.parametrize("nranks", [2, 5, 7])
def test_simple_allreduce(nranks):
    def body():
        tmp = jnp.asarray(np.random.rand(10))
        tmp2 = jnp.asarray(np.random.rand(10))
        tmp3 = jnp.asarray(np.random.rand(10))

        def loss(t, t2, t3):
            res = comm.Allreduce(t, mpi.MPI_SUM)
            res2 = mpi.JoinDummies(res, [t2, t3])
            return res2.sum()

        g1, g2, g3 = jax.grad(loss, argnums=(0, 1, 2))(tmp, tmp2, tmp3)
        assert (g2 == jnp.zeros(10)).all()
        assert (g3 == jnp.zeros(10)).all()
        assert (g1 == comm.size * jnp.ones(10)).all()

    run_ranks(body, nranks)


def test_no_dummies_is_identity():
    # reference: csrc/extension.cpp:1030-1033 — with no dummies the input is
    # returned untouched.
    x = jnp.ones(3)
    assert mpi.JoinDummies(x, []) is x


def test_mixed_dtype_dummies():
    # Descriptors (float32) and payloads (float64) are commonly mixed in the
    # dummies list (reference usage: examples/isend-recv-wait.py:8-13).
    def body():
        x = jnp.asarray(np.random.rand(4))
        d = jnp.zeros(8, jnp.float32)

        def loss(t, dd):
            return mpi.JoinDummies(t, [dd]).sum()

        g1, g2 = jax.grad(loss, argnums=(0, 1))(x, d)
        assert (g1 == jnp.ones(4)).all()
        assert g2.dtype == jnp.float32 and (g2 == 0).all()

    run_ranks(body, 2)

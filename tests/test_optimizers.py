"""Optimizer interop: the reference's DP recipe works with ANY
optimizer because the parameter-averaging Allreduce keeps per-rank
optimizer instances arithmetically identical (reference
doc/examples.rst:46-65, demonstrated there with torch LBFGS).  The
analogue here: any optax GradientTransformation composes with the same
two-Allreduce loss unchanged — per-rank Adam states stay in lock-step
and the trajectory is rank-count invariant.  (The eager LBFGS port
lives in utils/lbfgs.py with its own tests; optax's line-search
variants need in-jit tracing the eager backend refuses by design.)"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu.parallel import all_average_tree

N, D, STEPS = 64, 4, 25


def _data():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((N, D)))
    w_true = jnp.asarray(rng.standard_normal((D,)))
    y = x @ w_true + 0.1 * jnp.asarray(rng.standard_normal((N,)))
    return x, y


def _train_single(opt, x, y):
    params = jnp.zeros((D,))
    state = opt.init(params)
    traj = []
    for _ in range(STEPS):
        loss, g = jax.value_and_grad(
            lambda p: jnp.sum((y - x @ p) ** 2))(params)
        updates, state = opt.update(g, state, params)
        params = optax.apply_updates(params, updates)
        traj.append(float(loss))
    return params, traj


@pytest.mark.parametrize("nranks", [2, 4])
@pytest.mark.parametrize("make_opt", [
    lambda: optax.adam(1e-1),
    lambda: optax.sgd(1e-3, momentum=0.9),
], ids=["adam", "sgd-momentum"])
def test_optax_dp_lockstep_matches_single_process(nranks, make_opt):
    x, y = _data()
    ref_params, ref_traj = _train_single(make_opt(), x, y)
    shard = N // nranks

    def body():
        comm = mpi.COMM_WORLD
        xl = x[comm.rank * shard:(comm.rank + 1) * shard]
        yl = y[comm.rank * shard:(comm.rank + 1) * shard]
        opt = make_opt()
        params = jnp.zeros((D,))
        state = opt.init(params)
        traj = []

        def loss_fn(p):
            # The reference recipe: averaging the params makes the
            # adjoint divide the summed cotangents by size, so the
            # Allreduce'd local losses produce the GLOBAL gradient on
            # every rank — optimizer states never diverge.
            p = all_average_tree(comm, p)
            local = jnp.sum((yl - xl @ p) ** 2)
            return comm.Allreduce(local, mpi.MPI_SUM)

        for _ in range(STEPS):
            loss, g = jax.value_and_grad(loss_fn)(params)
            updates, state = opt.update(g, state, params)
            params = optax.apply_updates(params, updates)
            traj.append(float(loss))
        return np.asarray(params), traj

    outs = mpi.run_ranks(body, nranks)
    p0, t0 = outs[0]
    for p, t in outs[1:]:
        np.testing.assert_array_equal(p, p0)      # bit-identical ranks
        assert t == t0
    np.testing.assert_allclose(t0, ref_traj, rtol=1e-9)
    np.testing.assert_allclose(p0, np.asarray(ref_params), rtol=1e-9,
                               atol=1e-12)

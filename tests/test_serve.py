"""mpi4torch_tpu.serve — continuous-batching inference serving
(ISSUE 10).

Coverage per the acceptance criteria:

* engine-vs-oracle TOKEN parity: the continuously-batched engine emits
  exactly the tokens of per-request ``models/transformer.generate`` —
  across admission/eviction churn, on (1,), (4,) and (2,4) worlds,
  Mode A (run_spmd) and Mode B (run_ranks), greedy AND sampled, under
  every registered scheduling policy (the matrix parametrizes over
  :data:`serve.POLICIES`, so a policy registered without parity
  coverage fails here — the registry-sync guard pins the known set);
* slot-table semantics: slot reuse after eviction, full-capacity
  rejection (``QueueFullError``), occupancy/eviction counters, and the
  NaN-poisoned free-slot inertness proof (poisoned rows never move live
  rows' logits by a single bit);
* the deterministic censuses: ``scheduled_exposure`` of the lowered
  decode step strictly < 1.0 with overlap on (blocking baseline 1.0),
  and the latency-tier evidence — ``latency_report`` + the resolved
  ``Allreduce_start.rhd`` span in the lowered program;
* Mode A/Mode B bitwise parity of ``decode_step_tp`` under
  ``deterministic_mode``;
* the ZeRO-3 → TP admission recipe (``admit_zero3`` bitwise equal to
  the gather-then-slice route, plus the serving-dtype override);
* fault composition: a ``rank_death`` mid-decode raises an attributed
  ``RankFailedError`` on every survivor.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import serve, tune
from mpi4torch_tpu.models import transformer as T
from mpi4torch_tpu.serve import kv

CFG = T.TransformerConfig(vocab=37, d_model=16, n_heads=4, n_layers=2,
                          d_ff=32, max_seq=24)
CFG_GQA = dataclasses.replace(CFG, n_kv_heads=2)
CFG_ROPE = dataclasses.replace(CFG, rope=True)
CFG_SWIGLU = dataclasses.replace(CFG, ffn="swiglu")

PROMPTS = [np.array([1, 2, 3]), np.array([4, 5, 6, 7, 8]),
           np.array([9, 10]), np.array([11, 12, 13, 14])]
BUDGETS = [6, 4, 5, 3]


def _params(cfg, seed=0):
    return T.init_transformer(jax.random.PRNGKey(seed), cfg,
                              dtype=jnp.float64)


def oracle_tokens(cfg, params, prompt, n_new, eos=None, temperature=0.0,
                  top_k=0, key=None):
    out = T.generate(cfg, params, jnp.asarray(prompt, jnp.int32)[None, :],
                     n_new, dtype=jnp.float64, temperature=temperature,
                     top_k=top_k, key=key)
    seq = np.asarray(out[0])
    if eos is not None:
        gen = seq[len(prompt):]
        hits = np.where(gen == eos)[0]
        if hits.size:
            seq = seq[:len(prompt) + hits[0] + 1]
    return seq


def drive(eng, keys=None):
    for i, (p, n) in enumerate(zip(PROMPTS, BUDGETS)):
        eng.submit(p, max_new=n,
                   key=None if keys is None else keys[i])
    return eng.run()


def assert_matches_oracle(cfg, params, results, eos=None,
                          temperature=0.0, top_k=0, keys=None):
    for i, (p, n) in enumerate(zip(PROMPTS, BUDGETS)):
        want = oracle_tokens(cfg, params, p, n, eos=eos,
                             temperature=temperature, top_k=top_k,
                             key=None if keys is None else keys[i])
        np.testing.assert_array_equal(np.asarray(results[i]), want)


@pytest.fixture(autouse=True)
def _serve_isolation(tmp_path, monkeypatch):
    monkeypatch.setenv("MPI4TORCH_TPU_TUNE_CACHE",
                       str(tmp_path / "tune_cache.json"))
    tune.clear()
    serve.reset_stats()
    yield
    tune.clear()
    serve.reset_stats()
    mpi.config.set_latency_crossover_bytes(None)
    mpi.config.set_serve_decode_buckets(
        mpi.config.DEFAULT_SERVE_DECODE_BUCKETS)


class TestEngineOracleParity:
    """Bitwise token parity vs per-request generate(), with slot churn
    (4 requests through 2 slots: queueing, eviction, slot reuse)."""

    @pytest.mark.parametrize("policy", sorted(serve.POLICIES))
    @pytest.mark.parametrize("cfg", [CFG, CFG_GQA, CFG_ROPE, CFG_SWIGLU],
                             ids=["mha", "gqa", "rope", "swiglu"])
    def test_local_churn_matrix(self, cfg, policy):
        params = _params(cfg)
        eng = serve.Engine(cfg, params,
                           serve.ServeConfig(slots=2, policy=policy))
        assert_matches_oracle(cfg, params, drive(eng))

    def test_spmd_world4_overlap(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, overlap=True),
                           spmd=True, nranks=4)
        assert_matches_oracle(CFG, params, drive(eng))

    def test_spmd_world4_blocking(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, overlap=False),
                           spmd=True, nranks=4)
        assert_matches_oracle(CFG, params, drive(eng))

    def test_spmd_mesh_2x4(self):
        params = _params(CFG)
        mesh = mpi.device_mesh({"dp": 2, "tp": 4})
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, overlap=True),
                           spmd=True, mesh=mesh, axis_name="tp")
        assert_matches_oracle(CFG, params, drive(eng))

    def test_ranks_world4_mode_b(self):
        params = _params(CFG)

        def fn(rank):
            eng = serve.Engine(CFG, params,
                               serve.ServeConfig(slots=2, overlap=True))
            return drive(eng)

        outs = mpi.run_ranks(fn, 4, timeout=120.0)
        # Every rank ran the identical host loop: identical results.
        for r in range(1, 4):
            for i in range(len(PROMPTS)):
                np.testing.assert_array_equal(outs[r][i], outs[0][i])
        assert_matches_oracle(CFG, params, outs[0])

    def test_sampled_parity_local(self):
        params = _params(CFG)
        keys = [jax.random.PRNGKey(100 + i) for i in range(len(PROMPTS))]
        eng = serve.Engine(
            CFG, params,
            serve.ServeConfig(slots=2, temperature=0.9, top_k=7))
        res = drive(eng, keys=keys)
        assert_matches_oracle(CFG, params, res, temperature=0.9,
                              top_k=7, keys=keys)

    def test_eos_truncates_and_evicts_early(self):
        params = _params(CFG)
        # A naturally-emitted token as EOS: the engine must stop that
        # request right after it while the others run to budget.
        probe = oracle_tokens(CFG, params, PROMPTS[0], BUDGETS[0])
        eos = int(probe[len(PROMPTS[0]) + 1])     # its 2nd generated token
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, eos=eos))
        res = drive(eng)
        assert_matches_oracle(CFG, params, res, eos=eos)
        gen = probe[len(PROMPTS[0]):]
        first_hit = int(np.where(gen == eos)[0][0])
        assert len(res[0]) == len(PROMPTS[0]) + first_hit + 1
        assert res[0][-1] == eos
        assert len(res[0]) < len(PROMPTS[0]) + BUDGETS[0] + 1
        assert eng.stats.snapshot()["finished"] == len(PROMPTS)


class TestSlotTable:
    def test_slot_reuse_after_eviction(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=1))
        eng.submit(PROMPTS[0], max_new=2)
        eng.submit(PROMPTS[1], max_new=2)
        eng.run()
        # One slot, two requests: the second reused slot 0.
        assert eng.slot_log == [(0, 0), (1, 0)]
        assert eng.stats.snapshot()["evicted"] == 2

    def test_full_capacity_rejection(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=1, queue_limit=1))
        eng.submit(PROMPTS[0], max_new=3)
        eng.step()                       # occupies the single slot
        eng.submit(PROMPTS[1], max_new=3)   # waits in the queue
        with pytest.raises(serve.QueueFullError, match="queue full"):
            eng.submit(PROMPTS[2], max_new=3)
        assert eng.stats.snapshot()["rejected"] == 1
        # Draining frees capacity again.
        eng.run()
        assert eng.submit(PROMPTS[2], max_new=3) is not None

    def test_queue_bounded_before_first_step(self):
        """queue_limit must bound the waiting queue even while slots
        are still free (pre-step burst): capacity = free slots +
        queue_limit, nothing beyond it."""
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=1, queue_limit=1))
        eng.submit(PROMPTS[0], max_new=2)    # absorbed by the free slot
        eng.submit(PROMPTS[1], max_new=2)    # the one queued-waiter
        with pytest.raises(serve.QueueFullError):
            eng.submit(PROMPTS[2], max_new=2)
        # Both accepted requests still serve to completion.
        res = eng.run()
        assert set(res) == {0, 1}

    def test_finite_guard_composes_with_poisoned_free_slots(self):
        """config.comm_finite_guard='raise' (the PR 7 integrity knob)
        must not false-positive on a partially-occupied engine: free
        slots' poisoned rows are masked out of every collective payload
        before it reaches the wire, and live tokens are unchanged."""
        params = _params(CFG)
        want = oracle_tokens(CFG, params, PROMPTS[0], 4)
        mpi.config.set_comm_finite_guard("raise")
        try:
            def fn(rank):
                eng = serve.Engine(CFG, params,
                                   serve.ServeConfig(slots=3))
                eng.submit(PROMPTS[0], max_new=4)   # 2 slots stay free
                return eng.run()

            outs = mpi.run_ranks(fn, 2, timeout=60.0)
        finally:
            mpi.config.set_comm_finite_guard("off")
        np.testing.assert_array_equal(outs[0][0], want)

    def test_admission_finish_reports_through_step_events(self):
        """A request that finishes at admission (max_new=1, or first
        token == eos) must surface through step()'s emitted/finished
        events like any decode-finished request."""
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=2))
        eng.submit(PROMPTS[0], max_new=1)
        ev = eng.step()
        assert ev["admitted"] == [0] and ev["finished"] == [0]
        assert len(ev["emitted"][0]) == 1
        np.testing.assert_array_equal(
            eng.results()[0], oracle_tokens(CFG, params, PROMPTS[0], 1))
        # A longer request emits TWO tokens on its admission step:
        # the prefill first-token plus its first decode token.
        rid = eng.submit(PROMPTS[1], max_new=3)
        ev = eng.step()
        assert len(ev["emitted"][rid]) == 2

    def test_duplicate_rid_rejected(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=2))
        eng.submit(PROMPTS[0], rid="x", max_new=2)
        with pytest.raises(ValueError, match="already in use"):
            eng.submit(PROMPTS[1], rid="x", max_new=2)
        eng.run()
        # Still taken after finishing — results()['x'] must stay
        # unambiguous for the engine's lifetime.
        with pytest.raises(ValueError, match="already in use"):
            eng.submit(PROMPTS[1], rid="x", max_new=2)

    def test_pop_results_releases_memory_and_rids(self):
        """The steady-state serving API: pop finished results so a
        long-lived engine does not grow with total traffic; a popped
        rid becomes reusable."""
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=2))
        eng.submit(PROMPTS[0], rid="x", max_new=2)
        eng.run()
        popped = eng.pop_results()
        np.testing.assert_array_equal(
            popped["x"], oracle_tokens(CFG, params, PROMPTS[0], 2))
        assert eng.results() == {}
        # rid released: a second life for "x" serves normally.
        eng.submit(PROMPTS[1], rid="x", max_new=2)
        eng.run()
        np.testing.assert_array_equal(
            eng.pop_results()["x"],
            oracle_tokens(CFG, params, PROMPTS[1], 2))

    def test_stats_registry_drops_collected_engines(self):
        import gc

        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=1))
        eng.submit(PROMPTS[0], max_new=2)
        eng.run()
        assert serve.stats()["n_engines"] == 1
        del eng
        gc.collect()
        snap = serve.stats()
        assert snap["n_engines"] == 0 and snap["finished"] == 0

    def test_occupancy_counters(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=4))
        eng.submit(PROMPTS[0], max_new=3)
        eng.run()
        snap = eng.stats.snapshot()
        assert snap["steps"] == 2            # budget 3 = prefill + 2 decodes
        assert snap["occupancy"] == 0.25     # 1 of 4 slots live
        assert snap["decode_tokens"] == 2
        span = eng.stats.spans[0]
        assert span["submitted"] <= span["admitted"] \
            <= span["first_token"] <= span["finished"]

    def test_poisoned_free_slots_are_inert(self):
        """NaN-poisoned rows must not move a live row's logits by one
        bit (all per-slot compute is row-local; collectives reduce over
        ranks, not slots)."""
        params = _params(CFG)
        comm = mpi.COMM_WORLD
        shards = kv.shard_params_tp(CFG, params, comm)
        tokens = jnp.asarray([5, 0], jnp.int32)
        pos = jnp.asarray([2, 0], jnp.int32)

        clean = kv.init_kv_cache_tp(CFG, 2, 1, jnp.float64)
        poisoned = jax.tree.map(lambda a: a.at[1].set(jnp.nan), clean)
        l_clean, _ = kv.decode_step_tp(CFG, shards, clean, tokens, pos,
                                       comm)
        l_pois, _ = kv.decode_step_tp(CFG, shards, poisoned, tokens, pos,
                                      comm)
        np.testing.assert_array_equal(np.asarray(l_clean[0]),
                                      np.asarray(l_pois[0]))
        assert np.all(np.isfinite(np.asarray(l_pois[0])))

    def test_submit_validation(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=1))
        with pytest.raises(ValueError, match="exceeds max_seq"):
            eng.submit(np.arange(20), max_new=10)
        with pytest.raises(ValueError, match="non-empty 1-d"):
            eng.submit(np.zeros((2, 2), np.int32))
        with pytest.raises(ValueError, match="requires a PRNG"):
            serve.Engine(CFG, params,
                         serve.ServeConfig(slots=1, temperature=0.5)) \
                .submit(PROMPTS[0])

    def test_config_validation(self):
        with pytest.raises(ValueError, match="unknown scheduling"):
            serve.ServeConfig(policy="round_robin")
        with pytest.raises(ValueError, match="slots"):
            serve.ServeConfig(slots=0)
        params = _params(CFG)
        with pytest.raises(mpi.CommError, match="n_heads"):
            serve.Engine(CFG, params, serve.ServeConfig(slots=1),
                         spmd=True, nranks=3)
        moe = dataclasses.replace(CFG, n_experts=2, capacity=8)
        with pytest.raises(mpi.CommError, match="MoE"):
            serve.Engine(moe, _params(moe), serve.ServeConfig(slots=1))


class TestPolicies:
    def test_registry_sync_guard(self):
        """Every registered policy is covered by the parity matrix
        (which parametrizes over serve.POLICIES); pinning the known set
        makes registering a policy without extending coverage a loud CI
        failure rather than a silent gap.  One checker
        (analyze.registry.serve_policy_problems) shared with the
        serve-smoke lane."""
        from mpi4torch_tpu.analyze.registry import serve_policy_problems

        assert serve_policy_problems(("fcfs", "shortest_first")) == []

    def test_shortest_first_orders_admissions(self):
        params = _params(CFG)
        eng = serve.Engine(
            CFG, params,
            serve.ServeConfig(slots=1, policy="shortest_first"))
        eng.submit(PROMPTS[1], max_new=2)   # len 5
        eng.submit(PROMPTS[2], max_new=2)   # len 2 — admitted first
        eng.run()
        assert [rid for rid, _ in eng.slot_log] == [1, 0]


class TestCensusAndLatencyTier:
    def test_scheduled_exposure_overlap_vs_blocking(self):
        params = _params(CFG)
        seen = {}
        for name, ov in (("overlap", True), ("blocking", False)):
            eng = serve.Engine(CFG, params,
                               serve.ServeConfig(slots=2, overlap=ov),
                               spmd=True, nranks=4)
            eng.submit(PROMPTS[0], max_new=3)
            eng.step()
            seen[name] = mpi.overlap.scheduled_exposure(eng.lower_step())
        k = mpi.config.serve_decode_buckets()
        assert seen["overlap"]["n_buckets"] == 2 * CFG.n_layers * k
        assert seen["overlap"]["exposed_fraction"] < 1.0
        assert seen["blocking"]["exposed_fraction"] == 1.0

    def test_latency_tier_selection_and_span(self):
        from mpi4torch_tpu._compat import lowered_text

        params = _params(CFG)
        mpi.config.set_latency_crossover_bytes(1 << 14)
        rep = serve.latency_report(CFG, serve.ServeConfig(slots=2), 4,
                                   jnp.float64)
        assert rep["latency_tier"] and rep["algorithm"] == "rhd"
        assert rep["chunk_bytes"] <= rep["latency_crossover_bytes"]

        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, overlap=True),
                           spmd=True, nranks=4)
        eng.submit(PROMPTS[0], max_new=3)
        eng.step()
        txt = lowered_text(eng.lower_step(), debug_info=True)
        # Deterministic evidence off the program itself: the resolved
        # split-phase scope carries the latency algorithm, and no
        # bandwidth-tier schedule appears anywhere in the decode step.
        assert "Allreduce_start.rhd" in txt
        assert ".bidir" not in txt and ".torus" not in txt
        # Parity is schedule-independent.
        res = eng.run()
        np.testing.assert_array_equal(
            res[0], oracle_tokens(CFG, params, PROMPTS[0], 3))

    def test_degraded_scope_algorithm_not_claimed_in_span(self):
        """A scope-default hier whose group rule fails for this
        communicator degrades to ring inside the backend — the lowered
        split-phase scope must NOT claim the schedule that never ran
        (the census reads those spans as evidence)."""
        import jax as _jax

        comm = mpi.COMM_WORLD
        mpi.config.set_hier_group_size(5)    # does not divide 4
        try:
            with mpi.config.algorithm_scope("hier"):
                def body(x):
                    return comm.Wait(comm.Allreduce_start(x, mpi.MPI_SUM))
                lowered = _jax.jit(mpi.run_spmd(body, nranks=4)).lower(
                    jnp.ones(64, jnp.float32))
            from mpi4torch_tpu._compat import lowered_text
            txt = lowered_text(lowered, debug_info=True)
            assert "Allreduce_start.hier" not in txt
            assert "Allreduce_start" in txt
        finally:
            mpi.config.set_hier_group_size(None)

    def test_decode_message_bytes(self):
        scfg = serve.ServeConfig(slots=2)
        assert serve.decode_message_bytes(CFG, scfg, jnp.float64) \
            == 2 * CFG.d_model * 8


class TestCrossModeBitwise:
    def test_decode_step_tp_det_mode_a_vs_b(self):
        params = _params(CFG)
        tokens = jnp.asarray([3, 5, 7], jnp.int32)
        pos = jnp.asarray([0, 1, 2], jnp.int32)
        with mpi.config.deterministic_mode():
            def step_a(cache, t, p):
                comm = mpi.COMM_WORLD
                sh = kv.shard_params_tp(CFG, params, comm)
                rank = jnp.asarray(comm.rank)
                local = jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, rank, 0, keepdims=False), cache)
                return kv.decode_step_tp(CFG, sh, local, t, p, comm,
                                         overlap=True)[0]

            cache0 = kv.init_kv_cache_tp(CFG, 3, 4, jnp.float64)
            stacked = jax.tree.map(
                lambda a: jnp.broadcast_to(a[None], (4,) + a.shape),
                cache0)
            l_a = mpi.run_spmd(step_a, nranks=4)(stacked, tokens, pos)

            def rank_fn(rank):
                comm = mpi.COMM_WORLD
                sh = kv.shard_params_tp(CFG, params, comm)
                local = kv.init_kv_cache_tp(CFG, 3, 4, jnp.float64)
                return kv.decode_step_tp(CFG, sh, local, tokens, pos,
                                         comm, overlap=True)[0]

            outs = mpi.run_ranks(rank_fn, 4, timeout=60.0)
        for r in range(4):
            np.testing.assert_array_equal(np.asarray(l_a[r]),
                                          np.asarray(outs[r]))


class TestZero3Admission:
    def test_admit_zero3_matches_gather_then_slice(self):
        params = _params(CFG)

        def fn(rank):
            from mpi4torch_tpu.parallel import zero as Z

            comm = mpi.COMM_WORLD
            p_shards = Z.zero3_shard_params(comm, params)
            got = kv.admit_zero3(CFG, comm, p_shards, params)
            want = kv.shard_params_tp(
                CFG, Z.zero3_params(comm, p_shards, params), comm)
            same = jax.tree.map(
                lambda a, b: bool(jnp.array_equal(a, b)), got, want)
            return all(jax.tree.leaves(same))

        assert all(mpi.run_ranks(fn, 4, timeout=120.0))

    def test_admit_zero3_serving_dtype_override(self):
        params = _params(CFG)

        def fn(rank):
            from mpi4torch_tpu.parallel import zero as Z

            comm = mpi.COMM_WORLD
            p_shards = Z.zero3_shard_params(comm, params)
            got = kv.admit_zero3(CFG, comm, p_shards, params,
                                 dtype=jnp.float32)
            return all(leaf.dtype == jnp.float32
                       for leaf in jax.tree.leaves(got))

        assert all(mpi.run_ranks(fn, 2, timeout=120.0))


class TestFaultComposition:
    def test_rank_death_mid_decode_attributed(self):
        from mpi4torch_tpu import resilience as rz

        params = _params(CFG)

        def fn(rank):
            eng = serve.Engine(CFG, params, serve.ServeConfig(slots=2))
            eng.submit(PROMPTS[0], max_new=4)
            return eng.run()

        # Prefill issues 2*n_layers Allreduce calls; index 2*n_layers is
        # the FIRST decode-step collective — the fault fires mid-decode.
        with rz.fault_scope([rz.FaultSpec("rank_death", rank=1,
                                          op="Allreduce",
                                          index=2 * CFG.n_layers)]):
            with pytest.raises(mpi.RankFailedError) as ei:
                mpi.run_ranks(fn, 2, timeout=20.0)
        assert ei.value.ranks == frozenset({1})


class TestDeadlinesAndShedding:
    """ISSUE 15: deadline-expired eviction (typed result status, tokens
    a bitwise PREFIX of the per-request generate() oracle) and the
    overload shed policies — identical across the (1,), (4,) and (2,4)
    worlds, because expiry is driven by the engine's injectable clock
    and the host step loop, not by wall time."""

    def _drive_with_deadlines(self, eng, t):
        # rid 0 expires mid-flight (slotted), rid 3 expires while still
        # queued; rids 1/2 run to budget.  The fake clock advances one
        # "second" per step, so the eviction schedule is exact.
        eng.submit(PROMPTS[0], max_new=BUDGETS[0], deadline_s=2.5)
        eng.submit(PROMPTS[1], max_new=BUDGETS[1])
        eng.submit(PROMPTS[2], max_new=BUDGETS[2])
        eng.submit(PROMPTS[3], max_new=BUDGETS[3], deadline_s=1.5)
        expired = []
        for _ in range(32):
            ev = eng.step()
            expired += ev["expired"]
            t[0] += 1.0
            if not eng.pending():
                break
        return expired, eng.results(), eng.statuses()

    def _check(self, expired, results, statuses):
        params = self._params_cache
        assert statuses[0] == serve.STATUS_EXPIRED
        assert statuses[3] == serve.STATUS_EXPIRED
        assert statuses[1] == serve.STATUS_OK
        assert statuses[2] == serve.STATUS_OK
        assert sorted(expired) == [0, 3]
        # Finished requests: full oracle parity.
        for i in (1, 2):
            np.testing.assert_array_equal(
                np.asarray(results[i]),
                oracle_tokens(CFG, params, PROMPTS[i], BUDGETS[i]))
        # The slotted eviction kept an oracle PREFIX (it decoded >= 1
        # token before expiring); the queued eviction is a bare prompt.
        want0 = oracle_tokens(CFG, params, PROMPTS[0], BUDGETS[0])
        got0 = np.asarray(results[0])
        assert len(PROMPTS[0]) < len(got0) < len(want0)
        np.testing.assert_array_equal(got0, want0[:len(got0)])
        np.testing.assert_array_equal(np.asarray(results[3]),
                                      np.asarray(PROMPTS[3], np.int64))

    @pytest.mark.parametrize("world", ["local1", "spmd4", "mesh2x4"])
    def test_deadline_evictions_bitwise_vs_oracle(self, world):
        params = self._params_cache = _params(CFG)
        t = [0.0]
        kw = {"clock": lambda: t[0]}
        if world == "spmd4":
            kw.update(spmd=True, nranks=4)
        elif world == "mesh2x4":
            mesh = mpi.device_mesh({"dp": 2, "tp": 4})
            kw.update(spmd=True, mesh=mesh, axis_name="tp")
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=2), **kw)
        self._check(*self._drive_with_deadlines(eng, t))
        snap = eng.stats.snapshot()
        assert snap["deadline_expired"] == 2
        assert snap["finished"] == 2

    @pytest.mark.parametrize("policy", sorted(serve.SHED_POLICIES))
    def test_shed_policy_typed_and_bitwise(self, policy):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=1, queue_limit=2,
                                             shed_policy=policy))
        eng.submit(PROMPTS[0], max_new=4)
        eng.step()                      # rid 0 takes (and keeps) the slot
        eng.submit(PROMPTS[1], max_new=2)
        eng.submit(PROMPTS[2], max_new=2)
        eng.submit(PROMPTS[3], max_new=2)   # overflow -> shed
        # The victim is chosen among QUEUED requests at submit time:
        # oldest = rid 1, newest = rid 2 (rid 3 is not queued yet).
        victim = 1 if policy == "drop_oldest" else 2
        assert eng.status(victim) == serve.STATUS_SHED
        np.testing.assert_array_equal(
            np.asarray(eng.results()[victim]),
            np.asarray(PROMPTS[victim], np.int64))
        res = eng.run()
        survivors = [r for r in (0, 1, 2, 3) if r != victim]
        for i in survivors:
            assert eng.status(i) == serve.STATUS_OK
            np.testing.assert_array_equal(
                np.asarray(res[i]),
                oracle_tokens(CFG, params, PROMPTS[i], 4 if i == 0
                              else 2))
        assert eng.stats.snapshot()["shed"] == 1

    def test_shed_policy_none_keeps_queue_full_error(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=1, queue_limit=0))
        eng.submit(PROMPTS[0], max_new=4)
        eng.step()      # rid 0 occupies the only slot; queue bound is 0
        with pytest.raises(serve.QueueFullError):
            eng.submit(PROMPTS[1], max_new=2)

    def test_submit_validates_deadline(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=1))
        with pytest.raises(ValueError, match="deadline_s"):
            eng.submit(PROMPTS[0], deadline_s=0.0)

    def test_readmit_expired_ticket_surfaces_typed_status(self):
        """A drained ticket whose remaining deadline budget is consumed
        by resize downtime must NOT vanish at re-admission: readmit
        records it on the destination engine as a typed
        ``deadline_expired`` result carrying the oracle-prefix tokens
        it had earned — and the ticket's deadline travels as a
        REMAINING duration, so source and destination engines with
        different (injected) clocks never mix clock domains."""
        from mpi4torch_tpu.elastic import replan as E
        params = _params(CFG)
        t = [0.0]
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=2),
                           clock=lambda: t[0])
        eng.submit(PROMPTS[0], max_new=BUDGETS[0], deadline_s=5.0)
        eng.step()      # decodes >= 1 token; deadline still live
        t[0] = 1.0
        tickets, results = E.drain_tickets(eng)
        assert tickets[0].deadline_s == pytest.approx(4.0)
        assert tickets[0].remaining > 0
        # Resize "downtime": the destination engine's clock domain is
        # wildly different (default monotonic would be ~1e5 here); the
        # relative budget makes that irrelevant — only the drained
        # ticket's own remaining seconds count.
        t2 = [100.0]
        eng2 = serve.Engine(CFG, params, serve.ServeConfig(slots=2),
                            clock=lambda: t2[0])
        tickets[0].deadline_s = -0.5    # budget consumed by downtime
        assert E.readmit(eng2, tickets) == []
        assert eng2.status(0) == serve.STATUS_EXPIRED
        stitched = E.stitched_results(eng2.run(), tickets)
        want = oracle_tokens(CFG, params, PROMPTS[0], BUDGETS[0])
        got = np.asarray(stitched[0])
        assert len(PROMPTS[0]) < len(got) < len(want)
        np.testing.assert_array_equal(got, want[:len(got)])
        assert eng2.stats.snapshot()["deadline_expired"] == 1
        # A live budget re-admits through the ordinary path unchanged.
        eng3 = serve.Engine(CFG, params, serve.ServeConfig(slots=2),
                            clock=lambda: t2[0])
        tickets[0].deadline_s = 4.0
        assert E.readmit(eng3, tickets) == [0]
        np.testing.assert_array_equal(
            np.asarray(E.stitched_results(eng3.run(), tickets)[0]), want)

    def test_pop_results_drops_statuses(self):
        t = [0.0]
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=1),
                           clock=lambda: t[0])
        eng.submit(PROMPTS[0], max_new=2, deadline_s=0.5)
        t[0] = 1.0
        eng.step()
        assert eng.status(0) == serve.STATUS_EXPIRED
        eng.pop_results()
        assert eng.status(0) is None
        assert eng.statuses() == {}


# ---------------------------------------------------------------------------
# Paged KV cache: block-table paging, COW prefix sharing, chunked
# prefill (ISSUE 17).
# ---------------------------------------------------------------------------

# Tight pool: 4 requests' pages churn through it (dense-equivalent
# would be slots * max_seq / bs = 12 pages; 5 forces reuse + cached-
# page eviction).  bs=4 divides CFG.max_seq=24.
PAGED_TIGHT = dict(slots=2, block_size=4, num_blocks=5)


class TestPagedOracleParity:
    """Bitwise token parity vs per-request generate() with the KV cache
    paged — across block churn (tight pool), every policy, Mode A and
    Mode B, greedy and sampled."""

    @pytest.mark.parametrize("policy", sorted(serve.POLICIES))
    def test_local_churn_matrix(self, policy):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(policy=policy,
                                             **PAGED_TIGHT))
        assert_matches_oracle(CFG, params, drive(eng))

    @pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
    def test_gqa_rope_swiglu_variants(self):
        for cfg in (CFG_GQA, CFG_ROPE, CFG_SWIGLU):
            params = _params(cfg)
            eng = serve.Engine(cfg, params,
                               serve.ServeConfig(**PAGED_TIGHT))
            assert_matches_oracle(cfg, params, drive(eng))

    @pytest.mark.slow  # serve-smoke carries the paged Mode A (4,) parity cell
    def test_spmd_world4_overlap(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(overlap=True,
                                             **PAGED_TIGHT),
                           spmd=True, nranks=4)
        assert_matches_oracle(CFG, params, drive(eng))

    @pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
    def test_spmd_mesh_2x4(self):
        params = _params(CFG)
        mesh = mpi.device_mesh({"dp": 2, "tp": 4})
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(overlap=True,
                                             **PAGED_TIGHT),
                           spmd=True, mesh=mesh, axis_name="tp")
        assert_matches_oracle(CFG, params, drive(eng))

    @pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
    def test_ranks_world4_mode_b(self):
        params = _params(CFG)

        def fn(rank):
            eng = serve.Engine(CFG, params,
                               serve.ServeConfig(overlap=True,
                                                 **PAGED_TIGHT))
            return drive(eng)

        outs = mpi.run_ranks(fn, 4, timeout=120.0)
        # Identical deterministic host decisions on every rank keep
        # the block tables in lock-step under the decode collectives.
        for r in range(1, 4):
            for i in range(len(PROMPTS)):
                np.testing.assert_array_equal(outs[r][i], outs[0][i])
        assert_matches_oracle(CFG, params, outs[0])

    def test_sampled_parity_local(self):
        params = _params(CFG)
        keys = [jax.random.PRNGKey(100 + i) for i in range(len(PROMPTS))]
        eng = serve.Engine(
            CFG, params,
            serve.ServeConfig(temperature=0.9, top_k=7, **PAGED_TIGHT))
        res = drive(eng, keys=keys)
        assert_matches_oracle(CFG, params, res, temperature=0.9,
                              top_k=7, keys=keys)

    def test_preemption_under_pool_pressure(self):
        # 3 pages for two slots whose requests need 2 pages each: the
        # second admission eventually starves the first of a decode
        # page — the newest-admitted is preempted, requeued with its
        # emitted tokens folded into the prompt, and the STITCHED
        # stream stays bitwise the oracle.
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, block_size=4,
                                             num_blocks=3))
        eng.submit(PROMPTS[0], max_new=6)   # 3+6-1=8 rows -> 2 pages
        eng.submit(PROMPTS[1], max_new=4)   # 5+4-1=8 rows -> 2 pages
        res = eng.run()
        for i, n in ((0, 6), (1, 4)):
            np.testing.assert_array_equal(
                res[i], oracle_tokens(CFG, params, PROMPTS[i], n))
        assert eng.stats.snapshot()["preempted"] >= 1

    def test_deadline_evictions_compose(self):
        # PR 15 deadline path on the paged engine: the expired request
        # keeps an oracle PREFIX, survivors stay bitwise, pages return
        # to the pool.
        t = [0.0]
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(**PAGED_TIGHT),
                           clock=lambda: t[0])
        eng.submit(PROMPTS[0], max_new=6, deadline_s=2.5)
        eng.submit(PROMPTS[1], max_new=4)
        while eng.pending():
            eng.step()
            t[0] += 1.0
        res = eng.results()
        assert eng.status(0) == serve.STATUS_EXPIRED
        want0 = oracle_tokens(CFG, params, PROMPTS[0], 6)
        got0 = np.asarray(res[0])
        np.testing.assert_array_equal(got0, want0[:len(got0)])
        assert len(got0) < len(want0)
        np.testing.assert_array_equal(
            res[1], oracle_tokens(CFG, params, PROMPTS[1], 4))
        assert eng._mgr.blocks_in_use == 0


class TestPrefixSharing:
    def test_shared_prefix_prefilled_once_same_pages(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, block_size=4))
        sys_p = np.arange(1, 9)                  # 8 tokens = 2 pages
        pa = np.concatenate([sys_p, [20, 21]])
        pb = np.concatenate([sys_p, [22]])
        ra = eng.submit(pa, max_new=4)
        rb = eng.submit(pb, max_new=4)
        eng.step()                # both admitted: tables live now
        sa = [s for r, s in eng.slot_log if r == ra][0]
        sb = [s for r, s in eng.slot_log if r == rb][0]
        shared = list(eng._table[sb][:2])
        assert list(eng._table[sa][:2]) == shared
        assert min(shared) >= 0
        res = eng.run()
        np.testing.assert_array_equal(
            res[ra], oracle_tokens(CFG, params, pa, 4))
        np.testing.assert_array_equal(
            res[rb], oracle_tokens(CFG, params, pb, 4))
        snap = eng.stats.snapshot()
        # The census: the 8 shared tokens prefill ONCE.
        assert snap["prefill_tokens"] == len(pa) + (len(pb) - 8)
        assert snap["prefix_hits"] == 1
        assert snap["prefix_misses"] == 1

    def test_partial_tail_hit_is_cow_copied(self):
        # pa's 6-token prompt with bs=4 REGISTERS as one full page plus
        # a 2-row partial tail (a full-page chain cannot represent it).
        # pb extends that exact prefix, so its match lands mid-page on
        # the tail — which must be COPIED before pb's suffix rows hit
        # it (never written in place: pa still attends those rows).
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, block_size=4))
        pa = np.arange(1, 7)                     # 6 tokens
        pb = np.concatenate([pa, [22, 23]])
        ra = eng.submit(pa, max_new=4)
        rb = eng.submit(pb, max_new=4)
        eng.step()
        # Shared FULL page identical; tail pages distinct (the copy).
        sa = [s for r, s in eng.slot_log if r == ra][0]
        sb = [s for r, s in eng.slot_log if r == rb][0]
        assert eng._table[sa][0] == eng._table[sb][0] >= 0
        assert eng._table[sa][1] != eng._table[sb][1]
        res = eng.run()
        np.testing.assert_array_equal(
            res[ra], oracle_tokens(CFG, params, pa, 4))
        np.testing.assert_array_equal(
            res[rb], oracle_tokens(CFG, params, pb, 4))
        snap = eng.stats.snapshot()
        assert snap["cow_copies"] >= 1
        assert snap["prefix_hits"] == 1

    def test_prefix_cache_off_still_bitwise(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, block_size=4,
                                             prefix_cache=False))
        sys_p = np.arange(1, 9)
        pa = np.concatenate([sys_p, [20]])
        pb = np.concatenate([sys_p, [21]])
        eng.submit(pa, max_new=3)
        eng.submit(pb, max_new=3)
        res = eng.run()
        np.testing.assert_array_equal(
            res[0], oracle_tokens(CFG, params, pa, 3))
        np.testing.assert_array_equal(
            res[1], oracle_tokens(CFG, params, pb, 3))
        snap = eng.stats.snapshot()
        assert snap["prefix_hits"] == 0
        assert snap["prefill_tokens"] == len(pa) + len(pb)

    def test_cache_dtype_gate_disables_sharing_not_paging(self):
        # A down-cast cache would re-quantize shared rows the oracle
        # keeps at compute precision: the exactness gate turns the
        # prefix index (and chunking) off while paging stays on.
        params = _params(CFG)
        eng = serve.Engine(
            CFG, params,
            serve.ServeConfig(slots=2, block_size=4,
                              cache_dtype=jnp.bfloat16))
        assert eng._paged
        assert not eng._mgr.prefix_cache
        assert eng._chunk is None
        sys_p = np.arange(1, 9)
        eng.submit(np.concatenate([sys_p, [20]]), max_new=2)
        eng.submit(np.concatenate([sys_p, [21]]), max_new=2)
        eng.run()
        assert eng.stats.snapshot()["prefix_hits"] == 0


class TestChunkedPrefill:
    @pytest.mark.parametrize("chunk", [
        1, 3,
        # block-aligned + oversize chunks ride the TPU-manual lane
        # (tier-1 budget); 1 and 3 cover the mid-page boundary cases.
        pytest.param(4, marks=pytest.mark.slow),
        pytest.param(7, marks=pytest.mark.slow),
    ])
    def test_chunked_prefill_bitwise(self, chunk):
        params = _params(CFG)
        eng = serve.Engine(
            CFG, params,
            serve.ServeConfig(slots=2, block_size=4,
                              prefill_chunk=chunk))
        assert_matches_oracle(CFG, params, drive(eng))

    def test_long_prompt_never_stalls_resident_decode(self):
        # THE TTFT-bound regression: while a long prompt lands chunk by
        # chunk, the already-resident slot must emit one token on EVERY
        # step — chunked prefill interleaves, it does not stall.
        params = _params(CFG)
        eng = serve.Engine(
            CFG, params,
            serve.ServeConfig(slots=2, block_size=4, prefill_chunk=2))
        r0 = eng.submit(PROMPTS[0], max_new=10)
        eng.step()                       # r0 resident, decoding
        long_p = np.arange(1, 13)        # 12 tokens -> 6 chunks of 2
        r1 = eng.submit(long_p, max_new=3)
        stall_free_steps = 0
        while eng._prefill_jobs:
            ev = eng.step()
            assert r0 in ev["emitted"], \
                "resident decode stalled during chunked prefill"
            stall_free_steps += 1
        assert stall_free_steps >= 5     # the job really spanned steps
        res = eng.run()
        np.testing.assert_array_equal(
            res[r0], oracle_tokens(CFG, params, PROMPTS[0], 10))
        np.testing.assert_array_equal(
            res[r1], oracle_tokens(CFG, params, long_p, 3))

    def test_unchunked_long_prompt_admission_is_atomic(self):
        # Control for the test above: without prefill_chunk the same
        # admission runs the whole prompt in one step (dense
        # semantics), so the chunked path is what bounds it.
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, block_size=4))
        r1 = eng.submit(np.arange(1, 13), max_new=3)
        ev = eng.step()
        assert r1 in ev["admitted"]
        assert not eng._prefill_jobs


class TestPagedPoolAccounting:
    def test_block_level_counters_and_census(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, block_size=4,
                                             num_blocks=6))
        eng.submit(PROMPTS[0], max_new=4)     # 3 tokens -> 1 page
        eng.step()
        snap = eng.stats.snapshot()
        assert snap["blocks_in_use"] == eng._mgr.blocks_in_use > 0
        assert snap["blocks_in_use"] + snap["blocks_free"] \
            + snap["blocks_cached"] == 6
        hd = CFG.d_model // CFG.n_heads
        row = 2 * CFG.kv_heads * hd * CFG.n_layers \
            * jnp.dtype(eng._dtype).itemsize
        assert eng.kv_bytes_resident() \
            == eng._mgr.blocks_in_use * 4 * row
        # Dense census for comparison: full max_seq rows per occupied
        # slot — the paged engine's residency is strictly smaller for
        # a short sequence.
        dense = serve.Engine(CFG, params, serve.ServeConfig(slots=2))
        dense.submit(PROMPTS[0], max_new=4)
        dense.step()
        assert dense.kv_bytes_resident() == CFG.max_seq * row
        assert eng.kv_bytes_resident() < dense.kv_bytes_resident()

    def test_submit_rejects_request_larger_than_pool(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=1, block_size=4,
                                             num_blocks=2))
        with pytest.raises(ValueError, match="pages"):
            eng.submit(np.arange(1, 10), max_new=8)   # needs 4 pages

    def test_config_validation(self):
        params = _params(CFG)
        with pytest.raises(ValueError, match="divide"):
            serve.Engine(CFG, params,
                         serve.ServeConfig(slots=1, block_size=5))
        with pytest.raises(ValueError, match="block_size"):
            serve.ServeConfig(block_size=-1)
        with pytest.raises(ValueError, match="num_blocks"):
            serve.ServeConfig(block_size=4, num_blocks=0)
        with pytest.raises(ValueError, match="prefill_chunk"):
            serve.ServeConfig(prefill_chunk=2)       # needs paging
        with pytest.raises(ValueError, match="prefill_chunk"):
            serve.ServeConfig(block_size=4, prefill_chunk=0)

    def test_registry_sync_guard(self):
        from mpi4torch_tpu.analyze.registry import serve_paging_problems

        assert serve_paging_problems() == []


class TestPagedNoRetrace:
    def test_lowered_step_identical_across_table_states(self):
        from mpi4torch_tpu._compat import lowered_text

        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, block_size=4,
                                             overlap=True),
                           spmd=True, nranks=4)
        eng.submit(PROMPTS[0], max_new=6)
        eng.step()
        txt1 = lowered_text(eng.lower_step(), debug_info=False)
        eng.submit(PROMPTS[1], max_new=4)
        eng.step()
        txt2 = lowered_text(eng.lower_step(), debug_info=False)
        assert txt1 == txt2
        assert txt1.count('"stablehlo.gather"') >= 2 * CFG.n_layers


class TestPagedDrainReadmit:
    def test_tickets_carry_pages_and_readmit_prefix_hits(self):
        # Satellite 6: a drained paged request's ticket carries its
        # block-table state, and re-admission recovers the pages
        # through the prefix index — prefill re-runs ~1 token, and the
        # stitched stream stays bitwise the oracle.
        from mpi4torch_tpu.elastic import replan as E

        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, block_size=4))
        eng.submit(PROMPTS[0], max_new=6)
        eng.submit(PROMPTS[1], max_new=4)
        eng.step(); eng.step()
        tickets, _ = E.drain_tickets(eng)
        for t in tickets:
            assert t.pages is not None
            assert t.pages["n_tokens"] > 0
            assert len(t.pages["block_ids"]) \
                == -(-t.pages["n_tokens"] // 4)
        serve.reset_stats()
        E.readmit(eng, tickets)
        res = eng.run()
        stitched = E.stitched_results(res, tickets)
        np.testing.assert_array_equal(
            stitched[0], oracle_tokens(CFG, params, PROMPTS[0], 6))
        np.testing.assert_array_equal(
            stitched[1], oracle_tokens(CFG, params, PROMPTS[1], 4))
        snap = eng.stats.snapshot()
        assert snap["prefix_hits"] == 2          # both re-admissions hit
        # Each readmission prefilled ONLY its uncovered suffix (1-2
        # tokens past the registered rows), not the whole prompt.
        assert snap["prefill_tokens"] <= 2 * 2


class TestBlockManager:
    def test_alloc_release_cache_eviction(self):
        from mpi4torch_tpu.serve import BlockManager

        m = BlockManager(4, 2)
        a = m.alloc(2)
        assert m.blocks_in_use == 2 and m.free_blocks == 2
        # Register then release: pages park CACHED, not freed.
        m.register(np.array([1, 2, 3]), a, 3)
        m.release(a)
        assert m.blocks_in_use == 0 and m.cached_blocks == 2
        # A full-pool alloc reclaims them LRU (index entries dropped).
        b = m.alloc(4)
        assert b is not None and m.cached_blocks == 0
        assert m.match(np.array([1, 2, 3]), 2) == ([], 0)
        assert m.alloc(1) is None
        for x in b:
            m.release([x])
        assert m.free_blocks == 4

    def test_match_caps_below_limit_and_checks_content(self):
        from mpi4torch_tpu.serve import BlockManager

        m = BlockManager(8, 2)
        toks = np.array([5, 6, 7, 8, 9])
        ids = m.alloc(3)
        m.register(toks, ids, 5)
        # Full chain + partial tail, capped at limit.
        got_ids, n = m.match(toks, 4)
        assert n == 4 and got_ids == ids[:2]
        got_ids, n = m.match(toks, 5)
        assert n == 5 and got_ids == ids
        # Diverging content does not match past the divergence.
        other = np.array([5, 6, 99, 8, 9])
        got_ids, n = m.match(other, 5)
        assert n == 2 and got_ids == ids[:1]

    def test_release_unreferenced_raises(self):
        from mpi4torch_tpu.serve import BlockManager

        m = BlockManager(2, 2)
        a = m.alloc(1)
        m.release(a)
        with pytest.raises(ValueError, match="unreferenced"):
            m.release(a)

"""mpi4torch_tpu.serve — continuous-batching inference serving
(ISSUE 10).

Coverage per the acceptance criteria:

* engine-vs-oracle TOKEN parity: the continuously-batched engine emits
  exactly the tokens of per-request ``models/transformer.generate`` —
  across admission/eviction churn, on (1,), (4,) and (2,4) worlds,
  Mode A (run_spmd) and Mode B (run_ranks), greedy AND sampled, under
  every registered scheduling policy (the matrix parametrizes over
  :data:`serve.POLICIES`, so a policy registered without parity
  coverage fails here — the registry-sync guard pins the known set);
* slot-table semantics: slot reuse after eviction, full-capacity
  rejection (``QueueFullError``), occupancy/eviction counters, and the
  NaN-poisoned free-slot inertness proof (poisoned rows never move live
  rows' logits by a single bit);
* the deterministic censuses: ``scheduled_exposure`` of the lowered
  decode step strictly < 1.0 with overlap on (blocking baseline 1.0),
  and the latency-tier evidence — ``latency_report`` + the resolved
  ``Allreduce_start.rhd`` span in the lowered program;
* Mode A/Mode B bitwise parity of ``decode_step_tp`` under
  ``deterministic_mode``;
* the ZeRO-3 → TP admission recipe (``admit_zero3`` bitwise equal to
  the gather-then-slice route, plus the serving-dtype override);
* fault composition: a ``rank_death`` mid-decode raises an attributed
  ``RankFailedError`` on every survivor.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import serve, tune
from mpi4torch_tpu.models import transformer as T
from mpi4torch_tpu.serve import kv

CFG = T.TransformerConfig(vocab=37, d_model=16, n_heads=4, n_layers=2,
                          d_ff=32, max_seq=24)
CFG_GQA = dataclasses.replace(CFG, n_kv_heads=2)
CFG_ROPE = dataclasses.replace(CFG, rope=True)
CFG_SWIGLU = dataclasses.replace(CFG, ffn="swiglu")

PROMPTS = [np.array([1, 2, 3]), np.array([4, 5, 6, 7, 8]),
           np.array([9, 10]), np.array([11, 12, 13, 14])]
BUDGETS = [6, 4, 5, 3]


def _params(cfg, seed=0):
    return T.init_transformer(jax.random.PRNGKey(seed), cfg,
                              dtype=jnp.float64)


def oracle_tokens(cfg, params, prompt, n_new, eos=None, temperature=0.0,
                  top_k=0, key=None):
    out = T.generate(cfg, params, jnp.asarray(prompt, jnp.int32)[None, :],
                     n_new, dtype=jnp.float64, temperature=temperature,
                     top_k=top_k, key=key)
    seq = np.asarray(out[0])
    if eos is not None:
        gen = seq[len(prompt):]
        hits = np.where(gen == eos)[0]
        if hits.size:
            seq = seq[:len(prompt) + hits[0] + 1]
    return seq


def drive(eng, keys=None):
    for i, (p, n) in enumerate(zip(PROMPTS, BUDGETS)):
        eng.submit(p, max_new=n,
                   key=None if keys is None else keys[i])
    return eng.run()


def assert_matches_oracle(cfg, params, results, eos=None,
                          temperature=0.0, top_k=0, keys=None):
    for i, (p, n) in enumerate(zip(PROMPTS, BUDGETS)):
        want = oracle_tokens(cfg, params, p, n, eos=eos,
                             temperature=temperature, top_k=top_k,
                             key=None if keys is None else keys[i])
        np.testing.assert_array_equal(np.asarray(results[i]), want)


@pytest.fixture(autouse=True)
def _serve_isolation(tmp_path, monkeypatch):
    monkeypatch.setenv("MPI4TORCH_TPU_TUNE_CACHE",
                       str(tmp_path / "tune_cache.json"))
    tune.clear()
    serve.reset_stats()
    yield
    tune.clear()
    serve.reset_stats()
    mpi.config.set_latency_crossover_bytes(None)
    mpi.config.set_serve_decode_buckets(
        mpi.config.DEFAULT_SERVE_DECODE_BUCKETS)


class TestEngineOracleParity:
    """Bitwise token parity vs per-request generate(), with slot churn
    (4 requests through 2 slots: queueing, eviction, slot reuse)."""

    @pytest.mark.parametrize("policy", sorted(serve.POLICIES))
    @pytest.mark.parametrize("cfg", [CFG, CFG_GQA, CFG_ROPE, CFG_SWIGLU],
                             ids=["mha", "gqa", "rope", "swiglu"])
    def test_local_churn_matrix(self, cfg, policy):
        params = _params(cfg)
        eng = serve.Engine(cfg, params,
                           serve.ServeConfig(slots=2, policy=policy))
        assert_matches_oracle(cfg, params, drive(eng))

    def test_spmd_world4_overlap(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, overlap=True),
                           spmd=True, nranks=4)
        assert_matches_oracle(CFG, params, drive(eng))

    def test_spmd_world4_blocking(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, overlap=False),
                           spmd=True, nranks=4)
        assert_matches_oracle(CFG, params, drive(eng))

    def test_spmd_mesh_2x4(self):
        params = _params(CFG)
        mesh = mpi.device_mesh({"dp": 2, "tp": 4})
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, overlap=True),
                           spmd=True, mesh=mesh, axis_name="tp")
        assert_matches_oracle(CFG, params, drive(eng))

    def test_ranks_world4_mode_b(self):
        params = _params(CFG)

        def fn(rank):
            eng = serve.Engine(CFG, params,
                               serve.ServeConfig(slots=2, overlap=True))
            return drive(eng)

        outs = mpi.run_ranks(fn, 4, timeout=120.0)
        # Every rank ran the identical host loop: identical results.
        for r in range(1, 4):
            for i in range(len(PROMPTS)):
                np.testing.assert_array_equal(outs[r][i], outs[0][i])
        assert_matches_oracle(CFG, params, outs[0])

    def test_sampled_parity_local(self):
        params = _params(CFG)
        keys = [jax.random.PRNGKey(100 + i) for i in range(len(PROMPTS))]
        eng = serve.Engine(
            CFG, params,
            serve.ServeConfig(slots=2, temperature=0.9, top_k=7))
        res = drive(eng, keys=keys)
        assert_matches_oracle(CFG, params, res, temperature=0.9,
                              top_k=7, keys=keys)

    def test_eos_truncates_and_evicts_early(self):
        params = _params(CFG)
        # A naturally-emitted token as EOS: the engine must stop that
        # request right after it while the others run to budget.
        probe = oracle_tokens(CFG, params, PROMPTS[0], BUDGETS[0])
        eos = int(probe[len(PROMPTS[0]) + 1])     # its 2nd generated token
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, eos=eos))
        res = drive(eng)
        assert_matches_oracle(CFG, params, res, eos=eos)
        gen = probe[len(PROMPTS[0]):]
        first_hit = int(np.where(gen == eos)[0][0])
        assert len(res[0]) == len(PROMPTS[0]) + first_hit + 1
        assert res[0][-1] == eos
        assert len(res[0]) < len(PROMPTS[0]) + BUDGETS[0] + 1
        assert eng.stats.snapshot()["finished"] == len(PROMPTS)


class TestSlotTable:
    def test_slot_reuse_after_eviction(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=1))
        eng.submit(PROMPTS[0], max_new=2)
        eng.submit(PROMPTS[1], max_new=2)
        eng.run()
        # One slot, two requests: the second reused slot 0.
        assert eng.slot_log == [(0, 0), (1, 0)]
        assert eng.stats.snapshot()["evicted"] == 2

    def test_full_capacity_rejection(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=1, queue_limit=1))
        eng.submit(PROMPTS[0], max_new=3)
        eng.step()                       # occupies the single slot
        eng.submit(PROMPTS[1], max_new=3)   # waits in the queue
        with pytest.raises(serve.QueueFullError, match="queue full"):
            eng.submit(PROMPTS[2], max_new=3)
        assert eng.stats.snapshot()["rejected"] == 1
        # Draining frees capacity again.
        eng.run()
        assert eng.submit(PROMPTS[2], max_new=3) is not None

    def test_queue_bounded_before_first_step(self):
        """queue_limit must bound the waiting queue even while slots
        are still free (pre-step burst): capacity = free slots +
        queue_limit, nothing beyond it."""
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=1, queue_limit=1))
        eng.submit(PROMPTS[0], max_new=2)    # absorbed by the free slot
        eng.submit(PROMPTS[1], max_new=2)    # the one queued-waiter
        with pytest.raises(serve.QueueFullError):
            eng.submit(PROMPTS[2], max_new=2)
        # Both accepted requests still serve to completion.
        res = eng.run()
        assert set(res) == {0, 1}

    def test_finite_guard_composes_with_poisoned_free_slots(self):
        """config.comm_finite_guard='raise' (the PR 7 integrity knob)
        must not false-positive on a partially-occupied engine: free
        slots' poisoned rows are masked out of every collective payload
        before it reaches the wire, and live tokens are unchanged."""
        params = _params(CFG)
        want = oracle_tokens(CFG, params, PROMPTS[0], 4)
        mpi.config.set_comm_finite_guard("raise")
        try:
            def fn(rank):
                eng = serve.Engine(CFG, params,
                                   serve.ServeConfig(slots=3))
                eng.submit(PROMPTS[0], max_new=4)   # 2 slots stay free
                return eng.run()

            outs = mpi.run_ranks(fn, 2, timeout=60.0)
        finally:
            mpi.config.set_comm_finite_guard("off")
        np.testing.assert_array_equal(outs[0][0], want)

    def test_admission_finish_reports_through_step_events(self):
        """A request that finishes at admission (max_new=1, or first
        token == eos) must surface through step()'s emitted/finished
        events like any decode-finished request."""
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=2))
        eng.submit(PROMPTS[0], max_new=1)
        ev = eng.step()
        assert ev["admitted"] == [0] and ev["finished"] == [0]
        assert len(ev["emitted"][0]) == 1
        np.testing.assert_array_equal(
            eng.results()[0], oracle_tokens(CFG, params, PROMPTS[0], 1))
        # A longer request emits TWO tokens on its admission step:
        # the prefill first-token plus its first decode token.
        rid = eng.submit(PROMPTS[1], max_new=3)
        ev = eng.step()
        assert len(ev["emitted"][rid]) == 2

    def test_duplicate_rid_rejected(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=2))
        eng.submit(PROMPTS[0], rid="x", max_new=2)
        with pytest.raises(ValueError, match="already in use"):
            eng.submit(PROMPTS[1], rid="x", max_new=2)
        eng.run()
        # Still taken after finishing — results()['x'] must stay
        # unambiguous for the engine's lifetime.
        with pytest.raises(ValueError, match="already in use"):
            eng.submit(PROMPTS[1], rid="x", max_new=2)

    def test_pop_results_releases_memory_and_rids(self):
        """The steady-state serving API: pop finished results so a
        long-lived engine does not grow with total traffic; a popped
        rid becomes reusable."""
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=2))
        eng.submit(PROMPTS[0], rid="x", max_new=2)
        eng.run()
        popped = eng.pop_results()
        np.testing.assert_array_equal(
            popped["x"], oracle_tokens(CFG, params, PROMPTS[0], 2))
        assert eng.results() == {}
        # rid released: a second life for "x" serves normally.
        eng.submit(PROMPTS[1], rid="x", max_new=2)
        eng.run()
        np.testing.assert_array_equal(
            eng.pop_results()["x"],
            oracle_tokens(CFG, params, PROMPTS[1], 2))

    def test_stats_registry_drops_collected_engines(self):
        import gc

        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=1))
        eng.submit(PROMPTS[0], max_new=2)
        eng.run()
        assert serve.stats()["n_engines"] == 1
        del eng
        gc.collect()
        snap = serve.stats()
        assert snap["n_engines"] == 0 and snap["finished"] == 0

    def test_occupancy_counters(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=4))
        eng.submit(PROMPTS[0], max_new=3)
        eng.run()
        snap = eng.stats.snapshot()
        assert snap["steps"] == 2            # budget 3 = prefill + 2 decodes
        assert snap["occupancy"] == 0.25     # 1 of 4 slots live
        assert snap["decode_tokens"] == 2
        span = eng.stats.spans[0]
        assert span["submitted"] <= span["admitted"] \
            <= span["first_token"] <= span["finished"]

    def test_poisoned_free_slots_are_inert(self):
        """NaN-poisoned rows must not move a live row's logits by one
        bit (all per-slot compute is row-local; collectives reduce over
        ranks, not slots)."""
        params = _params(CFG)
        comm = mpi.COMM_WORLD
        shards = kv.shard_params_tp(CFG, params, comm)
        tokens = jnp.asarray([5, 0], jnp.int32)
        pos = jnp.asarray([2, 0], jnp.int32)

        clean = kv.init_kv_cache_tp(CFG, 2, 1, jnp.float64)
        poisoned = jax.tree.map(lambda a: a.at[1].set(jnp.nan), clean)
        l_clean, _ = kv.decode_step_tp(CFG, shards, clean, tokens, pos,
                                       comm)
        l_pois, _ = kv.decode_step_tp(CFG, shards, poisoned, tokens, pos,
                                      comm)
        np.testing.assert_array_equal(np.asarray(l_clean[0]),
                                      np.asarray(l_pois[0]))
        assert np.all(np.isfinite(np.asarray(l_pois[0])))

    def test_submit_validation(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=1))
        with pytest.raises(ValueError, match="exceeds max_seq"):
            eng.submit(np.arange(20), max_new=10)
        with pytest.raises(ValueError, match="non-empty 1-d"):
            eng.submit(np.zeros((2, 2), np.int32))
        with pytest.raises(ValueError, match="requires a PRNG"):
            serve.Engine(CFG, params,
                         serve.ServeConfig(slots=1, temperature=0.5)) \
                .submit(PROMPTS[0])

    def test_config_validation(self):
        with pytest.raises(ValueError, match="unknown scheduling"):
            serve.ServeConfig(policy="round_robin")
        with pytest.raises(ValueError, match="slots"):
            serve.ServeConfig(slots=0)
        params = _params(CFG)
        with pytest.raises(mpi.CommError, match="n_heads"):
            serve.Engine(CFG, params, serve.ServeConfig(slots=1),
                         spmd=True, nranks=3)
        moe = dataclasses.replace(CFG, n_experts=2, capacity=8)
        with pytest.raises(mpi.CommError, match="MoE"):
            serve.Engine(moe, _params(moe), serve.ServeConfig(slots=1))


class TestPolicies:
    def test_registry_sync_guard(self):
        """Every registered policy is covered by the parity matrix
        (which parametrizes over serve.POLICIES); pinning the known set
        makes registering a policy without extending coverage a loud CI
        failure rather than a silent gap.  One checker
        (analyze.registry.serve_policy_problems) shared with the
        serve-smoke lane."""
        from mpi4torch_tpu.analyze.registry import serve_policy_problems

        assert serve_policy_problems(("fcfs", "shortest_first")) == []

    def test_shortest_first_orders_admissions(self):
        params = _params(CFG)
        eng = serve.Engine(
            CFG, params,
            serve.ServeConfig(slots=1, policy="shortest_first"))
        eng.submit(PROMPTS[1], max_new=2)   # len 5
        eng.submit(PROMPTS[2], max_new=2)   # len 2 — admitted first
        eng.run()
        assert [rid for rid, _ in eng.slot_log] == [1, 0]


class TestCensusAndLatencyTier:
    def test_scheduled_exposure_overlap_vs_blocking(self):
        params = _params(CFG)
        seen = {}
        for name, ov in (("overlap", True), ("blocking", False)):
            eng = serve.Engine(CFG, params,
                               serve.ServeConfig(slots=2, overlap=ov),
                               spmd=True, nranks=4)
            eng.submit(PROMPTS[0], max_new=3)
            eng.step()
            seen[name] = mpi.overlap.scheduled_exposure(eng.lower_step())
        k = mpi.config.serve_decode_buckets()
        assert seen["overlap"]["n_buckets"] == 2 * CFG.n_layers * k
        assert seen["overlap"]["exposed_fraction"] < 1.0
        assert seen["blocking"]["exposed_fraction"] == 1.0

    def test_latency_tier_selection_and_span(self):
        from mpi4torch_tpu._compat import lowered_text

        params = _params(CFG)
        mpi.config.set_latency_crossover_bytes(1 << 14)
        rep = serve.latency_report(CFG, serve.ServeConfig(slots=2), 4,
                                   jnp.float64)
        assert rep["latency_tier"] and rep["algorithm"] == "rhd"
        assert rep["chunk_bytes"] <= rep["latency_crossover_bytes"]

        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=2, overlap=True),
                           spmd=True, nranks=4)
        eng.submit(PROMPTS[0], max_new=3)
        eng.step()
        txt = lowered_text(eng.lower_step(), debug_info=True)
        # Deterministic evidence off the program itself: the resolved
        # split-phase scope carries the latency algorithm, and no
        # bandwidth-tier schedule appears anywhere in the decode step.
        assert "Allreduce_start.rhd" in txt
        assert ".bidir" not in txt and ".torus" not in txt
        # Parity is schedule-independent.
        res = eng.run()
        np.testing.assert_array_equal(
            res[0], oracle_tokens(CFG, params, PROMPTS[0], 3))

    def test_degraded_scope_algorithm_not_claimed_in_span(self):
        """A scope-default hier whose group rule fails for this
        communicator degrades to ring inside the backend — the lowered
        split-phase scope must NOT claim the schedule that never ran
        (the census reads those spans as evidence)."""
        import jax as _jax

        comm = mpi.COMM_WORLD
        mpi.config.set_hier_group_size(5)    # does not divide 4
        try:
            with mpi.config.algorithm_scope("hier"):
                def body(x):
                    return comm.Wait(comm.Allreduce_start(x, mpi.MPI_SUM))
                lowered = _jax.jit(mpi.run_spmd(body, nranks=4)).lower(
                    jnp.ones(64, jnp.float32))
            from mpi4torch_tpu._compat import lowered_text
            txt = lowered_text(lowered, debug_info=True)
            assert "Allreduce_start.hier" not in txt
            assert "Allreduce_start" in txt
        finally:
            mpi.config.set_hier_group_size(None)

    def test_decode_message_bytes(self):
        scfg = serve.ServeConfig(slots=2)
        assert serve.decode_message_bytes(CFG, scfg, jnp.float64) \
            == 2 * CFG.d_model * 8


class TestCrossModeBitwise:
    def test_decode_step_tp_det_mode_a_vs_b(self):
        params = _params(CFG)
        tokens = jnp.asarray([3, 5, 7], jnp.int32)
        pos = jnp.asarray([0, 1, 2], jnp.int32)
        with mpi.config.deterministic_mode():
            def step_a(cache, t, p):
                comm = mpi.COMM_WORLD
                sh = kv.shard_params_tp(CFG, params, comm)
                rank = jnp.asarray(comm.rank)
                local = jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, rank, 0, keepdims=False), cache)
                return kv.decode_step_tp(CFG, sh, local, t, p, comm,
                                         overlap=True)[0]

            cache0 = kv.init_kv_cache_tp(CFG, 3, 4, jnp.float64)
            stacked = jax.tree.map(
                lambda a: jnp.broadcast_to(a[None], (4,) + a.shape),
                cache0)
            l_a = mpi.run_spmd(step_a, nranks=4)(stacked, tokens, pos)

            def rank_fn(rank):
                comm = mpi.COMM_WORLD
                sh = kv.shard_params_tp(CFG, params, comm)
                local = kv.init_kv_cache_tp(CFG, 3, 4, jnp.float64)
                return kv.decode_step_tp(CFG, sh, local, tokens, pos,
                                         comm, overlap=True)[0]

            outs = mpi.run_ranks(rank_fn, 4, timeout=60.0)
        for r in range(4):
            np.testing.assert_array_equal(np.asarray(l_a[r]),
                                          np.asarray(outs[r]))


class TestZero3Admission:
    def test_admit_zero3_matches_gather_then_slice(self):
        params = _params(CFG)

        def fn(rank):
            from mpi4torch_tpu.parallel import zero as Z

            comm = mpi.COMM_WORLD
            p_shards = Z.zero3_shard_params(comm, params)
            got = kv.admit_zero3(CFG, comm, p_shards, params)
            want = kv.shard_params_tp(
                CFG, Z.zero3_params(comm, p_shards, params), comm)
            same = jax.tree.map(
                lambda a, b: bool(jnp.array_equal(a, b)), got, want)
            return all(jax.tree.leaves(same))

        assert all(mpi.run_ranks(fn, 4, timeout=120.0))

    def test_admit_zero3_serving_dtype_override(self):
        params = _params(CFG)

        def fn(rank):
            from mpi4torch_tpu.parallel import zero as Z

            comm = mpi.COMM_WORLD
            p_shards = Z.zero3_shard_params(comm, params)
            got = kv.admit_zero3(CFG, comm, p_shards, params,
                                 dtype=jnp.float32)
            return all(leaf.dtype == jnp.float32
                       for leaf in jax.tree.leaves(got))

        assert all(mpi.run_ranks(fn, 2, timeout=120.0))


class TestFaultComposition:
    def test_rank_death_mid_decode_attributed(self):
        from mpi4torch_tpu import resilience as rz

        params = _params(CFG)

        def fn(rank):
            eng = serve.Engine(CFG, params, serve.ServeConfig(slots=2))
            eng.submit(PROMPTS[0], max_new=4)
            return eng.run()

        # Prefill issues 2*n_layers Allreduce calls; index 2*n_layers is
        # the FIRST decode-step collective — the fault fires mid-decode.
        with rz.fault_scope([rz.FaultSpec("rank_death", rank=1,
                                          op="Allreduce",
                                          index=2 * CFG.n_layers)]):
            with pytest.raises(mpi.RankFailedError) as ei:
                mpi.run_ranks(fn, 2, timeout=20.0)
        assert ei.value.ranks == frozenset({1})


class TestDeadlinesAndShedding:
    """ISSUE 15: deadline-expired eviction (typed result status, tokens
    a bitwise PREFIX of the per-request generate() oracle) and the
    overload shed policies — identical across the (1,), (4,) and (2,4)
    worlds, because expiry is driven by the engine's injectable clock
    and the host step loop, not by wall time."""

    def _drive_with_deadlines(self, eng, t):
        # rid 0 expires mid-flight (slotted), rid 3 expires while still
        # queued; rids 1/2 run to budget.  The fake clock advances one
        # "second" per step, so the eviction schedule is exact.
        eng.submit(PROMPTS[0], max_new=BUDGETS[0], deadline_s=2.5)
        eng.submit(PROMPTS[1], max_new=BUDGETS[1])
        eng.submit(PROMPTS[2], max_new=BUDGETS[2])
        eng.submit(PROMPTS[3], max_new=BUDGETS[3], deadline_s=1.5)
        expired = []
        for _ in range(32):
            ev = eng.step()
            expired += ev["expired"]
            t[0] += 1.0
            if not eng.pending():
                break
        return expired, eng.results(), eng.statuses()

    def _check(self, expired, results, statuses):
        params = self._params_cache
        assert statuses[0] == serve.STATUS_EXPIRED
        assert statuses[3] == serve.STATUS_EXPIRED
        assert statuses[1] == serve.STATUS_OK
        assert statuses[2] == serve.STATUS_OK
        assert sorted(expired) == [0, 3]
        # Finished requests: full oracle parity.
        for i in (1, 2):
            np.testing.assert_array_equal(
                np.asarray(results[i]),
                oracle_tokens(CFG, params, PROMPTS[i], BUDGETS[i]))
        # The slotted eviction kept an oracle PREFIX (it decoded >= 1
        # token before expiring); the queued eviction is a bare prompt.
        want0 = oracle_tokens(CFG, params, PROMPTS[0], BUDGETS[0])
        got0 = np.asarray(results[0])
        assert len(PROMPTS[0]) < len(got0) < len(want0)
        np.testing.assert_array_equal(got0, want0[:len(got0)])
        np.testing.assert_array_equal(np.asarray(results[3]),
                                      np.asarray(PROMPTS[3], np.int64))

    @pytest.mark.parametrize("world", ["local1", "spmd4", "mesh2x4"])
    def test_deadline_evictions_bitwise_vs_oracle(self, world):
        params = self._params_cache = _params(CFG)
        t = [0.0]
        kw = {"clock": lambda: t[0]}
        if world == "spmd4":
            kw.update(spmd=True, nranks=4)
        elif world == "mesh2x4":
            mesh = mpi.device_mesh({"dp": 2, "tp": 4})
            kw.update(spmd=True, mesh=mesh, axis_name="tp")
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=2), **kw)
        self._check(*self._drive_with_deadlines(eng, t))
        snap = eng.stats.snapshot()
        assert snap["deadline_expired"] == 2
        assert snap["finished"] == 2

    @pytest.mark.parametrize("policy", sorted(serve.SHED_POLICIES))
    def test_shed_policy_typed_and_bitwise(self, policy):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=1, queue_limit=2,
                                             shed_policy=policy))
        eng.submit(PROMPTS[0], max_new=4)
        eng.step()                      # rid 0 takes (and keeps) the slot
        eng.submit(PROMPTS[1], max_new=2)
        eng.submit(PROMPTS[2], max_new=2)
        eng.submit(PROMPTS[3], max_new=2)   # overflow -> shed
        # The victim is chosen among QUEUED requests at submit time:
        # oldest = rid 1, newest = rid 2 (rid 3 is not queued yet).
        victim = 1 if policy == "drop_oldest" else 2
        assert eng.status(victim) == serve.STATUS_SHED
        np.testing.assert_array_equal(
            np.asarray(eng.results()[victim]),
            np.asarray(PROMPTS[victim], np.int64))
        res = eng.run()
        survivors = [r for r in (0, 1, 2, 3) if r != victim]
        for i in survivors:
            assert eng.status(i) == serve.STATUS_OK
            np.testing.assert_array_equal(
                np.asarray(res[i]),
                oracle_tokens(CFG, params, PROMPTS[i], 4 if i == 0
                              else 2))
        assert eng.stats.snapshot()["shed"] == 1

    def test_shed_policy_none_keeps_queue_full_error(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params,
                           serve.ServeConfig(slots=1, queue_limit=0))
        eng.submit(PROMPTS[0], max_new=4)
        eng.step()      # rid 0 occupies the only slot; queue bound is 0
        with pytest.raises(serve.QueueFullError):
            eng.submit(PROMPTS[1], max_new=2)

    def test_submit_validates_deadline(self):
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=1))
        with pytest.raises(ValueError, match="deadline_s"):
            eng.submit(PROMPTS[0], deadline_s=0.0)

    def test_readmit_expired_ticket_surfaces_typed_status(self):
        """A drained ticket whose remaining deadline budget is consumed
        by resize downtime must NOT vanish at re-admission: readmit
        records it on the destination engine as a typed
        ``deadline_expired`` result carrying the oracle-prefix tokens
        it had earned — and the ticket's deadline travels as a
        REMAINING duration, so source and destination engines with
        different (injected) clocks never mix clock domains."""
        from mpi4torch_tpu.elastic import replan as E
        params = _params(CFG)
        t = [0.0]
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=2),
                           clock=lambda: t[0])
        eng.submit(PROMPTS[0], max_new=BUDGETS[0], deadline_s=5.0)
        eng.step()      # decodes >= 1 token; deadline still live
        t[0] = 1.0
        tickets, results = E.drain_tickets(eng)
        assert tickets[0].deadline_s == pytest.approx(4.0)
        assert tickets[0].remaining > 0
        # Resize "downtime": the destination engine's clock domain is
        # wildly different (default monotonic would be ~1e5 here); the
        # relative budget makes that irrelevant — only the drained
        # ticket's own remaining seconds count.
        t2 = [100.0]
        eng2 = serve.Engine(CFG, params, serve.ServeConfig(slots=2),
                            clock=lambda: t2[0])
        tickets[0].deadline_s = -0.5    # budget consumed by downtime
        assert E.readmit(eng2, tickets) == []
        assert eng2.status(0) == serve.STATUS_EXPIRED
        stitched = E.stitched_results(eng2.run(), tickets)
        want = oracle_tokens(CFG, params, PROMPTS[0], BUDGETS[0])
        got = np.asarray(stitched[0])
        assert len(PROMPTS[0]) < len(got) < len(want)
        np.testing.assert_array_equal(got, want[:len(got)])
        assert eng2.stats.snapshot()["deadline_expired"] == 1
        # A live budget re-admits through the ordinary path unchanged.
        eng3 = serve.Engine(CFG, params, serve.ServeConfig(slots=2),
                            clock=lambda: t2[0])
        tickets[0].deadline_s = 4.0
        assert E.readmit(eng3, tickets) == [0]
        np.testing.assert_array_equal(
            np.asarray(E.stitched_results(eng3.run(), tickets)[0]), want)

    def test_pop_results_drops_statuses(self):
        t = [0.0]
        params = _params(CFG)
        eng = serve.Engine(CFG, params, serve.ServeConfig(slots=1),
                           clock=lambda: t[0])
        eng.submit(PROMPTS[0], max_new=2, deadline_s=0.5)
        t[0] = 1.0
        eng.step()
        assert eng.status(0) == serve.STATUS_EXPIRED
        eng.pop_results()
        assert eng.status(0) is None
        assert eng.statuses() == {}

"""Test harness configuration.

The reference CI runs every test under ``mpirun -np {2,5,7}`` with
oversubscribed processes on one host (reference:
.github/workflows/test.yml:62-84).  The analogue here: a CPU platform with 8
virtual XLA devices (for the SPMD mesh backend) and the thread-SPMD eager
runtime (for per-rank tests) — see SURVEY.md §4 'What the rebuild needs'.

Must run before jax is imported anywhere.

Hardware gate (round-3 postmortem): the CPU pin must not be inescapable —
it previously was, which made the documented hardware command for the
compiled-kernel tests silently un-runnable, and the kernel's Mosaic
lowering bug survived three rounds behind the always-skipping gate.  An
ambient ``JAX_PLATFORMS`` (e.g. a TPU plugin's environment sets it
globally) is NOT a request to run the suite on hardware, so the gate is an
explicit escape hatch instead: ``MPI4TORCH_TPU_REAL_DEVICES=1`` leaves the
platform untouched and the real devices visible.  ``make tpu-test`` runs
the hardware-gated subset with the hatch open.
"""

import os

_real_devices = os.environ.get("MPI4TORCH_TPU_REAL_DEVICES", "") == "1"
if not _real_devices:
    os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
# The reference test suite is float64 throughout (torch.double) — but only
# on the CPU harness.  On TPU, x64 is unsupported (f64 is emulated; the
# kernel tests run bf16/f32 anyway), so the hardware run keeps default
# precision unless the user says otherwise.
if not _real_devices:
    os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# The env vars alone are not enough when something (e.g. an accelerator
# plugin's sitecustomize) imported jax before this conftest ran: the
# explicit config updates work post-import.  jax_platforms=cpu also stops
# an externally-registered TPU plugin from initializing (and possibly
# hanging on an unavailable tunnel).  Then warm the backend up on the main
# thread so rank-threads never race backend initialization.
if not _real_devices:
    jax.config.update("jax_platforms", "cpu")
if os.environ.get("JAX_ENABLE_X64") == "1":
    jax.config.update("jax_enable_x64", True)
jax.devices()

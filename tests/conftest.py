"""Test harness configuration.

The reference CI runs every test under ``mpirun -np {2,5,7}`` with
oversubscribed processes on one host (reference:
.github/workflows/test.yml:62-84).  The analogue here: a CPU platform with 8
virtual XLA devices (for the SPMD mesh backend) and the thread-SPMD eager
runtime (for per-rank tests) — see SURVEY.md §4 'What the rebuild needs'.

Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
# The reference test suite is float64 throughout (torch.double).
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# The env vars alone are not enough when something (e.g. an accelerator
# plugin's sitecustomize) imported jax before this conftest ran: the
# explicit config updates work post-import.  jax_platforms=cpu also stops
# an externally-registered TPU plugin from initializing (and possibly
# hanging on an unavailable tunnel).  Then warm the backend up on the main
# thread so rank-threads never race backend initialization.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.devices()

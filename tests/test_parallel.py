"""Parallel-strategy tests: ring transport, halo exchange, ring attention
(CP) and Ulysses attention (SP) must match their single-device oracles in
values AND gradients, on both backends (eager thread-SPMD and SPMD mesh) —
the §2.5 strategy checklist made executable."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm
from mpi4torch_tpu.parallel import (
    dense_attention,
    halo_exchange,
    ring_attention,
    ring_shift,
    ulysses_attention,
)

NR = 4
B, S, H, D = 2, 16, 4, 8
SL = S // NR  # local sequence block


def run(fn, **kw):
    return mpi.run_spmd(fn, nranks=NR, **kw)


def qkv():
    rng = np.random.default_rng(7)
    return tuple(
        jnp.asarray(rng.standard_normal((B, S, H, D))) for _ in range(3))


def local_slice(x, rank):
    start = jnp.asarray(rank) * SL
    return jax.lax.dynamic_slice_in_dim(x, start, SL, 1)


# ---------------------------------------------------------------------------
# ring_shift / halo_exchange
# ---------------------------------------------------------------------------


class TestRingShift:
    def test_eager_values_and_grad(self):
        def body():
            r = comm.rank
            x = jnp.full(3, float(r))

            def loss(x):
                return jnp.sum(ring_shift(comm, x) * (r + 1.0))

            val = ring_shift(comm, x)
            g = jax.grad(loss)(x)
            return np.asarray(val), np.asarray(g)

        outs = mpi.run_ranks(body, NR)
        for r in range(NR):
            val, g = outs[r]
            assert (val == (r - 1) % NR).all()
            # x_r reaches rank (r+1)%NR, whose loss weights it by that
            # rank's (rank+1): the gradient traveled the reverse ring.
            assert (g == ((r + 1) % NR) + 1.0).all()

    def test_spmd_values_and_grad(self):
        def fn(x):
            return ring_shift(comm, x * (comm.rank + 1.0))

        out = run(fn)(jnp.ones(3))
        for r in range(NR):
            assert (np.asarray(out[r]) == ((r - 1) % NR) + 1).all()
        g = jax.grad(lambda x: run(fn)(x).sum())(jnp.ones(3))
        # every rank's contribution is weighted by (rank+1), summed over NR
        # stacked outputs: total = sum of (r+1) = NR(NR+1)/2 per element.
        assert (np.asarray(g) == NR * (NR + 1) / 2).all()

    def test_negative_and_zero_shift(self):
        def fn(x):
            return ring_shift(comm, x * (comm.rank + 1.0), shift=-1)

        out = run(fn)(jnp.ones(2))
        for r in range(NR):
            assert (np.asarray(out[r]) == ((r + 1) % NR) + 1).all()
        assert ring_shift(comm, jnp.ones(2), shift=0) is not None

    def test_size_one_world_identity(self):
        x = jnp.arange(4.0)
        np.testing.assert_array_equal(ring_shift(comm, x), x)


class TestHaloExchange:
    def test_eager_periodic_halo(self):
        n, halo = 6, 2

        def body():
            r = comm.rank
            x = jnp.arange(n, dtype=jnp.float64) + 10.0 * r
            return np.asarray(halo_exchange(comm, x, halo))

        outs = mpi.run_ranks(body, NR)
        for r in range(NR):
            left_owner = (r - 1) % NR
            right_owner = (r + 1) % NR
            expect = np.concatenate([
                np.arange(n - halo, n) + 10.0 * left_owner,
                np.arange(n) + 10.0 * r,
                np.arange(halo) + 10.0 * right_owner,
            ])
            np.testing.assert_array_equal(outs[r], expect)

    def test_spmd_matches_eager_and_grad(self):
        n, halo = 4, 1

        def fn(x):
            local = x + 10.0 * comm.rank
            return halo_exchange(comm, local, halo)

        base = jnp.arange(n, dtype=jnp.float64)
        out = run(fn)(base)
        for r in range(NR):
            expect = np.concatenate([
                np.arange(n - halo, n) + 10.0 * ((r - 1) % NR),
                np.arange(n) + 10.0 * r,
                np.arange(halo) + 10.0 * ((r + 1) % NR),
            ])
            np.testing.assert_array_equal(np.asarray(out[r]), expect)
        # every input element appears once in its own rank's output and once
        # in a neighbor's halo (twice for elements in both edge slices).
        g = jax.grad(lambda x: run(fn)(x).sum())(base)
        expect_g = np.full(n, NR, np.float64)
        expect_g[0] += NR      # left edge also lands in left neighbor
        expect_g[-1] += NR     # right edge also lands in right neighbor
        np.testing.assert_array_equal(np.asarray(g), expect_g)

    def test_halo_validation(self):
        with pytest.raises(ValueError, match="halo"):
            halo_exchange(comm, jnp.ones(4), 0)
        with pytest.raises(ValueError, match="exceeds"):
            halo_exchange(comm, jnp.ones(4), 5)


# ---------------------------------------------------------------------------
# Ring attention (context parallel)
# ---------------------------------------------------------------------------


def _assemble(stacked):
    # (NR, B, SL, H, D) rank-major blocks -> (B, S, H, D)
    return jnp.concatenate([stacked[r] for r in range(NR)], axis=1)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_spmd_matches_dense(self, causal):
        q, k, v = qkv()
        ref = dense_attention(q, k, v, causal=causal)

        def fn(q, k, v):
            r = comm.rank
            return ring_attention(comm, local_slice(q, r), local_slice(k, r),
                                  local_slice(v, r), causal=causal)

        out = _assemble(run(fn)(q, k, v))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-10, atol=1e-12)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
    def test_spmd_grads_match_dense(self, causal):
        q, k, v = qkv()

        def dense_loss(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

        ref_grads = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)

        def fn(q, k, v):
            r = comm.rank
            out = ring_attention(comm, local_slice(q, r), local_slice(k, r),
                                 local_slice(v, r), causal=causal)
            return jnp.sum(out ** 2)

        ring_grads = jax.grad(
            lambda q, k, v: run(fn)(q, k, v).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(ring_grads, ref_grads):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-9, atol=1e-11)

    @pytest.mark.parametrize("window", [3, 9])
    def test_spmd_windowed_matches_oracle(self, window):
        # Sliding windows span ring-block boundaries: rank r's early
        # queries must still see rank r-1's tail keys.  Values AND grads
        # against the full-sequence windowed flash oracle.
        q, k, v = qkv()

        def oracle_loss(q, k, v):
            from mpi4torch_tpu.ops.flash import flash_attention
            out = flash_attention(q, k, v, causal=True, window=window,
                                  impl="jnp")
            return jnp.sum(out ** 2), out

        (_, ref), ref_grads = jax.value_and_grad(
            oracle_loss, argnums=(0, 1, 2), has_aux=True)(q, k, v)

        def fn(q, k, v):
            r = comm.rank
            out = ring_attention(comm, local_slice(q, r), local_slice(k, r),
                                 local_slice(v, r), causal=True,
                                 window=window)
            return jnp.sum(out ** 2), out

        (_, outs), grads = jax.value_and_grad(
            lambda q, k, v: ((lambda l, o: (l.sum(), o))(*run(fn)(q, k, v))),
            argnums=(0, 1, 2), has_aux=True)(q, k, v)
        np.testing.assert_allclose(np.asarray(_assemble(outs)),
                                   np.asarray(ref), rtol=1e-10, atol=1e-12)
        for got, want in zip(grads, ref_grads):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-9, atol=1e-11)

    def test_windowed_ring_cuts_rotations(self):
        # window=3 at s_local=4 reaches at most 1 block back: the lowered
        # program must contain exactly ceil(2/4)+1 = 2 live ring steps ->
        # 2 collective_permutes (k and v, one hop each), not the full
        # ring's 2*(NR-1) = 6.
        q, k, v = qkv()

        def fn(q, k, v):
            r = comm.rank
            return ring_attention(comm, local_slice(q, r), local_slice(k, r),
                                  local_slice(v, r), causal=True, window=3)

        hlo = jax.jit(run(fn)).lower(q, k, v).as_text()
        assert hlo.count("collective_permute") == 2, \
            hlo.count("collective_permute")

    def test_eager_matches_dense(self):
        q, k, v = qkv()
        ref = np.asarray(dense_attention(q, k, v, causal=True))

        def body():
            r = comm.rank
            out = ring_attention(comm, q[:, r * SL:(r + 1) * SL],
                                 k[:, r * SL:(r + 1) * SL],
                                 v[:, r * SL:(r + 1) * SL], causal=True)
            return np.asarray(out)

        outs = mpi.run_ranks(body, NR)
        got = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(got, ref, rtol=1e-10, atol=1e-12)


# ---------------------------------------------------------------------------
# Ulysses attention (sequence parallel via Alltoall)
# ---------------------------------------------------------------------------


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_spmd_matches_dense(self, causal):
        q, k, v = qkv()
        ref = dense_attention(q, k, v, causal=causal)

        def fn(q, k, v):
            r = comm.rank
            return ulysses_attention(comm, local_slice(q, r),
                                     local_slice(k, r), local_slice(v, r),
                                     causal=causal)

        out = _assemble(run(fn)(q, k, v))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-10, atol=1e-12)

    @pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
    def test_spmd_grads_match_dense(self):
        q, k, v = qkv()

        def dense_loss(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

        ref_grads = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)

        def fn(q, k, v):
            r = comm.rank
            out = ulysses_attention(comm, local_slice(q, r),
                                    local_slice(k, r), local_slice(v, r),
                                    causal=True)
            return jnp.sum(out ** 2)

        got = jax.grad(lambda q, k, v: run(fn)(q, k, v).sum(),
                       argnums=(0, 1, 2))(q, k, v)
        for g, want in zip(got, ref_grads):
            np.testing.assert_allclose(np.asarray(g), np.asarray(want),
                                       rtol=1e-9, atol=1e-11)

    def test_eager_matches_dense(self):
        q, k, v = qkv()
        ref = np.asarray(dense_attention(q, k, v, causal=False))

        def body():
            r = comm.rank
            out = ulysses_attention(comm, q[:, r * SL:(r + 1) * SL],
                                    k[:, r * SL:(r + 1) * SL],
                                    v[:, r * SL:(r + 1) * SL])
            return np.asarray(out)

        outs = mpi.run_ranks(body, NR)
        got = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(got, ref, rtol=1e-10, atol=1e-12)

    def test_head_divisibility_error(self):
        def fn(q):
            return ulysses_attention(comm, q, q, q)

        with pytest.raises(ValueError, match="divisible"):
            run(fn)(jnp.ones((1, SL, 3, 2)))

    def test_ulysses_with_pallas_blocks_matches_dense(self):
        # Kernel-eligible shapes through the full SP path (interpret
        # mode): post-reshuffle each rank runs the fused primitive on the
        # complete sequence of its head group.
        NR4, S_TOT = 4, 512
        rng = np.random.default_rng(11)
        q, k, v = (jnp.asarray(rng.standard_normal((1, S_TOT, 4, 128)),
                               jnp.float32) for _ in range(3))
        ref = dense_attention(q, k, v, causal=True)
        sl = S_TOT // NR4

        def body():
            r = jnp.asarray(comm.rank)
            s = [jax.lax.dynamic_slice_in_dim(t, r * sl, sl, 1)
                 for t in (q, k, v)]
            return ulysses_attention(comm, *s, causal=True, impl="pallas")

        out = np.asarray(mpi.run_spmd(body, nranks=NR4)())
        got = np.concatenate(list(out), axis=1)
        np.testing.assert_allclose(got, np.asarray(ref), rtol=2e-4,
                                   atol=2e-5)


# ---------------------------------------------------------------------------
# DP helpers
# ---------------------------------------------------------------------------


class TestDpHelpers:
    def test_dp_value_and_grad_lockstep(self):
        from mpi4torch_tpu.parallel import dp_value_and_grad

        rng = np.random.default_rng(11)
        X = jnp.asarray(rng.standard_normal((8 * NR, 3)))
        y = jnp.asarray(rng.standard_normal(8 * NR))
        w0 = jnp.asarray(rng.standard_normal(3))

        def local_loss(w, batch):
            xb, yb = batch
            return jnp.mean((xb @ w - yb) ** 2)

        # single-process oracle on the full data
        ref_val, ref_grad = jax.value_and_grad(
            lambda w: local_loss(w, (X, y)))(w0)

        def body():
            r = comm.rank
            batch = (X[r * 8:(r + 1) * 8], y[r * 8:(r + 1) * 8])
            f = dp_value_and_grad(comm, local_loss)
            val, grad = f(w0, batch)
            return np.asarray(val), np.asarray(grad)

        outs = mpi.run_ranks(body, NR)
        for val, grad in outs:
            np.testing.assert_allclose(val, np.asarray(ref_val), rtol=1e-12)
            np.testing.assert_allclose(grad, np.asarray(ref_grad),
                                       rtol=1e-12, atol=1e-14)

    def test_mlp_dp_train_step_rank_count_invariant(self):
        # models.mlp's DP wrappers (over parallel.dp) keep replicas in
        # lock-step and match the single-process full-data run.
        from mpi4torch_tpu.models import mlp

        rng = np.random.default_rng(12)
        X = jnp.asarray(rng.standard_normal((16, 4)))
        Y = jnp.asarray(rng.standard_normal((16, 2)))
        p0 = mlp.init_params(jax.random.PRNGKey(2), (4, 8, 2),
                             dtype=jnp.float64)

        ref_loss, ref_params = mlp.dp_train_step(comm, p0, (X, Y), lr=0.1)

        def body():
            r = comm.rank
            batch = (X[r * 4:(r + 1) * 4], Y[r * 4:(r + 1) * 4])
            loss, params = mlp.dp_train_step(comm, p0, batch, lr=0.1)
            return float(loss), jax.tree.map(np.asarray, params)

        outs = mpi.run_ranks(body, NR)
        for loss, params in outs:
            np.testing.assert_allclose(loss, float(ref_loss), rtol=1e-12)
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    a, np.asarray(b), rtol=1e-12, atol=1e-14),
                params, ref_params)


# ---------------------------------------------------------------------------
# Zigzag (load-balanced) ring attention
# ---------------------------------------------------------------------------


class TestZigzagRingAttention:
    """Causal ring attention on the zigzag layout (rank r owns chunk r +
    mirror chunk 2N-1-r): must equal dense attention over the full
    sequence after the layout permutation, values and gradients — the
    load balance changes WHO computes what, never the math."""

    def _perm(self):
        from mpi4torch_tpu.parallel import zigzag_positions
        return np.concatenate(list(zigzag_positions(NR, SL)))

    def test_spmd_matches_dense(self):
        from mpi4torch_tpu.parallel import (zigzag_ring_attention,
                                            zigzag_slice)
        q, k, v = qkv()
        ref = dense_attention(q, k, v, causal=True)

        def fn(q, k, v):
            return zigzag_ring_attention(
                comm, zigzag_slice(comm, q), zigzag_slice(comm, k),
                zigzag_slice(comm, v))

        stacked = run(fn)(q, k, v)          # (NR, B, SL, H, D)
        out = _assemble(stacked)
        # Row r of zigzag_positions gives rank r's global positions:
        # scatter the concatenated outputs back to sequence order.
        inv = np.empty(S, np.int64)
        inv[self._perm()] = np.arange(S)
        out = out[:, inv]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-10, atol=1e-12)

    def test_spmd_grads_match_dense(self):
        from mpi4torch_tpu.parallel import (zigzag_ring_attention,
                                            zigzag_slice)
        q, k, v = qkv()

        def dense_loss(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

        ref_grads = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)

        def fn(q, k, v):
            out = zigzag_ring_attention(
                comm, zigzag_slice(comm, q), zigzag_slice(comm, k),
                zigzag_slice(comm, v))
            return jnp.sum(out ** 2)

        got = jax.grad(lambda q, k, v: run(fn)(q, k, v).sum(),
                       argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(got, ref_grads):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-9, atol=1e-11)

    def test_eager_matches_dense(self):
        from mpi4torch_tpu.parallel import (zigzag_positions,
                                            zigzag_ring_attention,
                                            zigzag_slice)
        q, k, v = qkv()
        ref = np.asarray(dense_attention(q, k, v, causal=True))
        pos = zigzag_positions(NR, SL)

        def body():
            out = zigzag_ring_attention(
                comm, zigzag_slice(comm, q), zigzag_slice(comm, k),
                zigzag_slice(comm, v))
            np.testing.assert_allclose(
                np.asarray(out), ref[:, pos[comm.rank]],
                rtol=1e-10, atol=1e-12)

        mpi.run_ranks(body, NR)

    def test_odd_local_length_raises(self):
        from mpi4torch_tpu.parallel import zigzag_ring_attention

        def fn(q):
            return zigzag_ring_attention(comm, q, q, q)

        with pytest.raises(ValueError, match="odd"):
            run(fn)(jnp.ones((1, 3, 1, 4)))

    def test_indivisible_global_raises(self):
        from mpi4torch_tpu.parallel import zigzag_slice

        def fn(q):
            return zigzag_slice(comm, q)

        with pytest.raises(ValueError, match="divisible"):
            run(fn)(jnp.ones((1, 2 * NR + 1, 1, 4)))

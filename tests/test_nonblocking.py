"""Port of the reference nonblocking ring tests (reference:
tests/test_nonblocking.py:1-35): Isend/Irecv/Wait rings in three orderings,
with full JoinDummies/JoinDummiesHandle token threading.  The gradient
oracle ``grad == neighbor_rank * ones`` proves the gradient traveled the
ring *backwards* over the network (reverse-flow messages on tag+10,
csrc/extension.cpp:1159-1218).

The reference uses 10M-element doubles to force true rendezvous-protocol
asynchrony; the thread runtime's mailbox semantics are size-independent, so
1M elements keep the same coverage at test-friendly cost."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm, run_ranks

N = 1_000_000
SIZES = [2, 5, 7]


@pytest.mark.parametrize("nranks", SIZES)
def test_simple_isendirecv(nranks):
    # reference: tests/test_nonblocking.py:8-16
    def body():
        tmp = jnp.asarray(np.random.rand(N))

        def loss(t):
            req = comm.Isend(t, (comm.rank + 1) % comm.size, 0)
            req2 = comm.Irecv(
                mpi.JoinDummies(jnp.empty_like(t), [req.dummy]),
                (comm.rank + comm.size - 1) % comm.size, 0)
            res = comm.Wait(mpi.JoinDummiesHandle(req, [req2.dummy]))
            res2 = comm.Wait(mpi.JoinDummiesHandle(req2, [res]))
            res3 = res2 * comm.rank
            return res3.sum()

        grad = jax.grad(loss)(tmp)
        assert (grad == ((comm.rank + 1) % comm.size) * jnp.ones_like(tmp)).all()

    run_ranks(body, nranks)


@pytest.mark.parametrize("nranks", SIZES)
def test_simple_isendrecv(nranks):
    # reference: tests/test_nonblocking.py:18-26
    def body():
        tmp = jnp.asarray(np.random.rand(N))

        def loss(t):
            req = comm.Isend(t, (comm.rank + 1) % comm.size, 0)
            res = comm.Recv(
                mpi.JoinDummies(jnp.empty_like(t), [req.dummy]),
                (comm.rank + comm.size - 1) % comm.size, 0)
            res2 = comm.Wait(mpi.JoinDummiesHandle(req, [res]))
            res3 = mpi.JoinDummies(res, [res2]) * comm.rank
            return res3.sum()

        grad = jax.grad(loss)(tmp)
        assert (grad == ((comm.rank + 1) % comm.size) * jnp.ones_like(tmp)).all()

    run_ranks(body, nranks)


@pytest.mark.parametrize("nranks", SIZES)
def test_simple_irecvsend(nranks):
    # reference: tests/test_nonblocking.py:28-35
    def body():
        tmp = jnp.asarray(np.random.rand(N))

        def loss(t):
            req = comm.Irecv(
                mpi.JoinDummies(jnp.empty_like(t), [t]),
                (comm.rank + comm.size - 1) % comm.size, 0)
            res = comm.Send(t, (comm.rank + 1) % comm.size, 0)
            res2 = comm.Wait(mpi.JoinDummiesHandle(req, [res]))
            res3 = res2 * comm.rank
            return res3.sum()

        grad = jax.grad(loss)(tmp)
        assert (grad == ((comm.rank + 1) % comm.size) * jnp.ones_like(tmp)).all()

    run_ranks(body, nranks)


def test_forward_ring_values():
    # Forward-only ring: every rank receives its left neighbor's payload
    # (reference usage: examples/isend-recv-wait.py:8-13).
    def body():
        a = jnp.asarray([1.0 + comm.rank])
        handle = comm.Isend(a, (comm.rank + 1) % comm.size, 0)
        recvbuf = mpi.JoinDummies(jnp.empty_like(a), [handle.dummy])
        b = comm.Recv(recvbuf, (comm.rank - 1 + comm.size) % comm.size, 0)
        wait_ret = comm.Wait(mpi.JoinDummiesHandle(handle, [b]))
        res = mpi.JoinDummies(a + b, [wait_ret])
        left = (comm.rank - 1 + comm.size) % comm.size
        assert res[0] == (1.0 + comm.rank) + (1.0 + left)

    run_ranks(body, 5)

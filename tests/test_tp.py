"""Tensor-parallel layers must match their dense single-device oracles in
values and gradients on both backends — the §2.5 TP row made executable.
The reference provides the TP glue ops (axis-aware Gather/Allgather/Scatter,
csrc/extension.cpp:497-884) but no layers; these tests pin down the layer
semantics built on them."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm
from mpi4torch_tpu.parallel import (
    column_parallel_linear,
    row_parallel_linear,
    shard_axis,
    tp_attention,
    tp_mlp,
)
from mpi4torch_tpu.parallel.attention import dense_attention

NR = 4
B, S, DM, FF = 2, 6, 8, 16


def run(fn, **kw):
    return mpi.run_spmd(fn, nranks=NR, **kw)


def params(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "x": jnp.asarray(rng.standard_normal((B, S, DM))),
        "w1": jnp.asarray(rng.standard_normal((DM, FF)) / np.sqrt(DM)),
        "b1": jnp.asarray(rng.standard_normal(FF)),
        "w2": jnp.asarray(rng.standard_normal((FF, DM)) / np.sqrt(FF)),
        "b2": jnp.asarray(rng.standard_normal(DM)),
    }


class TestShardAxis:
    def test_rank_major_shards(self):
        x = jnp.arange(8.0)

        def body():
            return np.asarray(shard_axis(comm, x, 0))

        outs = mpi.run_ranks(body, NR)
        for r in range(NR):
            np.testing.assert_array_equal(outs[r], np.arange(8.0)[2 * r:2 * r + 2])

    def test_indivisible_raises(self):
        with pytest.raises(ValueError, match="not divisible"):
            def body():
                return shard_axis(comm, jnp.ones(7), 0)
            mpi.run_ranks(body, NR)


class TestColumnRowParallel:
    def test_column_parallel_matches_dense(self):
        p = params()
        dense = p["x"] @ p["w1"] + p["b1"]

        def fn():
            w = shard_axis(comm, p["w1"], 1)
            b = shard_axis(comm, p["b1"], 0)
            return column_parallel_linear(comm, p["x"], w, b)

        out = run(fn)()
        for r in range(NR):
            np.testing.assert_allclose(np.asarray(out[r]), dense, rtol=1e-12)

    def test_row_parallel_matches_dense(self):
        p = params()
        x_full = p["x"]
        w_full = jnp.asarray(np.random.default_rng(3).standard_normal((DM, DM)))
        dense = x_full @ w_full + p["b2"]

        def fn():
            xs = shard_axis(comm, x_full, 2)
            ws = shard_axis(comm, w_full, 0)
            return row_parallel_linear(comm, xs, ws, p["b2"])

        out = run(fn)()
        for r in range(NR):
            np.testing.assert_allclose(np.asarray(out[r]), dense, rtol=1e-10)

    def test_tp_mlp_value_and_grads_match_dense(self):
        p = params()

        def dense_mlp(p):
            return jnp.sum(
                jax.nn.gelu(p["x"] @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"])

        def tp_loss(p):
            # The reference's lock-step recipe (doc/examples.rst:46-65)
            # applied to TP: the param-averaging Allreduce's adjoint
            # reassembles the disjoint shard gradients (and cancels the
            # row-layer Allreduce's rank-count factor), so EVERY rank ends
            # up holding the exact full dense gradient.
            from mpi4torch_tpu.parallel import all_average_tree
            p = all_average_tree(comm, p)
            w1 = shard_axis(comm, p["w1"], 1)
            b1 = shard_axis(comm, p["b1"], 0)
            w2 = shard_axis(comm, p["w2"], 0)
            return jnp.sum(tp_mlp(comm, p["x"], w1, b1, w2, p["b2"]))

        val_d, g_d = jax.value_and_grad(dense_mlp)(p)

        def body():
            val, g = jax.value_and_grad(tp_loss)(p)
            return np.asarray(val), jax.tree.map(np.asarray, g)

        outs = mpi.run_ranks(body, NR)
        for r in range(NR):
            val, g = outs[r]
            np.testing.assert_allclose(val, np.asarray(val_d), rtol=1e-10)
            for k in ("x", "w1", "b1", "w2", "b2"):
                np.testing.assert_allclose(
                    g[k], np.asarray(g_d[k]), rtol=1e-9, atol=1e-11,
                    err_msg=f"rank {r} grad {k}")

    def test_spmd_tp_mlp_matches_dense(self):
        p = params(1)
        dense = jax.nn.gelu(p["x"] @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]

        def fn():
            w1 = shard_axis(comm, p["w1"], 1)
            b1 = shard_axis(comm, p["b1"], 0)
            w2 = shard_axis(comm, p["w2"], 0)
            return tp_mlp(comm, p["x"], w1, b1, w2, p["b2"])

        out = run(fn)()
        for r in range(NR):
            np.testing.assert_allclose(np.asarray(out[r]), np.asarray(dense),
                                       rtol=1e-10)


class TestTPAttention:
    def test_matches_dense_attention(self):
        rng = np.random.default_rng(11)
        n_heads = 4
        x = jnp.asarray(rng.standard_normal((B, S, DM)))
        wq, wk, wv, wo = (
            jnp.asarray(rng.standard_normal((DM, DM)) / np.sqrt(DM))
            for _ in range(4))

        def dense_oracle():
            def heads(t):
                return t.reshape(B, S, n_heads, DM // n_heads)
            o = dense_attention(heads(x @ wq), heads(x @ wk), heads(x @ wv),
                                causal=True)
            return o.reshape(B, S, DM) @ wo

        expect = np.asarray(dense_oracle())

        def fn():
            q = shard_axis(comm, wq, 1)
            k = shard_axis(comm, wk, 1)
            v = shard_axis(comm, wv, 1)
            o = shard_axis(comm, wo, 0)
            return tp_attention(comm, q, k, v, o, x, n_heads)

        out = run(fn)()
        for r in range(NR):
            np.testing.assert_allclose(np.asarray(out[r]), expect, rtol=1e-9,
                                       atol=1e-11)

    def test_head_divisibility_error(self):
        with pytest.raises(ValueError, match="divisible"):
            def body():
                z = jnp.zeros((1, 2, 6))
                w = jnp.zeros((6, 6))
                return tp_attention(comm, w, w, w, w, z, n_heads=3)
            mpi.run_ranks(body, NR)

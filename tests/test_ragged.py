"""Ragged collectives: capacity-padded + masked per-rank-varying exchange
(SURVEY.md §7 hard part 2 — the SPMD-compatible form of the reference's
Gatherv/Alltoallv semantics, csrc/extension.cpp:540-554, 947-979).

Oracles: explicit routing tables built in numpy; identical results on the
eager and SPMD backends; gradients route back through the exchange with
zero gradient into padding slots."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm
from mpi4torch_tpu.ops import (block_gather, block_scatter,
                               ragged_allgather, ragged_alltoall,
                               ragged_gather, ragged_scatter, segment_mask)

NR = 4
CAP = 5
FEAT = 3

# counts[src][dst] = rows src sends to dst (varying, some zero).
COUNTS = np.array([[1, 2, 0, 3],
                   [4, 0, 1, 2],
                   [0, 5, 2, 1],
                   [2, 1, 3, 0]])


def payload(src):
    """Deterministic payload: row r of src's block for dst carries value
    100*src + 10*dst + r in every feature slot."""
    x = np.zeros((NR, CAP, FEAT))
    for dst in range(NR):
        for r in range(COUNTS[src][dst]):
            x[dst, r, :] = 100 * src + 10 * dst + r
    # Poison the padding so masking is actually load-bearing.
    for dst in range(NR):
        x[dst, COUNTS[src][dst]:, :] = -999.0
    return jnp.asarray(x)


def expected_recv(dst):
    r = np.zeros((NR, CAP, FEAT))
    for src in range(NR):
        for row in range(COUNTS[src][dst]):
            r[src, row, :] = 100 * src + 10 * dst + row
    return r


class TestRaggedAlltoall:
    def test_eager_matches_routing_oracle(self):
        def body():
            r = int(comm.rank)
            recv, rc = ragged_alltoall(comm, payload(r),
                                       jnp.asarray(COUNTS)[r])
            return np.asarray(recv), np.asarray(rc)

        outs = mpi.run_ranks(body, NR)
        for dst, (recv, rc) in enumerate(outs):
            np.testing.assert_array_equal(recv, expected_recv(dst))
            np.testing.assert_array_equal(rc, COUNTS[:, dst])

    def test_spmd_matches_eager(self):
        def body():
            rk = jnp.asarray(comm.rank)
            x = jnp.stack([payload(s) for s in range(NR)])[rk]
            cnt = jnp.asarray(COUNTS)[rk]
            return ragged_alltoall(comm, x, cnt)

        recv, rc = mpi.run_spmd(body, nranks=NR)()
        for dst in range(NR):
            np.testing.assert_array_equal(np.asarray(recv)[dst],
                                          expected_recv(dst))
            np.testing.assert_array_equal(np.asarray(rc)[dst],
                                          COUNTS[:, dst])

    def test_grads_route_back_and_padding_gets_zero(self):
        def body():
            r = int(comm.rank)
            x = payload(r)
            cnt = jnp.asarray(COUNTS)[r]

            def loss(x):
                recv, _ = ragged_alltoall(comm, x, cnt)
                return jnp.sum(recv)

            return np.asarray(jax.grad(loss)(x))

        grads = mpi.run_ranks(body, NR)
        for src, g in enumerate(grads):
            mask = np.zeros((NR, CAP, FEAT))
            for dst in range(NR):
                mask[dst, :COUNTS[src][dst], :] = 1.0
            # Valid slots got cotangent 1 (delivered across ranks); the
            # poisoned padding slots got exactly zero.
            np.testing.assert_array_equal(g, mask)

    def test_shape_validation(self):
        def body():
            with pytest.raises(ValueError, match="capacity"):
                ragged_alltoall(comm, jnp.zeros((2, CAP, 1)),
                                jnp.zeros((NR,), jnp.int32))
            with pytest.raises(ValueError, match="send_counts"):
                ragged_alltoall(comm, jnp.zeros((NR, CAP, 1)),
                                jnp.zeros((2,), jnp.int32))
            return True

        assert all(mpi.run_ranks(body, NR))


class TestRaggedAllgather:
    def test_reconstructs_allgatherv(self):
        lens = [2, 5, 1, 3]

        def body():
            r = int(comm.rank)
            x = np.full((CAP, FEAT), -999.0)
            x[:lens[r]] = 10 * r + np.arange(lens[r])[:, None]
            g, c = ragged_allgather(comm, jnp.asarray(x), lens[r])
            return np.asarray(g), np.asarray(c)

        outs = mpi.run_ranks(body, NR)
        want = np.concatenate([
            (10 * r + np.arange(lens[r])[:, None]) * np.ones((1, FEAT))
            for r in range(NR)])
        for g, c in outs:
            np.testing.assert_array_equal(c, lens)
            got = np.concatenate([g[r, :lens[r]] for r in range(NR)])
            np.testing.assert_array_equal(got, want)

    def test_spmd_backend(self):
        lens = jnp.asarray([2, 5, 1, 3])

        def body():
            r = jnp.asarray(comm.rank)
            base = jnp.arange(CAP, dtype=jnp.float64)[:, None] + 10.0 * r
            x = jnp.broadcast_to(base, (CAP, FEAT))
            return ragged_allgather(comm, x, lens[r])

        g, c = mpi.run_spmd(body, nranks=NR)()
        g, c = np.asarray(g), np.asarray(c)
        for dst in range(NR):
            np.testing.assert_array_equal(c[dst], [2, 5, 1, 3])
            for src in range(NR):
                valid = g[dst, src, :int(c[dst][src])]
                expect = (10.0 * src
                          + np.arange(int(c[dst][src]))[:, None]
                          ) * np.ones((1, FEAT))
                np.testing.assert_array_equal(valid, expect)
                np.testing.assert_array_equal(
                    g[dst, src, int(c[dst][src]):], 0.0)


class TestSegmentMask:
    def test_mask_shape_and_values(self):
        m = np.asarray(segment_mask(jnp.asarray([0, 2, 5]), 5))
        np.testing.assert_array_equal(m[0], np.zeros(5))
        np.testing.assert_array_equal(m[1], [1, 1, 0, 0, 0])
        np.testing.assert_array_equal(m[2], np.ones(5))

    def test_scalar_count_gives_1d_mask(self):
        m = np.asarray(segment_mask(jnp.asarray(3), 5))
        assert m.shape == (5,)
        np.testing.assert_array_equal(m, [1, 1, 1, 0, 0])


class TestRobustness:
    def test_over_capacity_counts_are_clamped(self):
        # A count > capacity must not transmit a recv_count larger than
        # the actual zero-padded valid data.
        def body():
            r = int(comm.rank)
            x = jnp.ones((NR, CAP, FEAT))
            cnt = jnp.full((NR,), CAP + 3)
            recv, rc = ragged_alltoall(comm, x, cnt)
            return np.asarray(rc)

        for rc in mpi.run_ranks(body, NR):
            np.testing.assert_array_equal(rc, np.full(NR, CAP))

    def test_allgather_rejects_vector_count(self):
        def body():
            with pytest.raises(ValueError, match="scalar"):
                ragged_allgather(comm, jnp.zeros((CAP, FEAT)),
                                 jnp.zeros((NR,), jnp.int32))
            return True

        assert all(mpi.run_ranks(body, NR))

    def test_nan_padding_does_not_leak(self):
        # Padding may hold NaN (e.g. masked-softmax leftovers); the
        # exchange must still deliver zeros in invalid slots.
        def body():
            r = int(comm.rank)
            x = jnp.where(jnp.isnan(jnp.full((NR, CAP, FEAT), jnp.nan)),
                          jnp.nan, 0.0)
            x = x.at[:, 0].set(1.0)
            recv, rc = ragged_alltoall(comm, x, jnp.ones((NR,), jnp.int32))
            return np.asarray(recv)

        for recv in mpi.run_ranks(body, NR):
            assert np.all(np.isfinite(recv))
            np.testing.assert_array_equal(recv[:, 0], 1.0)
            np.testing.assert_array_equal(recv[:, 1:], 0.0)

    def test_negative_counts_clamped_to_zero(self):
        def body():
            x = jnp.ones((NR, CAP, FEAT))
            recv, rc = ragged_alltoall(comm, x, jnp.full((NR,), -2))
            return np.asarray(rc), np.asarray(recv)

        for rc, recv in mpi.run_ranks(body, NR):
            np.testing.assert_array_equal(rc, 0)
            np.testing.assert_array_equal(recv, 0.0)

    def test_allgather_clamps_count(self):
        def body():
            g, c = ragged_allgather(comm, jnp.ones((CAP, FEAT)), CAP + 9)
            return np.asarray(c)

        for c in mpi.run_ranks(body, NR):
            np.testing.assert_array_equal(c, np.full(NR, CAP))


# Root-varying Gatherv/Scatterv (reference: varying ``numelem`` cases,
# tests/test_collectives.py:121-125, csrc/extension.cpp:540-577, 839-871).
GLENS = np.array([2, 0, 3, 1])          # per-rank valid lengths
ROOT = 2


def gv_payload(r):
    """Rank r's padded block: row i carries 10*r + i; padding poisoned."""
    x = np.full((CAP, FEAT), -999.0)
    for i in range(GLENS[r]):
        x[i, :] = 10 * r + i
    return jnp.asarray(x)


def gv_expected():
    g = np.zeros((NR, CAP, FEAT))
    for r in range(NR):
        for i in range(GLENS[r]):
            g[r, i, :] = 10 * r + i
    return g


def packed(gathered, counts):
    """MPI_Gatherv's packed result: concatenated valid prefixes."""
    return np.concatenate([np.asarray(gathered)[s, :c]
                           for s, c in enumerate(np.asarray(counts))])


class TestRaggedGatherScatter:
    def test_eager_gather_matches_oracle(self):
        def body():
            r = int(comm.rank)
            g, c = ragged_gather(comm, gv_payload(r),
                                 jnp.asarray(GLENS)[r], root=ROOT)
            return np.asarray(g), np.asarray(c)

        outs = mpi.run_ranks(body, NR)
        g_root, c_root = outs[ROOT]
        np.testing.assert_array_equal(g_root, gv_expected())
        np.testing.assert_array_equal(c_root, GLENS)
        ref_packed = np.concatenate(
            [np.asarray(gv_payload(r))[:GLENS[r]] for r in range(NR)])
        np.testing.assert_array_equal(packed(g_root, c_root), ref_packed)
        for r, (g, c) in enumerate(outs):
            if r != ROOT:
                np.testing.assert_array_equal(g, 0.0)   # zeroed non-root
                np.testing.assert_array_equal(c, 0)

    def test_spmd_gather_matches_eager(self):
        lens = jnp.asarray(GLENS)

        def body():
            r = jnp.asarray(comm.rank)
            x = jnp.where(
                jnp.arange(CAP)[:, None] < lens[r],
                (10.0 * r + jnp.arange(CAP))[:, None]
                * jnp.ones((CAP, FEAT)),
                -999.0)
            return ragged_gather(comm, x, lens[r], root=ROOT)

        g, c = mpi.run_spmd(body, nranks=NR)()
        np.testing.assert_array_equal(np.asarray(g)[ROOT], gv_expected())
        np.testing.assert_array_equal(np.asarray(c)[ROOT], GLENS)
        for r in range(NR):
            if r != ROOT:
                np.testing.assert_array_equal(np.asarray(g)[r], 0.0)

    @pytest.mark.parametrize("backend", ["eager", "spmd"])
    def test_scatter_inverts_gather_on_valid_prefixes(self, backend):
        # Scatterv(Gatherv(x)) == x on valid slots, zeros on padding —
        # the reference's Scatter∘Gather identity with varying numelem.
        lens = jnp.asarray(GLENS)

        def body():
            r = jnp.asarray(comm.rank)
            x = jnp.where(
                jnp.arange(CAP)[:, None] < lens[r],
                (10.0 * r + jnp.arange(CAP))[:, None]
                * jnp.ones((CAP, FEAT)),
                -999.0)
            g, c = ragged_gather(comm, x, lens[r], root=ROOT)
            recv, mc = ragged_scatter(comm, g, c, root=ROOT)
            return recv, mc

        if backend == "eager":
            outs = mpi.run_ranks(lambda: tuple(
                np.asarray(t) for t in body()), NR)
        else:
            recv, mc = mpi.run_spmd(body, nranks=NR)()
            outs = [(np.asarray(recv)[r], np.asarray(mc)[r])
                    for r in range(NR)]
        for r, (recv, mc) in enumerate(outs):
            np.testing.assert_array_equal(mc, GLENS[r])
            expect = np.zeros((CAP, FEAT))
            for i in range(GLENS[r]):
                expect[i, :] = 10 * r + i
            np.testing.assert_array_equal(recv, expect)

    def test_gather_grads_route_back_padding_zero(self):
        lens = jnp.asarray(GLENS)

        def body():
            r = int(comm.rank)

            def loss(x):
                g, _ = ragged_gather(comm, x, lens[r], root=ROOT)
                return jnp.sum(g * 2.0)

            return np.asarray(jax.grad(loss)(jnp.ones((CAP, FEAT))))

        for r, grad in enumerate(mpi.run_ranks(body, NR)):
            expect = np.zeros((CAP, FEAT))
            expect[:GLENS[r]] = 2.0       # valid slots see the cotangent
            np.testing.assert_array_equal(grad, expect)

    def test_scatter_grads_route_back_padding_zero(self):
        lens = jnp.asarray(GLENS)

        def body():
            r = int(comm.rank)

            def loss(x):
                recv, _ = ragged_scatter(comm, x, lens, root=ROOT)
                return jnp.sum(recv * 3.0)

            return np.asarray(jax.grad(loss)(jnp.ones((NR, CAP, FEAT))))

        grads = mpi.run_ranks(body, NR)
        expect_root = np.zeros((NR, CAP, FEAT))
        for r in range(NR):
            expect_root[r, :GLENS[r]] = 3.0
        np.testing.assert_array_equal(grads[ROOT], expect_root)
        for r, g in enumerate(grads):
            if r != ROOT:
                np.testing.assert_array_equal(g, 0.0)  # root-only input

    def test_shape_validation(self):
        def body():
            with pytest.raises(ValueError, match="capacity"):
                ragged_gather(comm, jnp.asarray(0.0), 1)
            with pytest.raises(ValueError, match="scalar"):
                ragged_gather(comm, jnp.zeros((CAP,)), jnp.zeros((2,)))
            with pytest.raises(ValueError, match="size"):
                ragged_scatter(comm, jnp.zeros((NR + 1, CAP)),
                               jnp.zeros((NR,)))
            with pytest.raises(ValueError, match="shape"):
                ragged_scatter(comm, jnp.zeros((NR, CAP)),
                               jnp.zeros((NR + 1,)))
            return True

        assert all(mpi.run_ranks(body, NR))


# ---------------------------------------------------------------------------
# Paged KV-pool primitives (ISSUE 17): block_gather / block_scatter.
# Pure single-device ops — the serving engine drives them through the
# block table; here they are pinned standalone.
# ---------------------------------------------------------------------------


def _pool(nb=5, bs=3, feat=(2,), dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((nb, bs) + feat).astype(dtype)


class TestBlockGather:
    def test_oracle_concat(self):
        pool = _pool()
        table = np.array([[2, 0], [4, 4]], np.int32)
        got = np.asarray(block_gather(pool, table))
        want = np.stack([np.concatenate([pool[2], pool[0]]),
                         np.concatenate([pool[4], pool[4]])])
        np.testing.assert_array_equal(got, want)

    def test_unmapped_tail_blocks_inert(self):
        # -1 entries (the engine's free convention) come back as ZERO
        # pages — even when the pool holds NaN poison elsewhere, the
        # padded tail must be inert, not plausible.
        pool = _pool()
        pool[3] = np.nan
        table = np.array([[1, -1, -1]], np.int32)
        got = np.asarray(block_gather(pool, table))
        np.testing.assert_array_equal(got[0, :3], pool[1])
        np.testing.assert_array_equal(got[0, 3:], 0.0)

    def test_dtype_preserved_bitwise(self):
        for dtype in (np.float16, np.float32, np.int32):
            pool = (np.arange(5 * 3 * 2).reshape(5, 3, 2) * 7 + 1) \
                .astype(dtype)
            got = np.asarray(block_gather(pool, np.array([[4, 2]])))
            assert got.dtype == dtype
            np.testing.assert_array_equal(
                got[0], np.concatenate([pool[4], pool[2]]))

    def test_table_is_data_not_structure(self):
        # One compiled program for EVERY table state — the no-retrace
        # contract the serving decode step rides on.
        pool = _pool()
        f = jax.jit(block_gather)
        t1 = np.array([[0, 1]], np.int32)
        t2 = np.array([[3, -1]], np.int32)
        np.testing.assert_array_equal(np.asarray(f(pool, t1)),
                                      np.asarray(block_gather(pool, t1)))
        np.testing.assert_array_equal(np.asarray(f(pool, t2)),
                                      np.asarray(block_gather(pool, t2)))
        assert f._cache_size() == 1

    def test_shape_validation(self):
        with pytest.raises(ValueError, match="pool"):
            block_gather(jnp.zeros((4,)), np.zeros((1, 1), np.int32))
        with pytest.raises(ValueError, match="table"):
            block_gather(jnp.zeros((4, 2)), np.zeros((3,), np.int32))


class TestBlockScatter:
    def test_one_hot_write_at_block_granularity(self):
        pool = _pool()
        out = np.asarray(block_scatter(
            pool, np.array([3, 1]), np.array([0, 2]),
            np.array([[10.0, 11.0], [20.0, 21.0]], np.float32)))
        want = pool.copy()
        want[3, 0] = [10.0, 11.0]
        want[1, 2] = [20.0, 21.0]
        np.testing.assert_array_equal(out, want)

    def test_negative_or_oob_targets_write_nothing(self):
        pool = _pool()
        vals = np.full((3, 2), 99.0, np.float32)
        out = np.asarray(block_scatter(
            pool, np.array([-1, 7, 2]), np.array([0, 1, 9]), vals))
        np.testing.assert_array_equal(out, pool)

    def test_active_mask_suppresses_writer(self):
        pool = _pool()
        vals = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        out = np.asarray(block_scatter(
            pool, np.array([0, 1]), np.array([0, 0]), vals,
            active=np.array([False, True])))
        want = pool.copy()
        want[1, 0] = [3.0, 4.0]
        np.testing.assert_array_equal(out, want)

    def test_untouched_cells_bitwise_unchanged(self):
        # `where`-routed, never summed: a write elsewhere must not
        # perturb (or de-NaN) any other cell by a single bit.
        pool = _pool()
        pool[0, 0] = -0.0
        pool[2, 1] = np.nan
        out = np.asarray(block_scatter(
            pool, np.array([4]), np.array([2]),
            np.array([[5.0, 6.0]], np.float32)))
        np.testing.assert_array_equal(out[4, 2], [5.0, 6.0])
        assert np.signbit(out[0, 0]).all()
        assert np.isnan(out[2, 1]).all()

    def test_dtype_cast_to_pool(self):
        pool = _pool(dtype=np.float16)
        out = block_scatter(pool, np.array([1]), np.array([1]),
                            jnp.asarray([[1.5, 2.5]], jnp.float32))
        assert out.dtype == jnp.float16
        np.testing.assert_array_equal(np.asarray(out[1, 1]), [1.5, 2.5])

    def test_feature_shape_validation(self):
        with pytest.raises(ValueError, match="feature"):
            block_scatter(jnp.zeros((4, 2, 3)), np.array([0]),
                          np.array([0]), jnp.zeros((1, 5)))

    def test_scatter_then_gather_roundtrip(self):
        # The decode step's exact composition: write one row per slot,
        # gather each slot's pages back — the written row must come
        # back bit-identical through the table.
        pool = _pool(nb=6, bs=2)
        table = np.array([[0, 3], [5, 1]], np.int32)
        vals = np.array([[7.0, 8.0], [9.0, 10.0]], np.float32)
        # slot 0 writes position 3 (page table[0,1]=3, offset 1);
        # slot 1 writes position 0 (page table[1,0]=5, offset 0).
        out = block_scatter(pool, np.array([3, 5]), np.array([1, 0]),
                            vals)
        g = np.asarray(block_gather(out, table))
        np.testing.assert_array_equal(g[0, 3], vals[0])
        np.testing.assert_array_equal(g[1, 0], vals[1])

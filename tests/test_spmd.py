"""SPMD mesh-backend tests (Mode A): the reference oracles re-expressed over
an 8-virtual-device CPU mesh — the analogue of the reference CI's
oversubscribed `mpirun` (SURVEY.md §4), but single-trace SPMD with XLA
collectives.  Includes the cross-backend equivalence checks that play the
role of the reference's TorchScript-parity tests
(tests/test_collectives.py:14-21): the same program must give identical
results eagerly (thread-SPMD) and traced (mesh SPMD)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm

NR = 8


def run(fn, **kw):
    return mpi.run_spmd(fn, nranks=NR, **kw)


class TestAllreduceSpmd:
    def test_forward_and_grad(self):
        def fn(x):
            return comm.Allreduce(x * (comm.rank + 1), mpi.MPI_SUM)

        out = run(fn)(jnp.ones(4))
        assert out.shape == (NR, 4)
        expect = NR * (NR + 1) / 2
        assert (np.asarray(out) == expect).all()
        g = jax.grad(lambda x: run(fn)(x).sum())(jnp.ones(4))
        assert (np.asarray(g) == NR * expect).all()

    def test_jit_compatible(self):
        # The traced path *is* the compiled path — the analogue of the
        # reference's TorchScript test (tests/test_collectives.py:14-21).
        fn = run(lambda x: comm.Allreduce(x, mpi.MPI_SUM), jit=True)
        out1 = fn(jnp.ones(3))
        out2 = fn(jnp.ones(3) * 2)
        assert (np.asarray(out1) == NR).all()
        assert (np.asarray(out2) == 2 * NR).all()

    def test_max_forward_ok_backward_raises(self):
        def fn(x):
            return comm.Allreduce(x * (comm.rank + 1), mpi.MPI_MAX)

        out = run(fn)(jnp.ones(3))
        assert (np.asarray(out) == NR).all()
        with pytest.raises(RuntimeError, match="MPI_MAX"):
            jax.grad(lambda x: run(fn)(x).sum())(jnp.ones(3))

    def test_prod_and_bitwise_forward(self):
        out = run(lambda x: comm.Allreduce(x * 2, mpi.MPI_PROD))(jnp.ones(2))
        assert (np.asarray(out) == 2.0 ** NR).all()

        def bor(x):
            t = (x * 0 + (comm.rank + 0)).astype(jnp.int32)
            return comm.Allreduce(1 << t, mpi.MPI_BOR)

        out = run(bor)(jnp.zeros(2))
        assert (np.asarray(out) == (1 << NR) - 1).all()

    def test_deterministic_mode_matches_eager_oracle(self):
        # BASELINE.md north star: gradients bit-exact vs. the MPI-linear-
        # order reference.  The eager runtime reduces in ascending rank
        # order; deterministic SPMD mode must match it bit for bit.
        rng = np.random.default_rng(3)
        data = jnp.asarray(rng.standard_normal((NR, 513)).astype(np.float32))

        def spmd_fn(x):
            t = jax.lax.dynamic_index_in_dim(x, jnp.asarray(comm.rank + 0),
                                             0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM)

        with mpi.config.deterministic_mode(True):
            det = np.asarray(run(spmd_fn)(data))

        def eager_body(rank):
            return np.asarray(comm.Allreduce(data[rank], mpi.MPI_SUM))

        eager = mpi.run_ranks(eager_body, NR)
        for r in range(NR):
            np.testing.assert_array_equal(det[r], eager[r])

    def test_ring_fold_bit_identical_to_gather_fold(self, monkeypatch):
        # The O(1)-memory chunked ring fold (VERDICT r4 item 3) must
        # produce the very bits of the all-gather+fold and of the eager
        # MPI-linear-order oracle.  Force the ring path at test size and
        # a tiny chunk so the pipeline runs multi-chunk WITH padding
        # (513 f32 elems / 16-elem chunks = 33 chunks, last one padded).
        from mpi4torch_tpu.ops import spmd as spmd_mod
        rng = np.random.default_rng(7)
        data = jnp.asarray(rng.standard_normal((NR, 513)).astype(np.float32))

        def spmd_fn(x):
            t = jax.lax.dynamic_index_in_dim(x, jnp.asarray(comm.rank + 0),
                                             0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM)

        with mpi.config.deterministic_mode(True):
            gather_path = np.asarray(run(spmd_fn)(data))
            monkeypatch.setattr(mpi.config,
                                "_ordered_fold_gather_max_bytes", 0)
            monkeypatch.setattr(mpi.config, "_ordered_ring_chunk_bytes", 64)
            ring_path = np.asarray(run(spmd_fn)(data))

        np.testing.assert_array_equal(ring_path, gather_path)

        def eager_body(rank):
            return np.asarray(comm.Allreduce(data[rank], mpi.MPI_SUM))

        eager = mpi.run_ranks(eager_body, NR)
        for r in range(NR):
            np.testing.assert_array_equal(ring_path[r], eager[r])

    def test_ring_fold_single_chunk_and_exact_multiple(self, monkeypatch):
        # Degenerate pipeline shapes: one chunk (no pipelining) and an
        # exact chunk multiple (no padding).
        from mpi4torch_tpu.ops import spmd as spmd_mod
        rng = np.random.default_rng(11)
        data = jnp.asarray(rng.standard_normal((NR, 64)).astype(np.float32))

        def spmd_fn(x):
            t = jax.lax.dynamic_index_in_dim(x, jnp.asarray(comm.rank + 0),
                                             0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM)

        with mpi.config.deterministic_mode(True):
            want = np.asarray(run(spmd_fn)(data))
            monkeypatch.setattr(mpi.config,
                                "_ordered_fold_gather_max_bytes", 0)
            for chunk_bytes in (64 * 4, 16 * 4):   # 1 chunk; 4 exact chunks
                monkeypatch.setattr(mpi.config, "_ordered_ring_chunk_bytes",
                                    chunk_bytes)
                got = np.asarray(run(spmd_fn)(data))
                np.testing.assert_array_equal(got, want)

    @pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
    def test_ring_fold_reduce_scatter_matches(self, monkeypatch):
        # reduce_scatter's large-payload deterministic path is the
        # relay-routed ring fold (segment s delivered straight to rank s);
        # must equal the slice-before-fold bits.  Shapes cover: exact
        # chunk multiple, padded last chunk, single-chunk segments, and a
        # non-leading scatter axis (moveaxis round-trip).
        from mpi4torch_tpu.ops import spmd as spmd_mod
        rng = np.random.default_rng(13)
        cases = [
            ((NR * 8,), 0, 32),       # 4 exact chunks per segment
            ((NR * 9,), 0, 32),       # padded last chunk (9 f32 per seg)
            ((NR * 8,), 0, 8 * 4),    # one chunk per segment
            ((3, NR * 4, 2), 1, 32),  # non-leading axis, rest dims
        ]
        for shape, axis, chunk_bytes in cases:
            data = jnp.asarray(
                rng.standard_normal((NR,) + shape).astype(np.float32))

            def spmd_fn(x):
                t = jax.lax.dynamic_index_in_dim(
                    x, jnp.asarray(comm.rank + 0), 0, keepdims=False)
                return comm.Reduce_scatter(t, mpi.MPI_SUM, axis)

            with mpi.config.deterministic_mode(True):
                want = np.asarray(run(spmd_fn)(data))
                monkeypatch.setattr(
                    mpi.config, "_ordered_fold_gather_max_bytes", 0)
                monkeypatch.setattr(
                    mpi.config, "_ordered_ring_chunk_bytes", chunk_bytes)
                got = np.asarray(run(spmd_fn)(data))
                monkeypatch.setattr(
                    mpi.config, "_ordered_fold_gather_max_bytes",
                    4 * 1024 * 1024)
            np.testing.assert_array_equal(got, want, err_msg=str(
                (shape, axis, chunk_bytes)))


class TestReduceScatterSpmd:
    def test_forward_and_identity(self):
        def fn(x):
            rs = comm.Reduce_scatter(x * (comm.rank + 1), mpi.MPI_SUM, 0)
            ag = comm.Allgather(rs, 0)
            ar = comm.Allreduce(x * (comm.rank + 1), mpi.MPI_SUM)
            return rs, ag - ar

        rs, diff = run(fn)(jnp.ones((NR * 2,)))
        assert rs.shape == (NR, 2)
        assert (np.asarray(rs) == NR * (NR + 1) / 2).all()
        assert (np.asarray(diff) == 0).all()

    def test_grad_is_allgather(self):
        # Per-rank loss weights its shard by rank+1; summing the
        # per-rank backward seeds gives the concatenated weights.
        def fn(x):
            rs = comm.Reduce_scatter(x, mpi.MPI_SUM, 0)
            w = jnp.asarray(comm.rank + 1, rs.dtype)
            return jnp.sum(w * rs)

        g = jax.grad(lambda x: run(fn)(x).sum())(jnp.ones((NR * 2,)))
        want = np.repeat(np.arange(1, NR + 1, dtype=float), 2) * NR
        np.testing.assert_array_equal(np.asarray(g), want)

    def test_non_sum_forward_ok_backward_raises(self):
        def fn(x):
            return comm.Reduce_scatter(x * (comm.rank + 1), mpi.MPI_MAX, 0)

        out = run(fn)(jnp.ones((NR,)))
        assert (np.asarray(out) == NR).all()
        with pytest.raises(RuntimeError, match="MPI_MAX"):
            jax.grad(lambda x: run(fn)(x).sum())(jnp.ones((NR,)))

    def test_deterministic_mode_matches_eager_order(self):
        # Under deterministic reductions the lowering is ordered-fold +
        # slice; values must still satisfy the allreduce identity.
        def fn(x):
            rs = comm.Reduce_scatter(x * (comm.rank + 1), mpi.MPI_SUM, 0)
            ar = comm.Allreduce(x * (comm.rank + 1), mpi.MPI_SUM)
            return comm.Allgather(rs, 0) - ar

        with mpi.config.deterministic_mode(True):
            diff = run(fn)(jnp.ones((NR * 2,)))
        assert (np.asarray(diff) == 0).all()

    def test_indivisible_axis_raises(self):
        with pytest.raises(mpi.CommError, match="divisible"):
            run(lambda x: comm.Reduce_scatter(x, mpi.MPI_SUM, 0))(
                jnp.ones((NR + 1,)))


class TestBcastReduceSpmd:
    def test_bcast_forward_and_grad(self):
        def fn(x):
            return comm.Bcast_(x * (comm.rank + 1), 2)

        out = np.asarray(run(fn)(jnp.ones(3)))
        assert (out == 3.0).all()  # root 2 holds x*3, broadcast everywhere

        # grad w.r.t. replicated x: every rank's output is x*(root+1);
        # d/dx sum over ranks = NR * 3
        g = jax.grad(lambda x: run(fn)(x).sum())(jnp.ones(3))
        assert (np.asarray(g) == NR * 3.0).all()

    def test_reduce_zeroes_nonroot(self):
        def fn(x):
            return comm.Reduce_(x * (comm.rank + 1), mpi.MPI_SUM, 0)

        out = np.asarray(run(fn)(jnp.ones(3)))
        assert (out[0] == NR * (NR + 1) / 2).all()
        assert (out[1:] == 0).all()

    def test_bcast_reduce_adjoint_pair(self):
        # Reduce_ grad == Bcast of upstream root gradient; exercised via a
        # root-weighted loss.
        def fn(x):
            return comm.Reduce_(x, mpi.MPI_SUM, 0)

        g = jax.grad(lambda x: run(fn)(x).sum())(jnp.ones(3))
        # each rank's input contributes only to root output; upstream grad
        # at root is 1 per element summed over... stacked loss sums all
        # ranks' outputs; only root row nonzero => grad = NR? No: root row
        # = sum of all ranks' x => d/dx (replicated) = NR * 1
        assert (np.asarray(g) == NR).all()


class TestShardOpsSpmd:
    def test_allgather_roundtrip_and_grad(self):
        def fn(x):
            t = x * (comm.rank + 1)
            return comm.Allgather(t, 0)

        out = np.asarray(run(fn)(jnp.ones((2, 3))))
        assert out.shape == (NR, 2 * NR, 3)
        for r in range(NR):
            for k in range(NR):
                assert (out[r, 2 * k:2 * k + 2] == k + 1).all()
        g = jax.grad(lambda x: run(fn)(x).sum())(jnp.ones((2, 3)))
        # every rank's t appears in every rank's output: sum_r sum_k (k+1)
        assert (np.asarray(g) == NR * NR * (NR + 1) / 2).all()

    def test_gather_root_only(self):
        def fn(x):
            return comm.Gather(x * (comm.rank + 1), 0, 3)

        out = np.asarray(run(fn)(jnp.ones((1, 2))))
        assert out.shape == (NR, NR, 2)
        for k in range(NR):
            assert (out[3, k] == k + 1).all()
        assert (out[np.arange(NR) != 3] == 0).all()

    def test_gather_grad_is_ones(self):
        # reference oracle (tests/test_collectives.py:58-63): grad of
        # Gather(...).sum() is ones on every rank.
        def fn(x):
            t = x * (comm.rank + 1)
            return comm.Gather(t, 0, 0)

        g = jax.grad(lambda x: run(fn)(x).sum())(jnp.ones((1, 2)))
        # d/dx: rank r's t = x*(r+1) lands once in root's gather =>
        # sum_r (r+1)
        assert (np.asarray(g) == NR * (NR + 1) / 2).all()

    def test_scatter_gather_identity(self):
        def fn(x):
            t = x * (comm.rank + 1)
            full = comm.Allgather(t, 0)
            back = comm.Scatter(full, 0, 2, 0)
            return back - t

        out = np.asarray(run(fn)(jnp.ones((2, 3))))
        assert (out == 0).all()

    def test_scatter_numelem_validation(self):
        def fn(x):
            return comm.Scatter(x, 0, 3, 0)

        with pytest.raises(ValueError, match="numelem"):
            run(fn)(jnp.ones((NR * 2, 2)))

    def test_alltoall_involution_and_grad(self):
        # reference identities (tests/test_collectives.py:137-147)
        def fn(x):
            t = x * (comm.rank + 1)
            y = comm.Alltoall(t, 0, 1, 1)
            z = comm.Alltoall(y, 1, 0, 2)
            return z - t

        out = np.asarray(run(fn)(jnp.ones((2, NR))))
        assert (out == 0).all()

        def fn2(x):
            return comm.Alltoall(x * (comm.rank + 1), 0, 1, 1)

        g = jax.grad(lambda x: run(fn2)(x).sum())(jnp.ones((2, NR)))
        assert (np.asarray(g) == NR * (NR + 1) / 2).all()


class TestP2PSpmd:
    def test_ring_three_orderings(self):
        # reference: tests/test_nonblocking.py:8-35, all three orderings.
        def ring_isendirecv(a0):
            a = a0 * (1.0 + comm.rank)
            req = comm.Isend(a, (comm.rank + 1) % comm.size, 0)
            req2 = comm.Irecv(mpi.JoinDummies(jnp.empty_like(a), [req.dummy]),
                              (comm.rank + comm.size - 1) % comm.size, 0)
            res = comm.Wait(mpi.JoinDummiesHandle(req, [req2.dummy]))
            res2 = comm.Wait(mpi.JoinDummiesHandle(req2, [res]))
            return res2 * comm.rank

        def ring_isendrecv(a0):
            a = a0 * (1.0 + comm.rank)
            req = comm.Isend(a, (comm.rank + 1) % comm.size, 0)
            res = comm.Recv(mpi.JoinDummies(jnp.empty_like(a), [req.dummy]),
                            (comm.rank + comm.size - 1) % comm.size, 0)
            res2 = comm.Wait(mpi.JoinDummiesHandle(req, [res]))
            return mpi.JoinDummies(res, [res2]) * comm.rank

        def ring_irecvsend(a0):
            a = a0 * (1.0 + comm.rank)
            req = comm.Irecv(mpi.JoinDummies(jnp.empty_like(a), [a]),
                             (comm.rank + comm.size - 1) % comm.size, 0)
            res = comm.Send(a, (comm.rank + 1) % comm.size, 0)
            res2 = comm.Wait(mpi.JoinDummiesHandle(req, [res]))
            return res2 * comm.rank

        for prog in (ring_isendirecv, ring_isendrecv, ring_irecvsend):
            out = np.asarray(run(prog)(jnp.ones(2)))
            for r in range(NR):
                left = (r - 1 + NR) % NR
                assert (out[r] == (1.0 + left) * r).all(), prog.__name__
            # gradient: rank r's a reaches rank (r+1)'s output scaled by
            # (r+1)%NR; loss sums all ranks → d/dx sum_r (1+r)*((r+1)%NR)
            g = jax.grad(lambda x: run(prog)(x).sum())(jnp.ones(2))
            expect = sum((1 + r) * ((r + 1) % NR) for r in range(NR))
            assert (np.asarray(g) == expect).all(), prog.__name__

    def test_longer_shift(self):
        def prog(a0):
            a = a0 * (1.0 + comm.rank)
            h = comm.Isend(a, (comm.rank + 3) % comm.size, 7)
            b = comm.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                          (comm.rank - 3) % comm.size, 7)
            comm.Wait(mpi.JoinDummiesHandle(h, [b]))
            return b

        out = np.asarray(run(prog)(jnp.ones(1)))
        for r in range(NR):
            assert out[r, 0] == 1.0 + (r - 3) % NR

    def test_unmatched_send_trace_time_deadlock(self):
        def prog(a):
            comm.Isend(a, (comm.rank + 1) % comm.size, 0)
            return a

        with pytest.raises(mpi.DeadlockError, match="unmatched"):
            run(prog)(jnp.ones(1))

    def test_wait_unmatched_recv_raises(self):
        def prog(a):
            h = comm.Irecv(jnp.empty_like(a), (comm.rank - 1) % comm.size, 0)
            return comm.Wait(h)

        with pytest.raises(mpi.DeadlockError, match="before the matching"):
            run(prog)(jnp.ones(1))

    def test_blocking_send_recv_ring(self):
        # Blocking Send = Isend+Wait: the Wait on a buffered send completes
        # locally even though the matching Recv appears later in the
        # program (fixed: an eager wait must not be a false deadlock).
        def prog(a0):
            a = a0 * (1.0 + comm.rank)
            comm.Send(a, (comm.rank + 1) % comm.size, 0)
            return comm.Recv(jnp.empty_like(a), (comm.rank - 1) % comm.size, 0)

        out = np.asarray(run(prog)(jnp.ones(2)))
        for r in range(NR):
            assert (out[r] == 1.0 + (r - 1) % NR).all()

    def test_unwrapped_destination_rejected(self):
        # `comm.rank + 1` without `% size` is out of range on the last rank;
        # silent ring-wrapping would mask the bug the eager backend reports.
        def prog(a):
            h = comm.Isend(a, comm.rank + 1, 0)
            return comm.Wait(h)

        with pytest.raises(mpi.CommError, match="out of range"):
            run(prog)(jnp.ones(1))

    def test_rankexpr_arith_after_wrap_materializes(self):
        # ((rank+1) % size) + 1 must wrap before the +1: on the last of 8
        # ranks the value is 0+1=1, not 9.
        def prog(x):
            return x * ((((comm.rank + 1) % comm.size) + 1))

        out = np.asarray(run(prog)(jnp.ones(1)))
        assert out.ravel().tolist() == [(r + 1) % NR + 1 for r in range(NR)]


class TestGeneralPermutationsP2P:
    """Arbitrary static bijections on the SPMD p2p path (reference contract:
    any dest/source rank, csrc/extension.cpp:1071-1157).  Ring shifts remain
    the common case; butterfly (rank ^ k), explicit permutation tables, and
    self-sends all lower to at most one collective_permute."""

    def test_butterfly_xor(self):
        # dest = rank ^ 1: pairwise exchange, its own inverse.
        def prog(a0):
            a = a0 * (1.0 + comm.rank)
            h = comm.Isend(a, comm.rank ^ 1, 0)
            b = comm.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                          comm.rank ^ 1, 0)
            comm.Wait(mpi.JoinDummiesHandle(h, [b]))
            return b

        out = np.asarray(run(prog)(jnp.ones(2)))
        for r in range(NR):
            assert (out[r] == 1.0 + (r ^ 1)).all()

    def test_butterfly_gradient_crosschecked_with_eager(self):
        # Gradient must travel the butterfly backwards; the eager runtime
        # (arbitrary concrete destinations) is the oracle.
        def prog(a0):
            a = a0 * (1.0 + comm.rank)
            h = comm.Isend(a, comm.rank ^ 2, 3)
            b = comm.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                          comm.rank ^ 2, 3)
            comm.Wait(mpi.JoinDummiesHandle(h, [b]))
            return b * (1.0 + comm.rank)

        g_spmd = np.asarray(
            jax.grad(lambda x: run(prog)(x).sum())(jnp.ones(2)))

        per_rank = {}

        def body():
            def eager_prog(a0):
                a = a0 * (1.0 + comm.rank)
                h = comm.Isend(a, comm.rank ^ 2, 3)
                b = comm.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                              comm.rank ^ 2, 3)
                comm.Wait(mpi.JoinDummiesHandle(h, [b]))
                return (b * (1.0 + comm.rank)).sum()

            per_rank[comm.rank] = np.asarray(jax.grad(eager_prog)(jnp.ones(2)))

        mpi.run_ranks(body, NR)
        g_eager = sum(per_rank[r] for r in range(NR))
        np.testing.assert_array_equal(g_spmd, g_eager)

    def test_explicit_table_reversal(self):
        # dest table r -> NR-1-r (an involution that is NOT a ring shift).
        table = [NR - 1 - r for r in range(NR)]

        def prog(a0):
            a = a0 * (1.0 + comm.rank)
            h = comm.Isend(a, table, 0)
            b = comm.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                          table, 0)
            comm.Wait(mpi.JoinDummiesHandle(h, [b]))
            return b

        out = np.asarray(run(prog)(jnp.ones(1)))
        for r in range(NR):
            assert out[r, 0] == 1.0 + (NR - 1 - r)

    def test_non_involution_table(self):
        # A 3-cycle embedded in the identity: recv source is the inverse
        # table, exercising _invert_perm on an asymmetric permutation.
        dest = list(range(NR))
        dest[0], dest[1], dest[2] = 1, 2, 0          # 0->1->2->0
        src = [0] * NR
        for r, d in enumerate(dest):
            src[d] = r

        def prog(a0):
            a = a0 * (1.0 + comm.rank)
            h = comm.Isend(a, dest, 0)
            b = comm.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                          src, 0)
            comm.Wait(mpi.JoinDummiesHandle(h, [b]))
            return b

        out = np.asarray(run(prog)(jnp.ones(1)))
        for r in range(NR):
            assert out[r, 0] == 1.0 + src[r]

    def test_self_send(self):
        # MPI permits Isend(dest=rank); a local hand-off, no collective.
        def prog(a0):
            a = a0 * (1.0 + comm.rank)
            h = comm.Isend(a, comm.rank, 0)
            b = comm.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                          comm.rank, 0)
            comm.Wait(mpi.JoinDummiesHandle(h, [b]))
            return b

        out = np.asarray(run(prog)(jnp.ones(2)))
        for r in range(NR):
            assert (out[r] == 1.0 + r).all()

    def test_self_send_ring_shift_zero(self):
        # (comm.rank + 0) % comm.size spells self-send through RankExpr.
        def prog(a0):
            a = a0 * (1.0 + comm.rank)
            h = comm.Isend(a, (comm.rank + comm.size) % comm.size, 0)
            b = comm.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                          (comm.rank + comm.size) % comm.size, 0)
            comm.Wait(mpi.JoinDummiesHandle(h, [b]))
            return b

        out = np.asarray(run(prog)(jnp.ones(2)))
        for r in range(NR):
            assert (out[r] == 1.0 + r).all()

    def test_non_bijection_table_rejected(self):
        bad = [0] * NR

        def prog(a):
            h = comm.Isend(a, bad, 0)
            return comm.Wait(h)

        with pytest.raises(mpi.CommError, match="not a permutation"):
            run(prog)(jnp.ones(1))

    def test_xor_out_of_range_rejected(self):
        def prog(a):
            h = comm.Isend(a, comm.rank ^ (NR + 1), 0)
            return comm.Wait(h)

        with pytest.raises(mpi.CommError, match="leaves"):
            run(prog)(jnp.ones(1))

    def test_ring_and_butterfly_do_not_cross_match(self):
        # Same tag, different permutations: must stay unmatched and raise
        # at region close, not silently pair up.
        def prog(a):
            comm.Isend(a, (comm.rank + 1) % comm.size, 0)
            h = comm.Irecv(jnp.empty_like(a), comm.rank ^ 1, 0)
            return a

        with pytest.raises(mpi.DeadlockError, match="unmatched"):
            run(prog)(jnp.ones(1))


class TestEagerPeerTables:
    def test_table_program_runs_on_both_backends(self):
        # The SPMD backends' portable permutation-table form must run
        # unchanged on the eager backend (each rank takes its entry).
        table = [NR - 1 - r for r in range(NR)]

        def prog(a0):
            a = a0 * (1.0 + comm.rank)
            h = comm.Isend(a, table, 2)
            b = comm.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                          table, 2)
            comm.Wait(mpi.JoinDummiesHandle(h, [b]))
            return b

        spmd = np.asarray(run(prog)(jnp.ones(2)))
        eager = {}

        def body():
            eager[comm.rank] = np.asarray(prog(jnp.ones(2)))

        mpi.run_ranks(body, NR)
        for r in range(NR):
            np.testing.assert_array_equal(eager[r], spmd[r])

    def test_wrong_length_table_rejected_eager(self):
        def body():
            with pytest.raises(mpi.CommError, match="entries"):
                comm.Isend(jnp.ones(1), [0] * (4 + 1), 0)

        mpi.run_ranks(body, 4)


class TestEagerSelfSend:
    def test_self_send_eager(self):
        # MPI semantics: Isend(dest=rank) + Recv(source=rank) completes
        # locally on the eager (mailbox) runtime too.
        def body():
            a = jnp.ones(2) * (1.0 + comm.rank)
            h = comm.Isend(a, comm.rank, 0)
            b = comm.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                          comm.rank, 0)
            comm.Wait(mpi.JoinDummiesHandle(h, [b]))
            assert (np.asarray(b) == 1.0 + comm.rank).all()

        mpi.run_ranks(body, 4)


class TestDeterministicToggle:
    def test_toggle_after_first_call_retraces(self):
        # The flag is part of the jit cache key: flipping it after the
        # first call must change the executed lowering, not silently reuse
        # the cached trace.
        rng = np.random.default_rng(5)
        data = jnp.asarray(rng.standard_normal((NR, 127)).astype(np.float32))

        def fn(x):
            t = jax.lax.dynamic_index_in_dim(x, jnp.asarray(comm.rank + 0),
                                             0, keepdims=False)
            return comm.Allreduce(t, mpi.MPI_SUM)

        f = run(fn)
        _ = f(data)  # traced with deterministic off
        with mpi.config.deterministic_mode(True):
            det = np.asarray(f(data))  # must retrace with the fold
        oracle = np.asarray(data)[0].copy()
        for r in range(1, NR):
            oracle = oracle + np.asarray(data)[r]
        np.testing.assert_array_equal(det[0], oracle)

    def test_double_wait_raises(self):
        def prog(a):
            h = comm.Isend(a, (comm.rank + 1) % comm.size, 0)
            b = comm.Recv(jnp.empty_like(a), (comm.rank - 1) % comm.size, 0)
            comm.Wait(h)
            comm.Wait(h)
            return b

        with pytest.raises(mpi.BifurcationError, match="already waited"):
            run(prog)(jnp.ones(1))

    def test_spliced_handle_raises(self):
        def prog(a):
            h = comm.Isend(a, (comm.rank + 1) % comm.size, 0)
            b = comm.Recv(jnp.empty_like(a), (comm.rank - 1) % comm.size, 0)
            franken = mpi.WaitHandle([h._handle[0], b, b])
            comm.Wait(franken)
            return b

        with pytest.raises(mpi.BifurcationError, match="bifurcation"):
            run(prog)(jnp.ones(1))

    def test_literal_destination_rejected(self):
        def prog(a):
            h = comm.Isend(a, 3, 0)
            return comm.Wait(h)

        with pytest.raises(mpi.CommError, match="static permutation"):
            run(prog)(jnp.ones(1))


class TestCrossBackendEquivalence:
    """The same per-rank program, executed eagerly (thread-SPMD) and traced
    (mesh SPMD), must agree — the moral equivalent of the reference's
    eager-vs-TorchScript parity tests."""

    def test_allreduce_program(self):
        rng = np.random.default_rng(11)
        data = rng.standard_normal((NR, 64))

        def spmd_fn(x):
            t = jax.lax.dynamic_index_in_dim(x, jnp.asarray(comm.rank + 0),
                                             0, keepdims=False)
            y = comm.Allreduce(t, mpi.MPI_SUM)
            return y * (comm.rank + 1)

        spmd_out = np.asarray(run(spmd_fn)(jnp.asarray(data)))

        def eager_body(rank):
            y = comm.Allreduce(jnp.asarray(data[rank]), mpi.MPI_SUM)
            return np.asarray(y * (comm.rank + 1))

        eager_out = mpi.run_ranks(eager_body, NR)
        for r in range(NR):
            np.testing.assert_allclose(spmd_out[r], eager_out[r], rtol=1e-12)

    def test_ring_program(self):
        def spmd_fn(x):
            a = x * (1.0 + comm.rank)
            h = comm.Isend(a, (comm.rank + 1) % comm.size, 0)
            b = comm.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                          (comm.rank - 1) % comm.size, 0)
            w = comm.Wait(mpi.JoinDummiesHandle(h, [b]))
            return mpi.JoinDummies(a + b, [w])

        spmd_out = np.asarray(run(spmd_fn)(jnp.ones(3)))

        def eager_body(rank):
            a = jnp.ones(3) * (1.0 + comm.rank)
            h = comm.Isend(a, (comm.rank + 1) % comm.size, 0)
            b = comm.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                          (comm.rank - 1 + comm.size) % comm.size, 0)
            w = comm.Wait(mpi.JoinDummiesHandle(h, [b]))
            return np.asarray(mpi.JoinDummies(a + b, [w]))

        eager_out = mpi.run_ranks(eager_body, NR)
        for r in range(NR):
            np.testing.assert_array_equal(spmd_out[r], eager_out[r])


class TestCommFromMesh:
    def test_user_managed_shard_map(self):
        # Foreign-mesh adoption (the mpi4py-interop analogue,
        # src/__init__.py:247-261): use the communicator inside a
        # user-managed shard_map over the user's own axis name.
        from jax.sharding import Mesh, PartitionSpec as P
        from mpi4torch_tpu._compat import shard_map

        devs = jax.devices()[:4]
        mesh = Mesh(np.asarray(devs), ("workers",))
        c = mpi.comm_from_mesh(mesh, "workers")
        assert c.size == 4

        def fn(x):
            return c.Allreduce(x, mpi.MPI_SUM)

        out = shard_map(fn, mesh=mesh, in_specs=P("workers"),
                        out_specs=P("workers"), check_vma=False)(
            jnp.arange(8.0))
        # shards [0,1],[2,3],[4,5],[6,7]; psum over shards: [12, 16] each
        assert (np.asarray(out) == np.tile([12.0, 16.0], 4)).all()

    def test_p2p_in_user_managed_shard_map(self):
        # Regression: Isend/Irecv posted through a comm_from_mesh
        # communicator must share one trace-region context so the pair can
        # fuse into a collective_permute (a fresh context per op call would
        # produce a spurious trace-time DeadlockError).
        from jax.sharding import Mesh, PartitionSpec as P
        from mpi4torch_tpu._compat import shard_map

        mesh = Mesh(np.asarray(jax.devices()), ("w",))
        c = mpi.comm_from_mesh(mesh, "w")

        def ring(a):
            h = c.Isend(a, (c.rank + 1) % c.size, 0)
            b = c.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                       (c.rank - 1) % c.size, 0)
            w = c.Wait(mpi.JoinDummiesHandle(h, [b]))
            return mpi.JoinDummies(b, [w])

        out = shard_map(ring, mesh=mesh, in_specs=P("w"), out_specs=P("w"),
                        check_vma=False)(jnp.arange(8.0))
        assert (np.asarray(out) == np.asarray(
            [7., 0., 1., 2., 3., 4., 5., 6.])).all()

    def test_bad_axis_rejected(self):
        from jax.sharding import Mesh
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("w",))
        with pytest.raises(mpi.CommError, match="axis"):
            mpi.comm_from_mesh(mesh, "nope")

    def test_p2p_scope_matches_and_returns_values(self):
        # Inside an explicit scope the ring still fuses and computes.
        from jax.sharding import Mesh, PartitionSpec as P
        from mpi4torch_tpu._compat import shard_map

        mesh = Mesh(np.asarray(jax.devices()), ("w",))
        c = mpi.comm_from_mesh(mesh, "w")

        def ring(a):
            with mpi.p2p_scope(c):
                h = c.Isend(a, (c.rank + 1) % c.size, 0)
                b = c.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                           (c.rank - 1) % c.size, 0)
                w = c.Wait(mpi.JoinDummiesHandle(h, [b]))
            return mpi.JoinDummies(b, [w])

        out = shard_map(ring, mesh=mesh, in_specs=P("w"), out_specs=P("w"),
                        check_vma=False)(jnp.arange(8.0))
        assert (np.asarray(out) == np.asarray(
            [7., 0., 1., 2., 3., 4., 5., 6.])).all()

    def test_p2p_scope_raises_on_unmatched_send(self):
        # A user-managed region has no exit hook, so unmatched p2p there
        # normally only warns from a finalizer; the explicit scope
        # restores run_spmd's hard trace-time DeadlockError.
        from jax.sharding import Mesh, PartitionSpec as P
        from mpi4torch_tpu._compat import shard_map

        mesh = Mesh(np.asarray(jax.devices()), ("w",))
        c = mpi.comm_from_mesh(mesh, "w")

        def lonely_send(a):
            with mpi.p2p_scope(c):
                h = c.Isend(a, (c.rank + 1) % c.size, 0)
            return mpi.JoinDummies(a, [h.dummy])

        with pytest.raises(mpi.DeadlockError, match="unmatched"):
            shard_map(lonely_send, mesh=mesh, in_specs=P("w"),
                      out_specs=P("w"), check_vma=False)(jnp.arange(8.0))

    def test_p2p_scope_rejects_non_mesh_comm(self):
        with pytest.raises(mpi.CommError, match="mesh-derived"):
            with mpi.p2p_scope(mpi.COMM_WORLD):
                pass


class TestAlltoallCrossModeParity:
    """ISSUE 9 satellite: Alltoall was the one facade collective with no
    cross-mode bitwise-parity matrix (the reduction family has one in
    TestDeterministic* above) — and the reshard executor leans on it.
    Mode A (compiled all_to_all) and Mode B (rendezvous gather+scatter)
    must agree BITWISE on general float data, forward and backward, on
    (3,), (8,) and the (2,4)-mesh worlds."""

    @staticmethod
    def _data(n, k=4):
        rng = np.random.default_rng(n)
        return rng.standard_normal((n, n * k)).astype(np.float64)

    @pytest.mark.parametrize("n", [3, 8])
    def test_forward_bitwise(self, n):
        data = self._data(n)

        def spmd_body():
            t = jnp.asarray(data)[jnp.asarray(comm.rank + 0)]
            t = t.reshape(n, -1)
            return comm.Alltoall(t, gatheraxis=1, scatteraxis=0,
                                 numelem=1)

        a = np.asarray(mpi.run_spmd(spmd_body, nranks=n)())

        def eager_body():
            t = jnp.asarray(data)[comm.rank].reshape(n, -1)
            return comm.Alltoall(t, gatheraxis=1, scatteraxis=0,
                                 numelem=1)

        b = mpi.run_ranks(eager_body, n)
        for r in range(n):
            assert np.array_equal(a[r], np.asarray(b[r])), r

    @pytest.mark.parametrize("n", [3, 8])
    def test_backward_bitwise(self, n):
        data = self._data(n)
        w = np.random.default_rng(n + 100).standard_normal(
            (n, n, self._data(n).shape[1] // n))

        def loss(c, t, wr):
            y = c.Alltoall(t, gatheraxis=1, scatteraxis=0, numelem=1)
            return jnp.vdot(y, wr)

        def spmd_body():
            t = jnp.asarray(data)[jnp.asarray(comm.rank + 0)]
            t = t.reshape(n, -1)
            wr = jnp.asarray(w)[jnp.asarray(comm.rank + 0)]
            return jax.grad(lambda v: loss(comm, v, wr))(t)

        a = np.asarray(mpi.run_spmd(spmd_body, nranks=n)())

        def eager_body():
            t = jnp.asarray(data)[comm.rank].reshape(n, -1)
            wr = jnp.asarray(w)[comm.rank]
            return jax.grad(lambda v: loss(comm, v, wr))(t)

        b = mpi.run_ranks(eager_body, n)
        for r in range(n):
            assert np.array_equal(a[r], np.asarray(b[r])), r

    def test_2d_mesh_per_axis_vs_local_oracle(self):
        # The (2,4) world: one Alltoall per mesh axis inside a 2D
        # shard_map, each checked against the local transpose oracle.
        from jax.sharding import Mesh, PartitionSpec as P
        from mpi4torch_tpu._compat import shard_map

        mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                    ("a", "b"))
        rng = np.random.default_rng(7)
        data = rng.standard_normal((2, 4, 4, 6)).astype(np.float64)

        for axis, size in (("a", 2), ("b", 4)):
            c = mpi.comm_from_mesh(mesh, axis)

            def body(x):
                ia = jax.lax.axis_index("a")
                ib = jax.lax.axis_index("b")
                t = jnp.asarray(data)[ia, ib].reshape(size, -1)
                y = c.Alltoall(t, gatheraxis=1, scatteraxis=0,
                               numelem=1)
                return jnp.expand_dims(jnp.expand_dims(y, 0), 0)

            out = jax.jit(shard_map(
                body, mesh=mesh, in_specs=P(),
                out_specs=P("a", "b"), check_vma=False))(
                    jnp.zeros(()))
            out = np.asarray(out)
            for ra in range(2):
                for rb in range(4):
                    me = (ra, rb)
                    group = [(i, rb) for i in range(2)] if axis == "a" \
                        else [(ra, j) for j in range(4)]
                    pos = group.index(me)
                    pieces = [
                        data[g].reshape(size, -1)[pos] for g in group]
                    want = np.concatenate(
                        [p.reshape(1, -1) for p in pieces], axis=1)
                    got = out[ra, rb]
                    assert np.array_equal(got.reshape(1, -1), want), \
                        (axis, ra, rb)


def test_no_private_jax_imports():
    # VERDICT round 1: `jax._src` is version-unstable; the package must
    # stick to public API (jax.core re-exports included).
    import pathlib

    pkg = pathlib.Path(mpi.__file__).parent
    offenders = [
        str(p) for p in pkg.rglob("*.py") if "jax._src" in p.read_text()
    ]
    assert offenders == []

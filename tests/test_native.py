"""Native-layer tests: the fused ordered-reduction kernels and the
descriptor hash must be bit-identical to their pure-Python fallbacks
(native.cc is the analogue of the reference's C++ runtime unit,
csrc/extension.cpp)."""

import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm, constants, run_ranks
from mpi4torch_tpu import _native


def test_native_built():
    # The toolchain is present in CI; the library must build and load —
    # unless the pure-python matrix axis explicitly disabled it
    # (MPI4TORCH_TPU_NO_NATIVE=1), where the correct outcome is
    # "cleanly unavailable", not a build.
    import os

    if os.environ.get("MPI4TORCH_TPU_NO_NATIVE") == "1":
        assert not _native.available(), \
            "native layer must stay disabled under MPI4TORCH_TPU_NO_NATIVE=1"
    else:
        assert _native.available(), "native library failed to build/load"


def test_fnv1a_matches_python_reference():
    def py_fnv(data: bytes) -> int:
        h = 0x811C9DC5
        for ch in data:
            h ^= ch
            h = (h * 0x01000193) & 0xFFFFFFFF
        return h & 0x7FFFFFFF

    for s in [b"", b"a", b"hello world", bytes(range(256)) * 7]:
        assert _native.fnv1a32(s) == py_fnv(s)


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32, np.int64])
@pytest.mark.parametrize("op", [constants.MPI_SUM, constants.MPI_MAX,
                                constants.MPI_MIN, constants.MPI_PROD])
def test_ordered_reduce_bit_equal_to_fold(dtype, op):
    if not _native.available():
        pytest.skip("no native library")
    rng = np.random.default_rng(0)
    if np.issubdtype(dtype, np.floating):
        arrays = [rng.standard_normal(1000).astype(dtype) for _ in range(5)]
    else:
        arrays = [rng.integers(1, 4, 1000).astype(dtype) for _ in range(5)]
    native = _native.ordered_reduce(arrays, op)
    assert native is not None
    fold = arrays[0].copy()
    for a in arrays[1:]:
        fold = np.asarray(constants.combine2(op, jnp.asarray(fold),
                                             jnp.asarray(a)))
    np.testing.assert_array_equal(native, fold.astype(dtype))


@pytest.mark.parametrize("op", [constants.MPI_BAND, constants.MPI_BOR,
                                constants.MPI_BXOR, constants.MPI_LAND,
                                constants.MPI_LOR, constants.MPI_LXOR])
def test_ordered_reduce_bitwise_int(op):
    if not _native.available():
        pytest.skip("no native library")
    rng = np.random.default_rng(1)
    arrays = [rng.integers(0, 2 ** 20, 64).astype(np.int64) for _ in range(4)]
    native = _native.ordered_reduce(arrays, op)
    fold = jnp.asarray(arrays[0])
    for a in arrays[1:]:
        fold = constants.combine2(op, fold, jnp.asarray(a))
    np.testing.assert_array_equal(native, np.asarray(fold))


def test_float_bitwise_rejected():
    if not _native.available():
        pytest.skip("no native library")
    arrays = [np.ones(10, np.float32)] * 2
    assert _native.ordered_reduce(arrays, constants.MPI_BAND) is None


def test_large_allreduce_uses_native_path_and_matches_oracle():
    # End-to-end through the eager runtime: a large float64 Allreduce takes
    # the native fused kernel; the result must equal the rank-order oracle
    # bit for bit.
    n = 100_000
    rng = np.random.default_rng(2)
    data = rng.standard_normal((4, n))

    def body(rank):
        return np.asarray(comm.Allreduce(jnp.asarray(data[rank]),
                                         mpi.MPI_SUM))

    out = run_ranks(body, 4)
    oracle = data[0].copy()
    for r in range(1, 4):
        oracle = oracle + data[r]
    for r in range(4):
        np.testing.assert_array_equal(out[r], oracle)


@pytest.mark.parametrize("op", [constants.MPI_MAX, constants.MPI_MIN])
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_signed_zero_ties_match_jnp_fold(op, dtype):
    # MAX(+0,-0) must be +0 and MIN(+0,-0) must be -0 in every operand
    # order, bit-identical to the jnp.maximum/minimum fold.
    if not _native.available():
        pytest.skip("no native library")
    pz, nz = dtype(0.0), dtype(-0.0)
    for pattern in [(pz, nz), (nz, pz), (nz, nz), (pz, pz)]:
        arrays = [np.full(64, v, dtype) for v in pattern]
        native = _native.ordered_reduce(arrays, op)
        fold = jnp.asarray(arrays[0])
        for a in arrays[1:]:
            fold = constants.combine2(op, fold, jnp.asarray(a))
        fold = np.asarray(fold)
        np.testing.assert_array_equal(
            np.signbit(native), np.signbit(fold),
            err_msg=f"op={op} pattern={pattern}")
        np.testing.assert_array_equal(native, fold)


def test_reduce_ordered_preserves_numpy_dtype_above_native_threshold():
    # Above the native-dispatch threshold, float64/int64 numpy operands must
    # come back in their own dtype (no jnp canonicalization downcast).
    n = constants._NATIVE_REDUCE_MIN_SIZE + 1
    for dtype in (np.float64, np.int64):
        arrays = [np.ones(n, dtype) for _ in range(3)]
        out = constants.reduce_ordered(constants.MPI_SUM, arrays)
        assert np.asarray(out).dtype == np.dtype(dtype)
        np.testing.assert_array_equal(np.asarray(out), np.full(n, 3, dtype))


def test_native_and_fallback_agree_on_dtype_for_all_ops():
    # Native-present and native-absent runs must return identical dtype AND
    # bits for numpy operands regardless of jnp canonicalization settings.
    n = constants._NATIVE_REDUCE_MIN_SIZE + 1
    rng = np.random.default_rng(5)
    arrays64 = [rng.standard_normal(n) for _ in range(3)]
    for op in (constants.MPI_MAX, constants.MPI_MIN, constants.MPI_SUM,
               constants.MPI_PROD):
        via_native = constants.reduce_ordered(op, arrays64)
        fold = arrays64[0]
        for a in arrays64[1:]:
            fold = constants.combine2(op, fold, a)
        assert np.asarray(via_native).dtype == np.float64
        assert np.asarray(fold).dtype == np.float64
        np.testing.assert_array_equal(np.asarray(via_native),
                                      np.asarray(fold))


class TestUnknownOpSentinel:
    """ADVICE r5 regression: unknown/unsupported op codes must come back
    as a not-handled sentinel (Python sees None and uses the jnp fold),
    never as a silent identity fold of rank-0's buffer."""

    def test_wrapper_returns_none_for_unknown_op(self):
        if not _native.available():
            pytest.skip("no native library")
        arrays = [np.ones(16, np.float32) * (i + 1) for i in range(3)]
        assert _native.ordered_reduce(arrays, 999) is None

    def test_raw_entry_point_reports_not_handled(self):
        if not _native.available():
            pytest.skip("no native library")
        import ctypes

        arrays = [np.ones(16, np.float32) * (i + 1) for i in range(3)]
        out = np.empty(16, np.float32)
        ptrs = (ctypes.c_void_p * 3)(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays])
        lib = _native._lib
        # float entry point: bitwise op is integer-only — sentinel, and
        # unknown codes likewise; supported ops report handled.
        assert lib.ordered_reduce_f32(
            ptrs, 3, 16, constants.MPI_BAND,
            out.ctypes.data_as(ctypes.c_void_p)) != 0
        assert lib.ordered_reduce_f32(
            ptrs, 3, 16, 999, out.ctypes.data_as(ctypes.c_void_p)) != 0
        assert lib.ordered_reduce_f32(
            ptrs, 3, 16, constants.MPI_SUM,
            out.ctypes.data_as(ctypes.c_void_p)) == 0
        np.testing.assert_array_equal(out, np.full(16, 6.0, np.float32))

    def test_integer_entry_point_handles_bitwise(self):
        if not _native.available():
            pytest.skip("no native library")
        arrays = [np.full(16, 1 << i, np.int32) for i in range(3)]
        res = _native.ordered_reduce(arrays, constants.MPI_BOR)
        assert res is not None
        np.testing.assert_array_equal(res, np.full(16, 0b111, np.int32))

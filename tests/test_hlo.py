"""HLO-level evidence for the SPMD lowerings (VERDICT round 1, item 7).

Every facade collective is lowered to StableHLO and its collective-op
census asserted — the compile-time counterpart of test_observability.py's
scope assertions.  These tests pin the claims made in ops/spmd.py's
docstrings: one op in the source program produces exactly the stated XLA
collectives, matched p2p pairs fuse into ONE collective_permute, adjoints
add exactly their stated collective, and the Bcast_ size dispatch picks
the documented strategy per payload class.

The matchers ride the shared StableHLO parse (mpi4torch_tpu.analyze):
``census()`` is :meth:`~mpi4torch_tpu.analyze.ParsedProgram.census`,
and the compressed-path assertions read payload dtypes and named-scope
labels off the typed :class:`~mpi4torch_tpu.analyze.CollectiveOp`
records instead of ad-hoc regexes over the text.  Assertion counts and
expected values are unchanged from the regex era.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from mpi4torch_tpu._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import mpi4torch_tpu as mpi
from mpi4torch_tpu import analyze
from mpi4torch_tpu.ops import spmd as spmd_mod

NR = 4

COLLECTIVES = analyze.COLLECTIVE_KINDS


def census(fn, *args):
    """Map collective-op name -> occurrence count in the lowered StableHLO
    of ``fn`` wrapped in a shard_map over a fresh NR-device mesh."""
    mesh = Mesh(np.asarray(jax.devices()[:NR]), ("w",))
    comm = mpi.comm_from_mesh(mesh, "w")

    def body(*a):
        with mpi.p2p_scope(comm):
            return fn(comm, *a)

    wrapped = shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                       check_vma=False)
    txt = jax.jit(wrapped).lower(*args).as_text()
    return analyze.parse_program(txt).census()


def only(**expected):
    out = {c: 0 for c in COLLECTIVES}
    out.update(expected)
    return out


SMALL = jnp.ones((16,))                      # tree-bcast regime
# > config.bcast_tree_max_bytes (f64 under the x64 test harness: 8 B/elem).
BIG = jnp.ones((mpi.config.bcast_tree_max_bytes() // 8 + 1024,))


class TestOrderedRingFoldCensus:
    def test_ring_fold_has_no_size_n_gather(self, monkeypatch):
        # VERDICT r4 item 3 "done" criterion: deterministic mode's large-
        # payload path must not materialize a size×-tensor buffer.  The
        # census shows zero all_gathers — only the scan's ring permute and
        # the tree broadcast's permutes remain.  (Thresholds live in
        # config since ISSUE 3; patch the backing globals.)
        monkeypatch.setattr(mpi.config, "_ordered_fold_gather_max_bytes", 0)
        monkeypatch.setattr(mpi.config, "_ordered_ring_chunk_bytes", 64)
        with mpi.config.deterministic_mode(True):
            got = census(lambda c, x: c.Allreduce(x, mpi.MPI_SUM),
                         jnp.ones((513,), jnp.float32))
        assert got["all_gather"] == 0
        assert got["all_reduce"] == 0
        assert got["collective_permute"] >= 1

    def test_small_payload_keeps_gather_fold(self):
        with mpi.config.deterministic_mode(True):
            got = census(lambda c, x: c.Allreduce(x, mpi.MPI_SUM),
                         jnp.ones((16,), jnp.float32))
        assert got["all_gather"] == 1


class TestForwardCensus:
    def test_allreduce_is_one_all_reduce(self):
        got = census(lambda c, x: c.Allreduce(x, mpi.MPI_SUM), SMALL)
        assert got == only(all_reduce=1)

    def test_reduce_scatter_is_one_native_collective(self):
        # The op's existence case: ONE stablehlo.reduce_scatter — half an
        # allreduce on the wire (the ZeRO gradient-sharding path).
        got = census(lambda c, x: c.Reduce_scatter(x, mpi.MPI_SUM, 0),
                     jnp.ones((NR * 4,)))
        assert got == only(reduce_scatter=1)

    def test_reduce_scatter_fwd_bwd_is_rs_plus_allgather(self):
        # value_and_grad keeps the forward live (plain grad would DCE the
        # psum_scatter: sum's cotangent is primal-independent).
        got = census(
            lambda c, x: jax.value_and_grad(lambda v: jnp.sum(
                c.Reduce_scatter(v, mpi.MPI_SUM, 0)))(x),
            jnp.ones((NR * 4,)))
        assert got == only(reduce_scatter=1, all_gather=1)

    def test_bcast_small_is_log2_permutes(self):
        got = census(lambda c, x: c.Bcast_(x, root=1), SMALL)
        assert got == only(collective_permute=math.ceil(math.log2(NR)))

    def test_bcast_large_is_one_all_reduce(self):
        got = census(lambda c, x: c.Bcast_(x, root=1), BIG)
        assert got == only(all_reduce=1)

    def test_reduce_is_one_all_reduce(self):
        # No reduce-to-one collective exists in StableHLO; masked
        # all-reduce is the documented lowering.
        got = census(lambda c, x: c.Reduce_(x, mpi.MPI_SUM, root=0), SMALL)
        assert got == only(all_reduce=1)

    def test_allgather_is_one_all_gather(self):
        got = census(lambda c, x: c.Allgather(x, gatheraxis=0), SMALL)
        assert got == only(all_gather=1)

    def test_gather_is_one_all_gather(self):
        # Documented cost: non-roots pay the all-gather too (see
        # ops/spmd.py gather docstring).
        got = census(lambda c, x: c.Gather(x, gatheraxis=0, root=0), SMALL)
        assert got == only(all_gather=1)

    def test_scatter_is_one_reduce_scatter(self):
        got = census(
            lambda c, x: c.Scatter(x, scatteraxis=0, numelem=4, root=0),
            jnp.ones((16,)))
        assert got == only(reduce_scatter=1)

    def test_alltoall_is_one_all_to_all(self):
        got = census(
            lambda c, x: c.Alltoall(x, gatheraxis=1, scatteraxis=0,
                                    numelem=1),
            jnp.ones((NR, 2)))
        assert got == only(all_to_all=1)

    def test_matched_p2p_pair_fuses_into_one_collective_permute(self):
        def ring(c, a):
            h = c.Isend(a, (c.rank + 1) % c.size, 0)
            b = c.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                       (c.rank - 1) % c.size, 0)
            w = c.Wait(mpi.JoinDummiesHandle(h, [b]))
            return mpi.JoinDummies(b, [w])

        got = census(ring, SMALL)
        assert got == only(collective_permute=1)

    def test_butterfly_pair_fuses_into_one_collective_permute(self):
        # General static permutations (rank ^ k) compile exactly like
        # ring shifts: one collective_permute per matched pair, also in
        # a user-managed shard_map region (comm_from_mesh + p2p_scope).
        def butterfly(c, a):
            h = c.Isend(a, c.rank ^ 1, 0)
            b = c.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                       c.rank ^ 1, 0)
            w = c.Wait(mpi.JoinDummiesHandle(h, [b]))
            return mpi.JoinDummies(b, [w])

        got = census(butterfly, SMALL)
        assert got == only(collective_permute=1)

    def test_self_send_emits_no_collective(self):
        # Identity permutation = local hand-off; nothing on the wire.
        def selfsend(c, a):
            h = c.Isend(a, c.rank, 0)
            b = c.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                       c.rank, 0)
            w = c.Wait(mpi.JoinDummiesHandle(h, [b]))
            return mpi.JoinDummies(b, [w])

        got = census(selfsend, SMALL)
        assert got == only()


class TestAdjointCensus:
    def test_allreduce_fwd_bwd_is_two_all_reduce(self):
        # The adjoint of psum is a second psum (SURVEY.md §3.3: backward
        # re-enters the network exactly once).
        def f(c, x):
            return jax.grad(
                lambda v: jnp.vdot(c.Allreduce(v, mpi.MPI_SUM), v))(x)

        got = census(f, SMALL)
        assert got == only(all_reduce=2)

    def test_allgather_bwd_is_one_reduce_scatter(self):
        def f(c, x):
            return jax.grad(
                lambda v: jnp.sum(c.Allgather(v, gatheraxis=0) ** 2))(x)

        got = census(f, SMALL)
        assert got == only(all_gather=1, reduce_scatter=1)

    def test_gather_bwd_is_one_reduce_scatter(self):
        def f(c, x):
            return jax.grad(
                lambda v: jnp.sum(c.Gather(v, gatheraxis=0, root=0) ** 2))(x)

        got = census(f, SMALL)
        assert got == only(all_gather=1, reduce_scatter=1)

    def test_scatter_bwd_is_one_all_gather(self):
        def f(c, x):
            return jax.grad(lambda v: jnp.sum(
                c.Scatter(v, scatteraxis=0, numelem=4, root=0) ** 2))(x)

        got = census(f, jnp.ones((16,)))
        assert got == only(reduce_scatter=1, all_gather=1)

    def test_bcast_small_bwd_adds_one_all_reduce(self):
        # Adjoint of Bcast_ is Reduce_(SUM, root) — a masked all-reduce —
        # regardless of which forward strategy the size dispatch chose.
        def f(c, x):
            return jax.grad(
                lambda v: jnp.sum(c.Bcast_(v, root=1) ** 2))(x)

        got = census(f, SMALL)
        assert got == only(
            collective_permute=math.ceil(math.log2(NR)), all_reduce=1)

    def test_alltoall_fwd_bwd_is_two_all_to_all(self):
        # ISSUE 9 satellite: Alltoall was the one facade collective with
        # no adjoint census — the reshard executor leans on it, so pin
        # it: the backward is the axes-swapped all-to-all, exactly one
        # more stablehlo.all_to_all (value_and_grad keeps the forward
        # live, as in the Reduce_scatter census above).
        got = census(
            lambda c, x: jax.value_and_grad(lambda v: jnp.sum(
                c.Alltoall(v, gatheraxis=1, scatteraxis=0,
                           numelem=1) ** 2))(x),
            jnp.ones((NR, 2)))
        assert got == only(all_to_all=2)

    def test_p2p_ring_fwd_bwd_is_two_collective_permutes(self):
        # Gradients ride the reverse ring: one fused permute per
        # direction (csrc/extension.cpp:1159-1218's tag+10 discipline,
        # compiler-scheduled here).
        def ring_loss(c, a):
            h = c.Isend(a, (c.rank + 1) % c.size, 0)
            b = c.Recv(mpi.JoinDummies(jnp.empty_like(a), [h.dummy]),
                       (c.rank - 1) % c.size, 0)
            w = c.Wait(mpi.JoinDummiesHandle(h, [b]))
            return jnp.sum(mpi.JoinDummies(a + b, [w]) ** 2)

        def f(c, a):
            return jax.grad(lambda v: ring_loss(c, v))(a)

        got = census(f, SMALL)
        assert got == only(collective_permute=2)


class TestTreeBcastExecution:
    """The size dispatch must be value-invisible: both strategies produce
    the root's values on every rank, with the same adjoint."""

    @pytest.mark.parametrize("shape", [(16,), (BIG.size,)])
    @pytest.mark.parametrize("root", [0, 2])
    def test_bcast_values_match_both_strategies(self, shape, root):
        def body():
            r = jnp.asarray(mpi.COMM_WORLD.rank)
            x = jnp.full(shape, 1.0) * (r + 1.0)
            return mpi.COMM_WORLD.Bcast_(x, root=root)

        out = np.asarray(mpi.run_spmd(body, nranks=NR)())
        for r in range(NR):
            np.testing.assert_array_equal(out[r], float(root + 1))

    def test_bcast_grads_match_both_strategies(self):
        # grad through Bcast_ is Reduce_(SUM, root): root rank accumulates
        # the cotangents of every rank, non-roots get zero.
        for shape in [(16,), (BIG.size,)]:
            def body():
                def loss(x):
                    return jnp.sum(mpi.COMM_WORLD.Bcast_(x, root=1))

                return jax.grad(loss)(jnp.ones(shape))

            g = np.asarray(mpi.run_spmd(body, nranks=NR)())
            np.testing.assert_array_equal(g[1], float(NR))
            for r in (0, 2, 3):
                np.testing.assert_array_equal(g[r], 0.0)

    def test_uneven_tree_sizes(self):
        # Non-power-of-two world: the last tree round has fewer pairs.
        for nr in (3, 5, 6):
            def body():
                r = jnp.asarray(mpi.COMM_WORLD.rank)
                x = jnp.arange(8.0) + 100.0 * r
                return mpi.COMM_WORLD.Bcast_(x, root=nr - 1)

            out = np.asarray(mpi.run_spmd(body, nranks=nr)())
            for r in range(nr):
                np.testing.assert_array_equal(
                    out[r], np.arange(8.0) + 100.0 * (nr - 1))


class TestStrategyCensus:
    """Wire counts of the composed strategies: the ring-attention loop
    must ship exactly 2*(size-1) hops (K and V per non-final step) — the
    comm/compute overlap reordering must not duplicate or drop any."""

    def test_ring_attention_wire_count(self):
        from mpi4torch_tpu.parallel import ring_attention

        q = jnp.ones((1, 8 * NR, 2, 8))

        def fn(comm, q):
            r = jnp.asarray(comm.rank)
            sl = jax.lax.dynamic_slice_in_dim(q, r * 8, 8, 1)
            return ring_attention(comm, sl, sl, sl, causal=True)

        got = census(fn, q)
        assert got == only(collective_permute=2 * (NR - 1)), got

    def test_ulysses_wire_count(self):
        # Ulysses = one all_to_all per q/k/v into head-sharding plus one
        # back for the output: exactly 4, independent of size.
        from mpi4torch_tpu.parallel import ulysses_attention

        q = jnp.ones((1, 8 * NR, NR, 8))

        def fn(comm, q):
            r = jnp.asarray(comm.rank)
            sl = jax.lax.dynamic_slice_in_dim(q, r * 8, 8, 1)
            return ulysses_attention(comm, sl, sl, sl, causal=True)

        got = census(fn, q)
        assert got == only(all_to_all=4), got


class TestCompressedCensus:
    """The quantized path's compile-time evidence (ISSUE 1 acceptance):
    int8-width transfer ops in the lowered program, no fp32 all_reduce on
    the compressed path, and codec-suffixed named scopes so profiler
    traces distinguish compressed transfers."""

    def _lowered(self, fn, *args, grad=False):
        mesh = Mesh(np.asarray(jax.devices()[:NR]), ("w",))
        comm = mpi.comm_from_mesh(mesh, "w")

        def body(*a):
            out = fn(comm, *a)
            return jnp.sum(out)

        prog = body
        if grad:
            prog = jax.grad(body)
        wrapped = shard_map(prog, mesh=mesh, in_specs=P(), out_specs=P(),
                            check_vma=False)
        from mpi4torch_tpu._compat import lowered_text
        return lowered_text(jax.jit(wrapped).lower(*args), debug_info=True)

    def test_q8_allreduce_ships_int8(self):
        txt = self._lowered(
            lambda c, x: c.Allreduce(x, mpi.MPI_SUM, compression="q8"),
            jnp.ones((512,), jnp.float32))
        parsed = analyze.parse_program(txt)
        # ring hops: collective_permute on int8 tensors
        assert parsed.ops("collective_permute", dtype="i8"), \
            "no int8-width collective_permute in the compressed lowering"
        # final stage: the encoded shards all_gather as int8
        assert parsed.ops("all_gather", dtype="i8"), \
            "no int8-width all_gather in the compressed lowering"
        # nothing rides the wire at full fp32 width
        assert parsed.census()["all_reduce"] == 0

    def test_q8_allreduce_wire_census(self):
        got = census(lambda c, x: c.Allreduce(x, mpi.MPI_SUM,
                                              compression="q8"),
                     jnp.ones((512,), jnp.float32))
        # n-1 ring hops x (int8 payload + scales) permutes, one encoded
        # all_gather pair, and no exact-path collectives.
        assert got["all_reduce"] == 0
        assert got["collective_permute"] == 2 * (NR - 1)
        assert got["all_gather"] == 2
        assert got["reduce_scatter"] == 0

    def test_q8_backward_is_compressed_too(self):
        # AD transparency on the wire: the adjoint must also ship int8 —
        # twice the forward's quantized collectives, no fp32 all_reduce.
        got = census(
            lambda c, x: jax.value_and_grad(lambda v: jnp.sum(
                c.Allreduce(v, mpi.MPI_SUM, compression="q8")))(x),
            jnp.ones((512,), jnp.float32))
        assert got["all_reduce"] == 0
        assert got["collective_permute"] == 2 * 2 * (NR - 1)
        assert got["all_gather"] == 2 * 2

    def test_q8_allgather_ships_int8(self):
        txt = self._lowered(
            lambda c, x: c.Allgather(x, 0, compression="q8"),
            jnp.ones((64,), jnp.float32))
        assert analyze.parse_program(txt).ops("all_gather", dtype="i8")

    def test_named_scope_carries_codec_suffix(self):
        # The codec suffix must sit on the WIRE ops' own scope paths —
        # the analyzer recovers each collective's label from the
        # debug-info loc table, so the assertion is per-op, not a
        # whole-text substring.
        txt = self._lowered(
            lambda c, x: c.Allreduce(x, mpi.MPI_SUM, compression="q8"),
            jnp.ones((64,), jnp.float32))
        parsed = analyze.parse_program(txt)
        assert any(op.label == "mpi4torch.Allreduce.q8"
                   for op in parsed.collectives)
        txt_bwd = self._lowered(
            lambda c, x: c.Allreduce(x, mpi.MPI_SUM, compression="q8"),
            jnp.ones((64,), jnp.float32), grad=True)
        parsed_bwd = analyze.parse_program(txt_bwd)
        assert any("mpi4torch.AllreduceBackward.q8" in op.scope
                   for op in parsed_bwd.collectives)

    def test_exact_path_untouched(self):
        # compression=None keeps the documented exact lowering.
        got = census(lambda c, x: c.Allreduce(x, mpi.MPI_SUM), SMALL)
        assert got == only(all_reduce=1)

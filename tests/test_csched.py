"""Collective-schedule IR + compiler (mpi4torch_tpu.csched, ISSUE 14).

The re-expression matrix: every registered allreduce algorithm's IR
program must lower to BIT-IDENTICAL StableHLO text as the hand-written
schedule it replaces (forward AND transposition-derived backward,
deterministic and not), the one interpreter must equal the eager
rendezvous fold bitwise, the q8 codec must ride per-step program
rewrites with the same pins, the tree Bcast_/Reduce_ pair must be each
other's transposition, the grouped-fold dedupe
(constants.reduce_grouped/reduce_torus → the interpreter's one
level_fold path) must be bitwise-invisible, the census generator must
reconcile EXACTLY with analyze.parse of the actual lowering, and
synthesis must be deterministic, cache-round-trippable, and
census-better than the deterministic ring.  `make ir-smoke` runs the
same matrix as a standalone lane.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import mpi4torch_tpu as mpi
from mpi4torch_tpu import constants as C
from mpi4torch_tpu import csched
from mpi4torch_tpu._compat import shard_map
from mpi4torch_tpu.ops import eager as op_eager
from mpi4torch_tpu.ops import spmd as op_spmd

NR = 8
ALGOS = ("ring", "rhd", "tree", "hier", "bidir", "torus")


@pytest.fixture(autouse=True)
def _isolated_state(tmp_path, monkeypatch):
    monkeypatch.setenv("MPI4TORCH_TPU_TUNE_CACHE",
                       str(tmp_path / "tune_cache.json"))
    from mpi4torch_tpu.csched import synth as S
    mpi.tune.clear()
    S.clear_installed()
    yield
    mpi.tune.clear()
    S.clear_installed()


def _lower_text(fn, n=NR, nelem=64, det=False, dtype=jnp.float32):
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("w",))
    ctx = op_spmd.SpmdContext(axis_name="w", size=n)
    x = jnp.arange(nelem, dtype=dtype)
    wrapped = shard_map(lambda v: fn(ctx, v), mesh=mesh, in_specs=P(),
                        out_specs=P(), check_vma=False)
    with mpi.config.deterministic_mode(det):
        return jax.jit(wrapped).lower(x).as_text()


# The hand-written forms — the bit-identity references the IR lowering
# is pinned against (they double as the registered emitter bodies).
LEGACY_FWD = {
    "ring": lambda c, v, op, det:
        op_spmd._ordered_fold_allreduce(c, v, op) if det
        else jax.lax.psum(v, c.axis_name),
    "rhd": lambda c, v, op, det: op_spmd._rhd_allreduce_value(c, v, op),
    "tree": lambda c, v, op, det:
        op_spmd._tree_allreduce_value(c, v, op),
    "hier": lambda c, v, op, det:
        op_spmd._hier_allreduce_value(c, v, op),
    "bidir": lambda c, v, op, det:
        op_spmd._bidir_allreduce_value(c, v, op),
    "torus": lambda c, v, op, det:
        op_spmd._torus_allreduce_value(c, v, op),
}


class TestReexpressionMatrix:
    """Lowered-text equality, forward and backward, per algorithm."""

    @pytest.mark.parametrize("det", [False, True])
    @pytest.mark.parametrize("algo", ALGOS)
    def test_forward_text_identical(self, algo, det):
        t_legacy = _lower_text(
            lambda c, v: LEGACY_FWD[algo](c, v, C.MPI_SUM, det), det=det)
        t_ir = _lower_text(
            lambda c, v: op_spmd._allreduce_fwd_value(c, v, C.MPI_SUM,
                                                      algo), det=det)
        assert t_legacy == t_ir

    @pytest.mark.parametrize("algo", ALGOS)
    def test_backward_text_is_transposed_program(self, algo):
        # The hand-written backward: bidir swaps channel directions,
        # everything else re-runs the forward.
        def legacy_bwd(c, v):
            if algo == "bidir":
                return op_spmd._bidir_allreduce_value(c, v, C.MPI_SUM,
                                                      reverse=True)
            return LEGACY_FWD[algo](c, v, C.MPI_SUM, False)

        t_legacy = _lower_text(legacy_bwd)
        t_ir = _lower_text(
            lambda c, v: op_spmd._allreduce_bwd_value(c, v, algo))
        assert t_legacy == t_ir

    def test_non_sum_ops_route_identically(self):
        for op, det in ((C.MPI_MAX, False), (C.MPI_PROD, False)):
            t_legacy = _lower_text(
                lambda c, v: jax.lax.pmax(v, c.axis_name)
                if op == C.MPI_MAX
                else op_spmd._ordered_fold_allreduce(c, v, op), det=det)
            t_ir = _lower_text(
                lambda c, v: op_spmd._allreduce_fwd_value(c, v, op,
                                                          "ring"),
                det=det)
            assert t_legacy == t_ir

    def test_rhd_raises_same_message_off_power_of_two(self):
        with pytest.raises(mpi.CommError, match="power-of-two"):
            csched.allreduce_program("rhd", 6, C.MPI_SUM,
                                     deterministic=False, nelems=8,
                                     itemsize=4)

    def test_minloc_raises_through_builder(self):
        with pytest.raises(NotImplementedError, match="MPI_MINLOC"):
            csched.allreduce_program("bidir", 8, C.MPI_MINLOC,
                                     deterministic=False, nelems=8,
                                     itemsize=4)


class TestBcastReducePrograms:
    def test_tree_bcast_text_identical(self):
        t_legacy = _lower_text(
            lambda c, v: op_spmd._tree_bcast_value(c, v, 1))
        t_ir = _lower_text(lambda c, v: csched.lower_value(
            csched.bcast_program("tree", NR, 1, nbytes=64 * 4), c, v))
        assert t_legacy == t_ir

    def test_tree_reduce_is_transposed_bcast(self):
        """The acceptance pin: the tree Reduce_ form IS the transposed
        tree Bcast_ program, at the lowered-text level."""
        t_reduce = _lower_text(
            lambda c, v: op_spmd._tree_reduce_value(c, v, C.MPI_SUM, 1))
        t_transposed = _lower_text(lambda c, v: csched.lower_value(
            csched.transpose(csched.bcast_program(
                "tree", NR, 1, nbytes=64 * 4)), c, v))
        assert t_reduce == t_transposed

    def test_ring_bcast_reduce_transpose_pair(self):
        bcast = csched.bcast_program("ring", NR, 0, nbytes=1 << 20)
        red = csched.transpose(bcast)
        kinds = [s.kind for s in red.steps()]
        assert kinds == ["native_allreduce", "mask_root"]
        assert csched.transpose(red).steps() == bcast.steps()

    def test_facade_bcast_reduce_text_unchanged(self):
        """The facade _bcast_value/_reduce_value (now IR-routed) keep
        the historical lowerings: size dispatch, masked psum, masks."""
        t_small = _lower_text(
            lambda c, v: op_spmd._bcast_value(c, v, 1))
        t_tree = _lower_text(
            lambda c, v: op_spmd._tree_bcast_value(c, v, 1))
        assert t_small == t_tree          # 256 B <= tree threshold
        t_red = _lower_text(
            lambda c, v: op_spmd._reduce_value(c, v, C.MPI_SUM, 1))
        t_manual = _lower_text(lambda c, v: op_spmd._mask_to_root(
            c, jax.lax.psum(v, c.axis_name), 1))
        assert t_red == t_manual


class TestInterpreter:
    @pytest.mark.parametrize("n", [3, 8])
    @pytest.mark.parametrize("algo", ALGOS)
    def test_interpreter_matches_rendezvous_fold(self, algo, n):
        if algo == "rhd" and n & (n - 1):
            pytest.skip("rhd needs a power-of-two world")
        if algo in ("hier", "torus") and n == 3:
            # No 2-level factorization: both the builder and the
            # rendezvous fold degrade/raise identically — nothing to
            # compare (the degrade rule is pinned in test_tune).
            pytest.skip("hier/torus need a factorable world")
        rng = np.random.default_rng(3)
        vals = [jnp.asarray(rng.standard_normal(41), jnp.float32)
                for _ in range(n)]
        prog = csched.allreduce_program(algo, n, C.MPI_SUM,
                                        deterministic=True, nelems=41,
                                        itemsize=4)
        _, fold = op_eager._rendezvous_fold(n, algo)
        got = csched.interpret_allreduce(prog, C.MPI_SUM, vals)
        assert jnp.all(got == fold(C.MPI_SUM, vals))

    def test_interpreter_matches_mode_a_deterministic(self):
        rng = np.random.default_rng(4)
        stack = jnp.asarray(rng.standard_normal((NR, 33)), jnp.float32)

        def body():
            idx = jax.lax.axis_index("mpi")
            return mpi.COMM_WORLD.Allreduce(stack[idx], mpi.MPI_SUM,
                                            algorithm="hier")

        with mpi.config.deterministic_mode(True):
            outs = mpi.run_spmd(body, nranks=NR)()
        prog = csched.allreduce_program("hier", NR, C.MPI_SUM,
                                        deterministic=True, nelems=33,
                                        itemsize=4)
        oracle = csched.interpret_allreduce(prog, C.MPI_SUM,
                                            list(stack))
        assert jnp.all(outs == oracle[None])


class TestGroupedFoldDedupe:
    """The triplicated grouped-fold bodies collapse onto the
    interpreter's one level_fold path — bitwise pinned against verbatim
    copies of the pre-dedupe implementations on (3,), (8,) and the
    (2,4) grid."""

    @staticmethod
    def _legacy_grouped(op, values, group):
        vals = list(values)
        partials = [C.reduce_ordered(op, vals[b:b + group])
                    for b in range(0, len(vals), group)]
        return C.reduce_ordered(op, partials)

    @classmethod
    def _legacy_torus(cls, op, values, inner):
        vals = list(values)
        n = len(vals)
        outer = n // inner
        shape = vals[0].shape
        flats = [v.reshape(-1) for v in vals]
        total = flats[0].size
        m = C.multipath_split(total)
        h0 = cls._legacy_grouped(op, [f[:m] for f in flats], inner)
        if m >= total:
            return h0.reshape(shape)
        perm = [o * inner + i for i in range(inner)
                for o in range(outer)]
        h1 = cls._legacy_grouped(op, [flats[p][m:] for p in perm],
                                 outer)
        xp = np if isinstance(h0, np.ndarray) else jnp
        return xp.concatenate([h0, h1]).reshape(shape)

    @pytest.mark.parametrize("n,group", [(3, 3), (8, 2), (8, 4)])
    def test_reduce_grouped_bitwise(self, n, group):
        rng = np.random.default_rng(n * 10 + group)
        vals = [jnp.asarray(rng.standard_normal(29), jnp.float32)
                for _ in range(n)]
        got = C.reduce_grouped(C.MPI_SUM, vals, group)
        assert jnp.all(got == self._legacy_grouped(C.MPI_SUM, vals,
                                                   group))

    @pytest.mark.parametrize("n,inner", [(3, 3), (8, 2), (8, 4)])
    def test_reduce_torus_bitwise(self, n, inner):
        # (8, 4) is the (2,4) grid of the two-axis communicator tests.
        rng = np.random.default_rng(n * 100 + inner)
        vals = [jnp.asarray(rng.standard_normal(37), jnp.float32)
                for _ in range(n)]
        got = C.reduce_torus(C.MPI_SUM, vals, inner)
        assert jnp.all(got == self._legacy_torus(C.MPI_SUM, vals,
                                                 inner))

    def test_numpy_dtype_preserved(self):
        vals = [np.arange(11, dtype=np.float64) * (r + 1)
                for r in range(8)]
        got = C.reduce_grouped(C.MPI_PROD, vals, 4)
        assert isinstance(got, np.ndarray) and got.dtype == np.float64
        assert np.all(got == self._legacy_grouped(C.MPI_PROD, vals, 4))
        got_t = C.reduce_torus(C.MPI_SUM, vals, 2)
        assert isinstance(got_t, np.ndarray)
        assert np.all(got_t == self._legacy_torus(C.MPI_SUM, vals, 2))


class TestTransposition:
    @pytest.mark.parametrize("algo", ALGOS)
    def test_vjp_census_agreement(self, algo):
        """Transposition-derived symmetry == the registry's declared
        AlgorithmSpec.vjp_census, for all six."""
        assert csched.declared_vjp_census(algo, NR) \
            == mpi.tune.get_algorithm(algo).vjp_census

    def test_bidir_transpose_flips_directions(self):
        prog = csched.allreduce_program("bidir", NR, C.MPI_SUM,
                                        deterministic=False,
                                        nelems=64, itemsize=4)
        back = csched.transpose(prog)
        assert [s.params[0] for s in prog.steps()] == [1, -1]
        assert [s.params[0] for s in back.steps()] == [-1, 1]
        assert csched.transpose(back) == prog

    def test_every_step_kind_has_dispatch_coverage(self):
        kinds = set(csched.STEP_KINDS)
        assert set(csched.lowering_covers()) == kinds
        assert set(csched.interpreter_covers()) == kinds
        assert set(csched.transposition_covers()) == kinds
        assert set(csched.census_covers()) == kinds

    def test_registry_guard_clean(self):
        from mpi4torch_tpu.analyze.registry import csched_problems
        assert csched_problems() == []


class TestCodecRewrite:
    @pytest.mark.parametrize("algo", ["ring", "bidir", "torus"])
    def test_q8_text_identical(self, algo):
        from mpi4torch_tpu.compress import get_codec
        from mpi4torch_tpu.compress import spmd as cspmd

        codec = get_codec("q8")
        t_legacy = _lower_text(
            lambda c, v: cspmd._fused_allreduce_value(c, v, codec, algo,
                                                      False), nelem=512)
        t_ir = _lower_text(
            lambda c, v: cspmd._allreduce_value(c, v, codec, algo),
            nelem=512)
        assert t_legacy == t_ir

    def test_q8_steps_carry_codec_annotation(self):
        prog = csched.q8_allreduce_program("bidir", NR, "q8_ef_hop",
                                           256)
        assert prog.codec == "q8_ef_hop"
        assert all(s.kind == "q8_ring_channel"
                   and s.codec == "q8_ef_hop" for s in prog.steps())
        # reverse = the transposed program (bidir directions flip)
        rev = csched.q8_allreduce_program("bidir", NR, "q8_ef_hop", 256,
                                          reverse=True)
        assert rev == csched.transpose(prog)

    def test_q8_interpreter_matches_hop_oracle(self):
        from mpi4torch_tpu.compress import get_codec

        codec = get_codec("q8_ef_hop")
        base = codec.base()
        rng = np.random.default_rng(9)
        vals = [jnp.asarray(rng.standard_normal(300), jnp.float32)
                for _ in range(NR)]
        prog = csched.q8_allreduce_program("bidir", NR, "q8_ef_hop",
                                           base.block)
        got = csched.interpret_allreduce(prog, C.MPI_SUM, vals)
        ref = C.reduce_q8_hop(
            vals, block=base.block, algorithm="bidir",
            stochastic=base.stochastic, hop_ef=base.hop_ef,
            ef_rounds=codec.ef_rounds)
        assert jnp.all(got == ref)


class TestCensus:
    @pytest.mark.parametrize("algo", ALGOS)
    def test_hlo_census_reconciles_with_parse(self, algo):
        """The program census's predicted per-kind collective counts
        equal analyze.parse_program of the actual lowering EXACTLY —
        no per-algorithm census tables anywhere in the chain."""
        from mpi4torch_tpu.analyze import parse_program

        prog = csched.allreduce_program(algo, NR, C.MPI_SUM,
                                        deterministic=False, nelems=64,
                                        itemsize=4)
        txt = _lower_text(
            lambda c, v: op_spmd._allreduce_fwd_value(c, v, C.MPI_SUM,
                                                      algo))
        got = parse_program(txt).census()
        pred = csched.program_census(prog, 64, 4)["hlo"]
        for kind, count in pred.items():
            assert got.get(kind, 0) == count, (algo, kind, got, pred)

    def test_det_ring_census_reconciles(self):
        from mpi4torch_tpu.analyze import parse_program

        prog = csched.allreduce_program("ring", NR, C.MPI_SUM,
                                        deterministic=True, nelems=64,
                                        itemsize=4)
        txt = _lower_text(
            lambda c, v: op_spmd._allreduce_fwd_value(c, v, C.MPI_SUM,
                                                      "ring"), det=True)
        got = parse_program(txt).census()
        pred = csched.program_census(prog, 64, 4)["hlo"]
        for kind, count in pred.items():
            assert got.get(kind, 0) == count

    def test_wire_accounting_matches_registry_formulas(self):
        s = 1 << 14
        ring = csched.program_census(csched.allreduce_program(
            "ring", NR, C.MPI_SUM, deterministic=False,
            nelems=s // 4, itemsize=4), s // 4, 4)
        assert ring["wire_bytes_per_rank"] == int(2 * s * 7 / 8)
        det = csched.program_census(csched.allreduce_program(
            "ring", NR, C.MPI_SUM, deterministic=True,
            nelems=s // 4, itemsize=4), s // 4, 4)
        assert det["wire_bytes_per_rank"] == 7 * s  # gather fold


class TestSerialization:
    @pytest.mark.parametrize("algo", ALGOS)
    def test_json_round_trip(self, algo):
        prog = csched.allreduce_program(algo, NR, C.MPI_SUM,
                                        deterministic=True, nelems=64,
                                        itemsize=4)
        blob = json.dumps(prog.to_json())
        back = csched.Program.from_json(json.loads(blob))
        assert back == prog
        assert back.digest() == prog.digest()


class TestSynthesis:
    def test_deterministic_and_beats_ring(self):
        a = csched.synthesize(NR, 1 << 14, 4)
        b = csched.synthesize(NR, 1 << 14, 4)
        assert a["winner"] == b["winner"]
        assert a["chain"] == [2, 2, 2]
        assert a["synthesis_beats_ring"]
        assert a["census"]["wire_bytes_per_rank"] \
            < a["ring_census"]["wire_bytes_per_rank"]

    def test_cache_round_trip_and_version_bump(self):
        from mpi4torch_tpu.csched import synth as S
        from mpi4torch_tpu.tune import autotuner as A

        rep = csched.autotune_synthesis(nranks=NR, sizes=(1 << 14,))
        ent = rep["entries"][str(1 << 14)]
        assert ent["recorded"] and ent["winner"].startswith("synth:")
        name = ent["winner"]
        # Cross-"process" round trip: drop in-memory state, re-read the
        # persisted file — the entry revalidates and reinstalls.
        # Synthesis entries live under their own codec="synth" slot so
        # they never collide with wall-clock-measured winners.
        S.clear_installed()
        mpi.tune.clear()
        got = mpi.tune.lookup_algorithm("allreduce", jnp.float32,
                                        1 << 14, NR, codec="synth")
        assert got == name and S.synth_applicable(name, NR)
        assert mpi.tune.lookup_algorithm("allreduce", jnp.float32,
                                         1 << 14, NR) is None
        # A CACHE_VERSION bump discards the entry safely (defaults
        # apply, nothing crashes) — the versioned-cache contract.
        S.clear_installed()
        mpi.tune.clear()
        old = A.CACHE_VERSION
        A.CACHE_VERSION = old + 1
        try:
            assert mpi.tune.lookup_algorithm(
                "allreduce", jnp.float32, 1 << 14, NR,
                codec="synth") is None
        finally:
            A.CACHE_VERSION = old

    def test_select_auto_serves_synth_in_det_mode_only(self):
        csched.autotune_synthesis(nranks=NR, sizes=(1 << 14,))
        det = mpi.tune.select_auto(collective="allreduce",
                                   nbytes=1 << 14, dtype=jnp.float32,
                                   nranks=NR, deterministic=True)
        assert det.startswith("synth:")
        nondet = mpi.tune.select_auto(collective="allreduce",
                                      nbytes=1 << 14,
                                      dtype=jnp.float32, nranks=NR,
                                      deterministic=False)
        assert nondet == "ring"

    def test_mode_a_b_bitwise_for_synth_winner(self):
        res = csched.synthesize(NR, 1 << 12, 4)
        name = csched.install(res["program"])
        rng = np.random.default_rng(11)
        stack = jnp.asarray(rng.standard_normal((NR, 50)), jnp.float32)
        oracle = csched.interpret_allreduce(res["program"], C.MPI_SUM,
                                            list(stack))

        def body():
            idx = jax.lax.axis_index("mpi")
            return mpi.COMM_WORLD.Allreduce(stack[idx], mpi.MPI_SUM,
                                            algorithm=name)

        outs = mpi.run_spmd(body, nranks=NR)()
        assert jnp.all(outs == oracle[None])
        eager = mpi.run_ranks(
            lambda rank: mpi.COMM_WORLD.Allreduce(
                stack[rank], mpi.MPI_SUM, algorithm=name), nranks=NR)
        assert all(jnp.all(r == oracle) for r in eager)

    def test_tune_show_renders_synth_distinctly(self):
        from mpi4torch_tpu.tune.__main__ import _rows

        csched.autotune_synthesis(nranks=NR, sizes=(1 << 14,))
        data = json.load(open(mpi.tune.cache_path()))
        rows = _rows(data)
        synth_rows = [r for r in rows if r[6].startswith("synth:")]
        assert synth_rows
        assert synth_rows[0][7] == "synthesized(3 steps)"

    def test_synth_degrades_when_not_installed(self):
        # Scope default naming an uninstalled synth program degrades to
        # auto; an explicit request raises — the standard rule.
        assert mpi.tune.resolve_request("synth:0000000000",
                                        nranks=NR) is None
        with pytest.raises(mpi.CommError, match="not installed"):
            mpi.tune.resolve_request("synth:0000000000", nranks=NR,
                                     explicit=True)

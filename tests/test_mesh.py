"""Mesh helpers (mesh.py): axis ordering, tier assignment, and that the
result plugs straight into comm_from_mesh/run_spmd collectives."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi


class TestDeviceMesh:
    def test_axes_order_and_sizes(self):
        mesh = mpi.device_mesh({"dp": 2, "tp": 4})
        assert mesh.axis_names == ("dp", "tp")
        assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4
        # Last axis varies fastest over the device order.
        devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
        assert (mesh.devices == devs).all()

    def test_size_mismatch_raises(self):
        with pytest.raises(ValueError, match="multiply to"):
            mpi.device_mesh({"dp": 3, "tp": 2})

    def test_collectives_over_helper_mesh(self):
        mesh = mpi.device_mesh({"dp": 2, "tp": 4})
        comm_tp = mpi.comm_from_mesh(mesh, "tp")
        from mpi4torch_tpu._compat import shard_map
        from jax.sharding import PartitionSpec as P

        def body():
            return comm_tp.Allreduce(jnp.ones(()), mpi.MPI_SUM)[None]

        out = jax.jit(shard_map(body, mesh=mesh, in_specs=(),
                                out_specs=P(("dp", "tp")),
                                check_vma=False))()
        np.testing.assert_array_equal(np.asarray(out), 4.0)


class TestHybridMesh:
    def test_single_granule_degrades_to_device_mesh(self):
        # CPU harness: every device reports process 0 -> one granule,
        # dcn axes must be 1 and the result is an ordinary mesh.
        mesh = mpi.hybrid_mesh({"tp": 8}, {"dp": 1})
        assert mesh.axis_names == ("dp", "tp")
        assert mesh.shape["dp"] == 1 and mesh.shape["tp"] == 8

    def test_single_granule_rejects_wide_dcn(self):
        with pytest.raises(ValueError, match="one granule"):
            mpi.hybrid_mesh({"tp": 4}, {"dp": 2})  # 4x2 = 8 devices

    def test_axis_name_collision_raises(self):
        with pytest.raises(ValueError, match="disjoint"):
            mpi.hybrid_mesh({"dp": 8}, {"dp": 1})

    def test_size_mismatch_raises(self):
        with pytest.raises(ValueError, match="multiply to"):
            mpi.hybrid_mesh({"tp": 3}, {"dp": 1})

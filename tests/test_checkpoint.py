"""Checkpoint/resume (utils/checkpoint.py, orbax-backed).

The reference has no training-state persistence (SURVEY.md §5) — these
tests pin the TPU-native framework's addition: pytree roundtrips
(including sharded jax.Array leaves restoring to their mesh placement),
the resume loop reproducing an uninterrupted run bit-for-bit, retention,
and atomicity of the latest-step discovery."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("orbax.checkpoint",
                    reason="checkpoint subsystem needs orbax "
                           "(pip install mpi4torch_tpu[checkpoint])")

from mpi4torch_tpu.utils import (CheckpointManager, restore_checkpoint,
                                 save_checkpoint)  # noqa: E402


def make_state(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "params": {
            "w": jnp.asarray(rng.standard_normal((4, 4))),
            "b": jnp.zeros((4,), jnp.float32),
        },
        "opt": {"m": jnp.ones((4, 4)), "count": jnp.asarray(3, jnp.int32)},
        "step": jnp.asarray(7, jnp.int32),
    }


def assert_tree_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


class TestRoundtrip:
    def test_save_restore_roundtrip(self, tmp_path):
        state = make_state()
        save_checkpoint(str(tmp_path / "ck"), state)
        got = restore_checkpoint(str(tmp_path / "ck"),
                                 jax.tree.map(jnp.zeros_like, state))
        assert_tree_equal(got, state)

    def test_restore_missing_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            restore_checkpoint(str(tmp_path / "nope"), make_state())

    def test_dtypes_preserved(self, tmp_path):
        state = {"f64": jnp.asarray([1.5], jnp.float64),
                 "i32": jnp.asarray([2], jnp.int32),
                 "bf16": jnp.asarray([0.5], jnp.bfloat16)}
        save_checkpoint(str(tmp_path / "ck"), state)
        got = restore_checkpoint(str(tmp_path / "ck"),
                                 jax.tree.map(jnp.zeros_like, state))
        for k in state:
            assert got[k].dtype == state[k].dtype, k
        assert_tree_equal(got, state)

    def test_sharded_leaves_restore_to_mesh(self, tmp_path):
        # A mesh-sharded array round-trips onto its sharding (no host
        # gather): the template's placement decides the restore layout.
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.asarray(jax.devices()[:4]), ("x",))
        sharding = NamedSharding(mesh, P("x"))
        x = jax.device_put(jnp.arange(16.0).reshape(4, 4), sharding)
        save_checkpoint(str(tmp_path / "ck"), {"x": x})
        template = {"x": jax.device_put(jnp.zeros((4, 4)), sharding)}
        got = restore_checkpoint(str(tmp_path / "ck"), template)
        assert got["x"].sharding == sharding
        np.testing.assert_array_equal(np.asarray(got["x"]), np.asarray(x))


class TestManagerResume:
    def test_resume_matches_uninterrupted(self, tmp_path):
        # An interrupted-then-resumed run must be bit-identical to an
        # uninterrupted one — the whole point of resume.
        def train_step(state):
            g = state["params"] * 0.1 + 1.0
            return {"params": state["params"] - 0.01 * g,
                    "step": state["step"] + 1}

        init = {"params": jnp.ones((3,)), "step": jnp.asarray(0, jnp.int32)}

        ref = init
        for _ in range(6):
            ref = train_step(ref)

        workdir = str(tmp_path / "run")
        # Phase 1: 3 steps, checkpointing each, then "crash".
        with CheckpointManager(workdir) as mgr:
            state = init
            for step in range(3):
                state = train_step(state)
                mgr.save(step, state)
            mgr.wait_until_finished()
        # Phase 2: fresh process-equivalent — discover latest and resume.
        with CheckpointManager(workdir) as mgr:
            latest = mgr.latest_step()
            assert latest == 2
            state = mgr.restore(latest, template=init)
            for step in range(latest + 1, 6):
                state = train_step(state)
                mgr.save(step, state)
            mgr.wait_until_finished()
        assert_tree_equal(state, ref)

    def test_retention_keeps_last_n(self, tmp_path):
        with CheckpointManager(str(tmp_path / "r"), max_to_keep=2) as mgr:
            s = {"x": jnp.zeros(())}
            for step in range(5):
                mgr.save(step, s, force=True)
            mgr.wait_until_finished()
            assert mgr.latest_step() == 4
            assert len(mgr.all_steps()) == 2

    def test_save_interval_skips_off_steps(self, tmp_path):
        with CheckpointManager(str(tmp_path / "i"),
                               save_interval_steps=2) as mgr:
            s = {"x": jnp.zeros(())}
            saved = [mgr.save(step, s) for step in range(4)]
            mgr.wait_until_finished()
        assert saved == [True, False, True, False]


class TestTopologyMigration:
    """ISSUE 9: checkpoint topology migration (mpi4torch_tpu.reshard).

    Train on (8,), serve on (2,4)/(4,2): the smoke transformer's state
    is saved once (the portable global on-disk form), each rank of the
    new world restores its OLD-layout shard and the device-side
    transition is a planned ``comm.Reshard`` — bitwise equal to the
    gather-then-slice oracle.  Plus the regression for the opaque-orbax
    failure: restoring onto mismatched leaf shapes now raises a typed
    ``CommError`` naming both layouts and pointing at the recipe."""

    N = 8

    @staticmethod
    def _params():
        import jax.numpy as jnp

        from mpi4torch_tpu.models import transformer as T

        cfg = T.TransformerConfig(vocab=31, d_model=16, n_heads=8,
                                  n_layers=2, d_ff=32, max_seq=16)
        return T.init_transformer(jax.random.PRNGKey(0), cfg,
                                  dtype=jnp.float64)

    @classmethod
    def _layouts(cls, tree, mesh):
        """Per-leaf layouts: 2D mesh splits the first/last axes where
        divisible, a 1D mesh shards the last divisible axis, everything
        else (odd vocab rows, scalars) replicates."""
        from mpi4torch_tpu import reshard as rs

        n = int(np.prod(mesh))

        def pick(x):
            shape = np.shape(x)
            if not shape or int(np.prod(shape)) == 1:
                return rs.Layout(mesh, ((),) * len(shape))
            if (len(mesh) == 2 and len(shape) >= 2
                    and shape[0] % mesh[0] == 0
                    and shape[-1] % mesh[1] == 0):
                spec = [()] * len(shape)
                spec[0], spec[-1] = (0,), (1,)
                return rs.Layout(mesh, tuple(spec))
            for a in reversed(range(len(shape))):
                if shape[a] % n == 0:
                    spec = [()] * len(shape)
                    spec[a] = tuple(range(len(mesh)))
                    return rs.Layout(mesh, tuple(spec))
            return rs.Layout(mesh, ((),) * len(shape))

        return jax.tree.map(pick, tree)

    def test_mismatched_restore_raises_typed_error(self, tmp_path):
        # Regression: this used to surface as an opaque orbax shape
        # error deep in the restore; now it is a CommError naming the
        # saved vs requested shapes and the migration recipe.
        from mpi4torch_tpu import reshard as rs
        from mpi4torch_tpu.runtime import CommError

        params = self._params()
        path = str(tmp_path / "ck")
        save_checkpoint(path, params)
        wrong = rs.shard_template(params, self._layouts(params, (8,)))
        with pytest.raises(CommError,
                           match="restore_resharded") as ei:
            restore_checkpoint(path, wrong)
        assert "saved" in str(ei.value) and "requested" in str(ei.value)

    def test_mismatch_caught_across_leaf_ranks(self, tmp_path):
        # A ZeRO flat-shard template of a 2D saved leaf differs in RANK,
        # not just extent — the guard must still fire (shape tuples are
        # themselves pytree containers; naive tree flattening would see
        # different treedefs and silently skip the comparison).
        import jax.numpy as jnp

        from mpi4torch_tpu.runtime import CommError

        path = str(tmp_path / "ck")
        save_checkpoint(path, {"w": jnp.ones((8, 4))})
        with pytest.raises(CommError, match="restore_resharded"):
            restore_checkpoint(path, {"w": jnp.ones((32,))})

    def test_manager_resume_mismatch_raises_not_walks_back(self,
                                                           tmp_path):
        # Regression for the resume path: CheckpointManager.restore used
        # to bypass the layout guard, so restore_or_init misread a
        # mesh-mismatched resume as a torn step, walked back through the
        # WHOLE history, and silently restarted from init.  Now the
        # typed CommError propagates from the newest step.
        import jax.numpy as jnp

        from mpi4torch_tpu import reshard as rs
        from mpi4torch_tpu.resilience import restore_or_init
        from mpi4torch_tpu.runtime import CommError

        workdir = str(tmp_path / "run")
        state = {"w": jnp.arange(32, dtype=jnp.float64).reshape(8, 4)}
        with CheckpointManager(workdir) as mgr:
            for step in range(2):
                mgr.save(step, state, force=True)
            mgr.wait_until_finished()
        wrong = rs.shard_template(
            state, {"w": rs.layout((8,), 0, None)})
        with CheckpointManager(workdir) as mgr:
            with pytest.raises(CommError, match="restore_resharded"):
                mgr.restore(1, template=wrong)
        with pytest.raises(CommError, match="restore_resharded"):
            restore_or_init(workdir, template=wrong)
        # the matched template still resumes normally
        got, step = restore_or_init(workdir, template=state)
        assert step == 1
        assert_tree_equal(got, state)

    @pytest.mark.parametrize("target_mesh", [(2, 4), (4, 2)])
    def test_migration_roundtrip_bitwise(self, tmp_path, target_mesh):
        import jax.numpy as jnp

        import mpi4torch_tpu as mpi
        from mpi4torch_tpu import reshard as rs
        from mpi4torch_tpu.utils import restore_resharded

        params = self._params()
        path = str(tmp_path / "ck")
        save_checkpoint(path, params)
        saved_specs = self._layouts(params, (self.N,))
        target_specs = self._layouts(params, target_mesh)

        def body():
            c = mpi.COMM_WORLD
            return restore_resharded(path, params, target_specs,
                                     saved_layout=saved_specs, comm=c)

        out = mpi.run_ranks(body, self.N)
        for r in range(self.N):
            oracle = rs.shard_of(params, target_specs, r)
            jax.tree.map(
                lambda a, b: np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b)), out[r], oracle)

    def test_migration_truncated_save_falls_back(self, tmp_path):
        # Composes with the PR 7 fault grammar: a truncate_save plan
        # kills the newest step mid-save; restore_or_init lands on the
        # last complete step and the device-side Reshard migrates THAT
        # state — one step of progress lost, never the job, never a
        # silently mixed-topology restore.
        import jax.numpy as jnp

        import mpi4torch_tpu as mpi
        from mpi4torch_tpu import reshard as rs
        from mpi4torch_tpu.resilience import (FaultSpec, fault_scope,
                                              restore_or_init)

        def state_at(step):
            return {"w": jnp.arange(32, dtype=jnp.float64).reshape(8, 4)
                    * (step + 1),
                    "step": jnp.asarray(step, jnp.int32)}

        workdir = str(tmp_path / "run")
        with CheckpointManager(workdir) as mgr:
            for step in range(2):
                mgr.save(step, state_at(step), force=True)
            with fault_scope([FaultSpec("truncate_save")]):
                mgr.save(2, state_at(2), force=True)
            mgr.wait_until_finished()
        with pytest.warns(RuntimeWarning):
            state, step = restore_or_init(workdir,
                                          template=state_at(0))
        assert step == 1

        saved_specs = self._layouts(state, (self.N,))
        target_specs = self._layouts(state, (2, 4))

        def body():
            c = mpi.COMM_WORLD
            mine = rs.shard_of(state, saved_specs, c.rank)
            return c.Reshard(mine, saved_specs, target_specs)

        out = mpi.run_ranks(body, self.N)
        for r in range(self.N):
            oracle = rs.shard_of(state_at(1), target_specs, r)
            jax.tree.map(
                lambda a, b: np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b)), out[r], oracle)


class TestCorruptionRecovery:
    """ISSUE 7: checkpoint corruption round-trips — a torn (truncated)
    save, a garbage step directory, or an empty workdir must cost at
    most one step of progress, never the job
    (mpi4torch_tpu.resilience.restore_or_init)."""

    @staticmethod
    def _state(step):
        return {"w": jnp.arange(6, dtype=jnp.float32) * (step + 1),
                "step": jnp.asarray(step, jnp.int32)}

    def _save_steps(self, workdir, steps):
        with CheckpointManager(workdir) as mgr:
            for step in steps:
                mgr.save(step, self._state(step), force=True)
            mgr.wait_until_finished()

    def test_truncated_newest_step_falls_back(self, tmp_path):
        # Simulate a kill mid-save on non-atomic storage: the newest
        # step exists but its largest data file is cut in half.
        # restore_or_init must fall back to the previous COMPLETE step.
        import os

        from mpi4torch_tpu.resilience import restore_or_init
        from mpi4torch_tpu.resilience.faults import _truncate_tree

        workdir = str(tmp_path / "run")
        self._save_steps(workdir, range(3))
        step2 = os.path.join(workdir, "2")
        assert os.path.isdir(step2)
        assert _truncate_tree(step2)
        with pytest.warns(RuntimeWarning):
            state, step = restore_or_init(workdir,
                                          template=self._state(0))
        assert step == 1
        assert_tree_equal(state, self._state(1))

    @pytest.mark.slow
    def test_mid_save_kill_via_fault_plan(self, tmp_path):
        # The same scenario driven end-to-end by the deterministic
        # fault-injection layer (the matrix's checkpoint cell; also run
        # by `make faults-smoke` — slow lane here to hold the tier-1
        # budget, the manual-truncation test above is the tier-1 pin).
        from mpi4torch_tpu.resilience.matrix import run_checkpoint_cell

        rec = run_checkpoint_cell(str(tmp_path / "run"))
        assert rec["status"] == "ok", rec

    def test_garbage_step_dir_skipped_not_fatal(self, tmp_path):
        # A numeric directory with junk inside AND a non-numeric stray:
        # discovery must skip both with a warning and land on the
        # newest real step.
        import os
        import warnings as _warnings

        from mpi4torch_tpu.resilience import restore_or_init

        workdir = str(tmp_path / "run")
        self._save_steps(workdir, range(2))
        os.makedirs(os.path.join(workdir, "7"))
        with open(os.path.join(workdir, "7", "junk"), "w") as f:
            f.write("not a checkpoint")
        os.makedirs(os.path.join(workdir, "stray-dir"), exist_ok=True)
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            state, step = restore_or_init(workdir,
                                          template=self._state(0))
        assert step == 1
        assert_tree_equal(state, self._state(1))

    def test_no_usable_checkpoint_returns_init(self, tmp_path):
        from mpi4torch_tpu.resilience import restore_or_init

        init = self._state(0)
        state, step = restore_or_init(str(tmp_path / "missing"),
                                      template=self._state(9), init=init)
        assert step is None
        assert_tree_equal(state, init)

    def test_intact_history_restores_newest(self, tmp_path):
        # The no-fault baseline of the recovery verb: newest step wins.
        from mpi4torch_tpu.resilience import restore_or_init

        workdir = str(tmp_path / "run")
        self._save_steps(workdir, range(3))
        state, step = restore_or_init(workdir, template=self._state(0))
        assert step == 2
        assert_tree_equal(state, self._state(2))

"""Checkpoint/resume (utils/checkpoint.py, orbax-backed).

The reference has no training-state persistence (SURVEY.md §5) — these
tests pin the TPU-native framework's addition: pytree roundtrips
(including sharded jax.Array leaves restoring to their mesh placement),
the resume loop reproducing an uninterrupted run bit-for-bit, retention,
and atomicity of the latest-step discovery."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("orbax.checkpoint",
                    reason="checkpoint subsystem needs orbax "
                           "(pip install mpi4torch_tpu[checkpoint])")

from mpi4torch_tpu.utils import (CheckpointManager, restore_checkpoint,
                                 save_checkpoint)  # noqa: E402


def make_state(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "params": {
            "w": jnp.asarray(rng.standard_normal((4, 4))),
            "b": jnp.zeros((4,), jnp.float32),
        },
        "opt": {"m": jnp.ones((4, 4)), "count": jnp.asarray(3, jnp.int32)},
        "step": jnp.asarray(7, jnp.int32),
    }


def assert_tree_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


class TestRoundtrip:
    def test_save_restore_roundtrip(self, tmp_path):
        state = make_state()
        save_checkpoint(str(tmp_path / "ck"), state)
        got = restore_checkpoint(str(tmp_path / "ck"),
                                 jax.tree.map(jnp.zeros_like, state))
        assert_tree_equal(got, state)

    def test_restore_missing_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            restore_checkpoint(str(tmp_path / "nope"), make_state())

    def test_dtypes_preserved(self, tmp_path):
        state = {"f64": jnp.asarray([1.5], jnp.float64),
                 "i32": jnp.asarray([2], jnp.int32),
                 "bf16": jnp.asarray([0.5], jnp.bfloat16)}
        save_checkpoint(str(tmp_path / "ck"), state)
        got = restore_checkpoint(str(tmp_path / "ck"),
                                 jax.tree.map(jnp.zeros_like, state))
        for k in state:
            assert got[k].dtype == state[k].dtype, k
        assert_tree_equal(got, state)

    def test_sharded_leaves_restore_to_mesh(self, tmp_path):
        # A mesh-sharded array round-trips onto its sharding (no host
        # gather): the template's placement decides the restore layout.
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.asarray(jax.devices()[:4]), ("x",))
        sharding = NamedSharding(mesh, P("x"))
        x = jax.device_put(jnp.arange(16.0).reshape(4, 4), sharding)
        save_checkpoint(str(tmp_path / "ck"), {"x": x})
        template = {"x": jax.device_put(jnp.zeros((4, 4)), sharding)}
        got = restore_checkpoint(str(tmp_path / "ck"), template)
        assert got["x"].sharding == sharding
        np.testing.assert_array_equal(np.asarray(got["x"]), np.asarray(x))


class TestManagerResume:
    def test_resume_matches_uninterrupted(self, tmp_path):
        # An interrupted-then-resumed run must be bit-identical to an
        # uninterrupted one — the whole point of resume.
        def train_step(state):
            g = state["params"] * 0.1 + 1.0
            return {"params": state["params"] - 0.01 * g,
                    "step": state["step"] + 1}

        init = {"params": jnp.ones((3,)), "step": jnp.asarray(0, jnp.int32)}

        ref = init
        for _ in range(6):
            ref = train_step(ref)

        workdir = str(tmp_path / "run")
        # Phase 1: 3 steps, checkpointing each, then "crash".
        with CheckpointManager(workdir) as mgr:
            state = init
            for step in range(3):
                state = train_step(state)
                mgr.save(step, state)
            mgr.wait_until_finished()
        # Phase 2: fresh process-equivalent — discover latest and resume.
        with CheckpointManager(workdir) as mgr:
            latest = mgr.latest_step()
            assert latest == 2
            state = mgr.restore(latest, template=init)
            for step in range(latest + 1, 6):
                state = train_step(state)
                mgr.save(step, state)
            mgr.wait_until_finished()
        assert_tree_equal(state, ref)

    def test_retention_keeps_last_n(self, tmp_path):
        with CheckpointManager(str(tmp_path / "r"), max_to_keep=2) as mgr:
            s = {"x": jnp.zeros(())}
            for step in range(5):
                mgr.save(step, s, force=True)
            mgr.wait_until_finished()
            assert mgr.latest_step() == 4
            assert len(mgr.all_steps()) == 2

    def test_save_interval_skips_off_steps(self, tmp_path):
        with CheckpointManager(str(tmp_path / "i"),
                               save_interval_steps=2) as mgr:
            s = {"x": jnp.zeros(())}
            saved = [mgr.save(step, s) for step in range(4)]
            mgr.wait_until_finished()
        assert saved == [True, False, True, False]

"""Multi-pod tier-stack collectives (ISSUE 18).

The tier matrix: N-level communicators (``comm_from_mesh`` with three
or more axis names, flat worlds under ``config.tier_stack``), the
csched tier dimension (tier-annotated steps, per-tier synthesis ranked
by the bandwidth-weighted wire census), and the per-tier accounting
chain (``analyze.tier_wire_table`` / ``obs.reconcile(tiers=)`` /
``tune.make_key(tiers=)``).  ``make tiers-smoke`` runs the standalone
verdict lane over the same surface.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import mpi4torch_tpu as mpi
from mpi4torch_tpu import analyze
from mpi4torch_tpu import config
from mpi4torch_tpu import constants as C
from mpi4torch_tpu import csched
from mpi4torch_tpu import obs
from mpi4torch_tpu import overlap
from mpi4torch_tpu._compat import shard_map
from mpi4torch_tpu.ops import spmd as op_spmd

NR = 8
STACKS = ((2, 2, 2), (4, 2), (2, 4), (8,))
SKEW = (1.0, 1.0, 0.05)


@pytest.fixture(autouse=True)
def _isolated_state(tmp_path, monkeypatch):
    monkeypatch.setenv("MPI4TORCH_TPU_TUNE_CACHE",
                       str(tmp_path / "tune_cache.json"))
    from mpi4torch_tpu.csched import synth as S
    mpi.tune.clear()
    S.clear_installed()
    yield
    mpi.tune.clear()
    S.clear_installed()
    config.set_tier_stack(None)
    config.set_tier_bandwidths(None)


def _lower_text(fn, n=NR, nelem=64, det=False, dtype=jnp.float32):
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("w",))
    ctx = op_spmd.SpmdContext(axis_name="w", size=n)
    x = jnp.arange(nelem, dtype=dtype)
    wrapped = shard_map(lambda v: fn(ctx, v), mesh=mesh, in_specs=P(),
                        out_specs=P(), check_vma=False)
    with config.deterministic_mode(det):
        return jax.jit(wrapped).lower(x).as_text()


def _skew_for(stack):
    return tuple([1.0] * (len(stack) - 1) + [0.05]) \
        if len(stack) > 1 else (1.0,)


# ---------------------------------------------------------------------------
# Config knobs
# ---------------------------------------------------------------------------


class TestConfigKnobs:
    def test_tier_stack_validation(self):
        config.set_tier_stack((2, 2, 2))
        assert config.tier_stack() == (2, 2, 2)
        config.set_tier_stack(None)
        assert config.tier_stack() is None
        for bad in ((1, 4), (), 5, ("x",)):
            with pytest.raises(ValueError):
                config.set_tier_stack(bad)

    def test_tier_bandwidths_validation(self):
        config.set_tier_bandwidths((1.0, 0.05))
        assert config.tier_bandwidths() == (1.0, 0.05)
        config.set_tier_bandwidths(None)
        for bad in ((), (1.0, 0.0), (1.0, -2.0), "fast"):
            with pytest.raises(ValueError):
                config.set_tier_bandwidths(bad)

    def test_knobs_ride_the_thresholds_fingerprint(self):
        base = config.thresholds_fingerprint()
        config.set_tier_stack((2, 4))
        with_stack = config.thresholds_fingerprint()
        config.set_tier_bandwidths((1.0, 0.1))
        with_both = config.thresholds_fingerprint()
        assert len({base, with_stack, with_both}) == 3
        config.set_tier_stack(None)
        config.set_tier_bandwidths(None)
        assert config.thresholds_fingerprint() == base

    def test_process_state_round_trip(self):
        config.set_tier_stack((2, 2, 2))
        config.set_tier_bandwidths((1.0, 1.0, 0.05))
        snap = config.snapshot_process_state()
        assert snap["tier_stack"] == (2, 2, 2)
        assert snap["tier_bandwidths"] == (1.0, 1.0, 0.05)
        config.set_tier_stack(None)
        config.set_tier_bandwidths(None)
        config.apply_process_state(snap)
        assert config.tier_stack() == (2, 2, 2)
        assert config.tier_bandwidths() == (1.0, 1.0, 0.05)

    def test_resolve_tier_stack_contract(self):
        from mpi4torch_tpu.tune import resolve_tier_stack

        assert resolve_tier_stack(8) == (2, 4)   # hier pair default
        config.set_tier_stack((2, 2, 2))
        assert resolve_tier_stack(8) == (2, 2, 2)
        with pytest.raises(mpi.CommError, match="does not factor"):
            resolve_tier_stack(6)


# ---------------------------------------------------------------------------
# Mode A/B parity matrix over nested factorizations
# ---------------------------------------------------------------------------


class TestNestedParityMatrix:
    """Deterministic grouped-fold forms stay bitwise Mode A == Mode B
    per tier on every factorization of the 8-device world, forward and
    backward."""

    def _payload(self, seed=0):
        rng = np.random.default_rng(seed)
        return jnp.asarray(rng.standard_normal((NR, 37)), jnp.float32)

    def _mode_a(self, vals, det=True, grad=False):
        def body():
            idx = jax.lax.axis_index("mpi")
            if grad:
                return jax.grad(lambda v: jnp.vdot(
                    mpi.COMM_WORLD.Allreduce(v, mpi.MPI_SUM,
                                             algorithm="hier"),
                    vals[idx]))(vals[idx])
            return mpi.COMM_WORLD.Allreduce(vals[idx], mpi.MPI_SUM,
                                            algorithm="hier")

        with config.deterministic_mode(det):
            return mpi.run_spmd(body, nranks=NR)()

    def _mode_b(self, vals, grad=False):
        def body(rank):
            if grad:
                return jax.grad(lambda v: jnp.vdot(
                    mpi.COMM_WORLD.Allreduce(v, mpi.MPI_SUM,
                                             algorithm="hier"),
                    vals[rank]))(vals[rank])
            return mpi.COMM_WORLD.Allreduce(vals[rank], mpi.MPI_SUM,
                                            algorithm="hier")
        return mpi.run_ranks(body, nranks=NR)

    @pytest.mark.parametrize("stack", [(2, 2, 2), (4, 2), (2, 4)])
    def test_det_hier_bitwise_fwd(self, stack):
        config.set_tier_stack(stack)
        vals = self._payload(1)
        a = self._mode_a(vals)
        b = self._mode_b(vals)
        assert bool(jnp.all(a == a[0]))
        assert all(bool(jnp.all(r == a[0])) for r in b)

    @pytest.mark.parametrize("stack", [(2, 2, 2), (2, 4)])
    def test_det_hier_bitwise_bwd(self, stack):
        # The backward of an MPI_SUM allreduce is the transposed
        # program — itself an allreduce, folded with the SAME per-tier
        # association in both modes.
        config.set_tier_stack(stack)
        vals = self._payload(2)
        a = self._mode_a(vals, grad=True)
        b = self._mode_b(vals, grad=True)
        assert all(bool(jnp.all(b[r] == a[r])) for r in range(NR))

    @pytest.mark.parametrize("stack", [(2, 2, 2), (4, 2)])
    def test_nondet_hier_correct(self, stack):
        config.set_tier_stack(stack)
        vals = self._payload(3)
        a = self._mode_a(vals, det=False)
        np.testing.assert_allclose(np.asarray(a[0]),
                                   np.asarray(vals.sum(0)), rtol=1e-5)

    def test_single_tier_stack_raises_for_explicit_hier(self):
        # (8,) has no 2-level split: the explicit request raises the
        # SAME way in both modes (the shared resolve_hier_group gate).
        config.set_tier_stack((8,))
        vals = self._payload(4)
        with pytest.raises(mpi.CommError, match="single flat tier"):
            self._mode_b(vals)
        with pytest.raises(mpi.CommError, match="single flat tier"):
            self._mode_a(vals)

    def test_process_transport_bitwise(self):
        # The tier stack rides the process-state snapshot: worker
        # processes fold with the same nested chain as rank-threads.
        config.set_tier_stack((2, 2, 2))
        rng = np.random.default_rng(5)
        base = rng.standard_normal(33).astype(np.float32)

        def body(rank):
            x = jnp.asarray(base) * (rank + 1)
            return np.asarray(mpi.COMM_WORLD.Allreduce(
                x, mpi.MPI_SUM, algorithm="hier"))

        try:
            got = mpi.run_ranks(body, NR, backend="process")
        finally:
            # Don't leak an 8-worker pool into later test modules whose
            # respawn accounting assumes a pool sized to their own runs.
            from mpi4torch_tpu.transport import shutdown
            shutdown()
        oracle = mpi.run_ranks(body, NR, backend="thread")
        for r in range(NR):
            np.testing.assert_array_equal(got[r], oracle[r])

    @pytest.mark.parametrize("comp", ["exact", "q8-slow"])
    def test_synth_composition_bitwise(self, comp):
        # Integer-valued payloads: po2-scale block-q8 round-trips
        # integer grids exactly, so the q8-slow cell compares real
        # schedules, not two rounding paths.
        stack = (2, 2, 2)
        rng = np.random.default_rng(18)
        vals = [jnp.asarray(rng.integers(-40, 40, 257), jnp.float32)
                for _ in range(NR)]
        prog = csched.fold_program(NR, stack, stack)
        if comp == "q8-slow":
            prog = csched.rewrite_fold_codec(prog, (len(stack) - 1,))
        name = csched.install(prog)
        oracle = csched.interpret_allreduce(prog, C.MPI_SUM, vals)
        stacked = jnp.stack(vals)

        def body():
            idx = jax.lax.axis_index("mpi")
            return mpi.COMM_WORLD.Allreduce(stacked[idx], mpi.MPI_SUM,
                                            algorithm=name)

        with config.deterministic_mode(True):
            rows = mpi.run_spmd(body, nranks=NR)()
        assert bool(jnp.all(rows[0] == oracle))
        assert bool(jnp.all(rows == rows[0]))
        eager = mpi.run_ranks(
            lambda rank: mpi.COMM_WORLD.Allreduce(
                vals[rank], mpi.MPI_SUM, algorithm=name), nranks=NR)
        assert all(bool(jnp.all(r == oracle)) for r in eager)


# ---------------------------------------------------------------------------
# Per-tier census
# ---------------------------------------------------------------------------


class TestTierCensus:
    def test_tier_of_group_attribution_rule(self):
        tiers = (2, 2, 2)
        assert csched.tier_of_group((0, 1), tiers) == 0
        assert csched.tier_of_group((0, 2), tiers) == 1
        assert csched.tier_of_group((0, 4), tiers) == 2
        assert csched.tier_of_group((0, 5), tiers) == 2
        assert csched.tier_of_groups(None, tiers) == 2
        assert csched.tier_of_groups(((0, 1), (2, 3)), tiers) == 0

    def test_weighted_cost_arithmetic(self):
        assert csched.weighted_cost((100, 50), (1.0, 0.05)) \
            == 100 + 50 / 0.05
        assert csched.weighted_cost((100, 50)) == 150.0

    @pytest.mark.parametrize("stack", [(2, 2, 2), (4, 2), (2, 4)])
    def test_program_tier_census_sums_to_wire(self, stack):
        prog = csched.fold_program(NR, stack, stack)
        per = csched.program_tier_census(prog, 1024, 4, stack)
        assert len(per) == len(stack)
        assert all(w > 0 for w in per)
        assert sum(per) \
            == csched.program_census(prog, 1024, 4)["wire_bytes_per_rank"]

    def test_lowering_tier_table_matches_program_census(self):
        # The analyze-side table of the ACTUAL lowering equals the
        # program-side prediction, with DISTINCT replica groups feeding
        # distinct tiers.
        stack = (2, 2, 2)
        prog = csched.fold_program(NR, stack, stack)
        name = csched.install(prog)
        txt = _lower_text(
            lambda c, v: op_spmd._allreduce_fwd_value(c, v, C.MPI_SUM,
                                                      name),
            nelem=256, det=True)
        got = analyze.tier_wire_table(txt, stack)
        assert got == csched.program_tier_census(prog, 256, 4, stack)
        assert sum(1 for w in got if w > 0) == 3
        parsed = analyze.parse_program(txt)
        tables = {str(op.replica_groups) for op in parsed.collectives
                  if op.replica_groups}
        assert len(tables) >= 2, "tiers share one replica-group table"

    def test_weighted_wire_cost_config_fallback(self):
        stack = (2, 4)
        txt = _lower_text(
            lambda c, v: op_spmd._allreduce_fwd_value(c, v, C.MPI_SUM,
                                                      "hier"),
            nelem=256, det=True)
        explicit = analyze.weighted_wire_cost(txt, (1.0, 0.05),
                                              tiers=stack)
        assert explicit == csched.weighted_cost(
            analyze.tier_wire_table(txt, stack), (1.0, 0.05))
        config.set_tier_stack(stack)
        assert analyze.weighted_wire_cost(txt, (1.0, 0.05)) == explicit
        config.set_tier_stack(None)
        with pytest.raises(ValueError, match="tier stack"):
            analyze.weighted_wire_cost(txt, (1.0, 0.05))


# ---------------------------------------------------------------------------
# Weighted synthesis verdict
# ---------------------------------------------------------------------------


class TestSynthesisWeighted:
    def test_pinned_skewed_verdict(self):
        # The acceptance numbers on the (2,2,2)/slow-outer cell: the
        # synthesized tier program beats flat bidir on the weighted
        # census, with the outer-tier byte reduction visible in the
        # per-tier breakdown.
        res = csched.synthesize_tiers(NR, 4096, 4, tiers=(2, 2, 2),
                                      tier_bandwidths=SKEW)
        assert res["tier_wire"] == [4096, 4096, 1040]
        assert res["weighted_cost"] == 28992.0
        assert res["bidir_tier_wire"] == [0, 0, 7168]
        assert res["bidir_weighted_cost"] == 143360.0
        assert res["beats_bidir"]
        assert res["tier_wire"][-1] < res["bidir_tier_wire"][-1]
        assert res["composition"] == "q8-slow"
        # and the all-exact runner-up is reported alongside
        assert res["exact_tier_wire"][-1] < res["bidir_tier_wire"][-1]

    @pytest.mark.parametrize("stack", STACKS)
    def test_search_is_deterministic(self, stack):
        a = csched.synthesize_tiers(NR, 4096, 4, tiers=stack,
                                    tier_bandwidths=_skew_for(stack))
        b = csched.synthesize_tiers(NR, 4096, 4, tiers=stack,
                                    tier_bandwidths=_skew_for(stack))
        assert a["winner"] == b["winner"]
        assert a["program"].digest() == b["program"].digest()

    @pytest.mark.parametrize("stack", [(2, 2, 2), (4, 2), (2, 4)])
    def test_uniform_bandwidths_stay_exact(self, stack):
        # No skew -> the q8-slow rewrite never fires: every candidate
        # is exact, so enabling tiers cannot regress accuracy.
        res = csched.synthesize_tiers(NR, 4096, 4, tiers=stack)
        assert all(c["composition"] == "exact"
                   for c in res["candidates"])
        assert res["winner"] == res["exact_winner"]

    def test_two_level_stack_is_hier_text_identical(self):
        # Uniform weights + a 2-level stack: TierStackBackend (flat
        # config form) lowers byte-identically to the pre-tier hier.
        config.set_hier_group_size(2)
        try:
            base = _lower_text(
                lambda c, v: op_spmd._allreduce_fwd_value(
                    c, v, C.MPI_SUM, "hier"), det=True)
        finally:
            config.set_hier_group_size(None)
        config.set_tier_stack((2, 4))
        tiered = _lower_text(
            lambda c, v: op_spmd._allreduce_fwd_value(
                c, v, C.MPI_SUM, "hier"), det=True)
        config.set_tier_stack(None)
        assert base == tiered

    def test_two_level_mesh_backend_is_hier_mesh_backend(self):
        from mpi4torch_tpu.ops.spmd import (HierMeshBackend,
                                            TierStackBackend)

        assert issubclass(HierMeshBackend, TierStackBackend)
        mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                    ("g", "l"))
        x = jnp.arange(64, dtype=jnp.float32)

        def lower(back):
            wrapped = shard_map(
                lambda v: back.allreduce(v, C.MPI_SUM), mesh=mesh,
                in_specs=P(), out_specs=P(), check_vma=False)
            return jax.jit(wrapped).lower(x).as_text()

        assert lower(TierStackBackend(("g", "l"), (2, 4))) \
            == lower(HierMeshBackend(("g", "l"), (2, 4)))


# ---------------------------------------------------------------------------
# Tier-keyed autotuner cache
# ---------------------------------------------------------------------------


class TestCacheTiers:
    def test_make_key_tier_dimension(self):
        flat = mpi.tune.make_key("allreduce", "float32", 1 << 14, NR,
                                 platform="cpu")
        tiered = mpi.tune.make_key("allreduce", "float32", 1 << 14, NR,
                                   platform="cpu", tiers=(2, 2, 2))
        assert "tiers=" not in flat
        assert tiered == flat + "|tiers=2x2x2"
        assert mpi.tune.make_key("allreduce", "float32", 1 << 14, NR,
                                 platform="cpu", tiers="2x2x2") == tiered
        # grammar order: codec= before tiers= before transition=
        full = mpi.tune.make_key("allreduce", "float32", 1 << 14, NR,
                                 platform="cpu", codec="synth",
                                 tiers=(2, 4), transition="warm")
        assert full.endswith("|codec=synth|tiers=2x4|transition=warm")

    def test_cache_version_is_3_and_v2_files_silently_ignored(self):
        from mpi4torch_tpu.tune import autotuner as A

        assert A.CACHE_VERSION == 3
        key = mpi.tune.make_key("allreduce", "float32", 512, NR)
        with open(mpi.tune.cache_path(), "w") as f:
            json.dump({"version": 2,
                       "entries": {key: {"algorithm": "hier"}}}, f)
        mpi.tune.clear()
        # pre-tier digests/keys are discarded by the version gate --
        # silently: no crash, defaults apply.
        assert mpi.tune.lookup("allreduce", "float32", 512, NR) is None
        assert mpi.tune.select_auto(nbytes=512, dtype=jnp.float32,
                                    nranks=NR) == "ring"

    def test_tier_synthesis_records_under_tier_keys(self):
        rep = csched.autotune_tier_synthesis(
            nranks=NR, sizes=(1 << 12,), tiers=(2, 2, 2),
            tier_bandwidths=SKEW)
        ent = rep["entries"][str(1 << 12)]
        assert ent["recorded"]
        # exact winner under codec="synth" (the slot select_auto's
        # deterministic path may consult), lossy under "synth_q8"
        # (never consulted implicitly).
        got_exact = mpi.tune.lookup_algorithm(
            "allreduce", jnp.float32, 1 << 12, NR, codec="synth",
            tiers=(2, 2, 2))
        got_lossy = mpi.tune.lookup_algorithm(
            "allreduce", jnp.float32, 1 << 12, NR, codec="synth_q8",
            tiers=(2, 2, 2))
        assert got_exact == ent["exact_winner"]
        assert got_lossy == ent["winner"]
        # the tier slot never leaks into flat lookups or auto selection
        assert mpi.tune.lookup_algorithm("allreduce", jnp.float32,
                                         1 << 12, NR) is None
        assert not mpi.tune.select_auto(
            collective="allreduce", nbytes=1 << 12, dtype=jnp.float32,
            nranks=NR, deterministic=True).startswith("synth:")

    def test_tune_show_has_tier_column(self):
        from mpi4torch_tpu.tune.__main__ import _COLUMNS, _rows

        assert "tiers" in _COLUMNS
        csched.autotune_tier_synthesis(nranks=NR, sizes=(1 << 12,),
                                       tiers=(2, 2, 2),
                                       tier_bandwidths=SKEW)
        mpi.tune.record("allreduce", "float32", 512, NR, "tree",
                        platform="cpu")
        rows = _rows(json.load(open(mpi.tune.cache_path())))
        by_tier = {r[5] for r in rows}
        assert "2x2x2" in by_tier and "-" in by_tier
        tiered = [r for r in rows if r[5] == "2x2x2"]
        assert all(r[6].startswith("synth:") for r in tiered)


# ---------------------------------------------------------------------------
# obs.reconcile prices per-tier traffic exactly
# ---------------------------------------------------------------------------


class TestReconcileTiers:
    def test_measured_tier_wire_matches_predicted_exactly(self):
        stack = (2, 2, 2)
        res = csched.synthesize_tiers(NR, 4096, 4, tiers=stack,
                                      tier_bandwidths=SKEW)
        name = csched.install(res["program"])
        x = jnp.arange(1024, dtype=jnp.float32)

        with obs.trace() as t:
            mpi.run_ranks(
                lambda rank: mpi.COMM_WORLD.Allreduce(
                    x * (rank + 1), mpi.MPI_SUM, algorithm=name), NR)
        lowered = _lower_text(
            lambda c, v: op_spmd._allreduce_fwd_value(c, v, C.MPI_SUM,
                                                      name),
            nelem=1024, det=True)
        rep = obs.reconcile(t.events, lowered, dropped=t.dropped,
                            tiers=stack)
        assert rep["ok"], rep
        assert rep["matches"]["tier_wire"]
        assert rep["measured"]["tier_wire"] \
            == rep["predicted"]["tier_wire"] == res["tier_wire"]

    def test_reconcile_without_tiers_is_unchanged(self):
        x = jnp.arange(256, dtype=jnp.float32)
        with obs.trace() as t:
            mpi.run_ranks(
                lambda rank: mpi.COMM_WORLD.Allreduce(
                    x * (rank + 1), mpi.MPI_SUM, algorithm="ring"), NR)
        lowered = _lower_text(
            lambda c, v: op_spmd._allreduce_fwd_value(c, v, C.MPI_SUM,
                                                      "ring"),
            nelem=256)
        rep = obs.reconcile(t.events, lowered, dropped=t.dropped)
        assert rep["ok"], rep
        assert "tier_wire" not in rep["measured"]
        assert "tier_wire" not in rep["matches"]


# ---------------------------------------------------------------------------
# Overlap window widening for slow outer tiers
# ---------------------------------------------------------------------------


class TestOverlapTierWindow:
    def _lower_tree(self, ov, nb=4):
        mesh = Mesh(np.asarray(jax.devices()[:NR]), ("w",))
        c = mpi.comm_from_mesh(mesh, "w")
        tree = [jnp.ones(1024, jnp.float32) for _ in range(nb)]
        wrapped = shard_map(
            lambda t: c.Allreduce_tree(t, mpi.MPI_SUM,
                                       bucket_bytes=4096, overlap=ov),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)
        return jax.jit(wrapped).lower(tree)

    def test_tier_window_depth_derivation(self):
        assert overlap.tier_window_depth() is None
        config.set_tier_stack((2, 2, 2))
        assert overlap.tier_window_depth() is None   # no bandwidths
        config.set_tier_bandwidths((1.0, 1.0, 0.05))
        assert overlap.tier_window_depth() == 21     # ceil(20) + 1
        config.set_tier_bandwidths((1.0, 1.0, 1.0))
        assert overlap.tier_window_depth() is None   # uniform: no skew
        config.set_tier_bandwidths((1.0, 0.05))      # misaligned stack
        assert overlap.tier_window_depth() is None

    def test_skewed_config_widens_the_window(self):
        blocking = overlap.scheduled_exposure(self._lower_tree(False))
        default = overlap.scheduled_exposure(self._lower_tree(True))
        txt_default = self._lower_tree(True).as_text()
        config.set_tier_stack((2, 2, 2))
        config.set_tier_bandwidths((1.0, 1.0, 0.05))
        widened = overlap.scheduled_exposure(self._lower_tree(True))
        txt_wide = self._lower_tree(True).as_text()
        assert blocking["exposed_fraction"] == 1.0
        assert widened["exposed_fraction"] \
            < blocking["exposed_fraction"]
        assert widened["exposed_fraction"] \
            <= default["exposed_fraction"]
        assert all(b["split_phase"]
                   for b in widened["buckets"].values())
        # the widened window IS a different schedule (deeper start ->
        # wait spans), not a relabeling
        assert txt_wide != txt_default

    def test_explicit_tier_window_parameter(self):
        from mpi4torch_tpu.fuse.collectives import fused_allreduce_tree

        mesh = Mesh(np.asarray(jax.devices()[:NR]), ("w",))
        c = mpi.comm_from_mesh(mesh, "w")
        tree = [jnp.ones(1024, jnp.float32) for _ in range(4)]

        def lower(tw):
            wrapped = shard_map(
                lambda t: fused_allreduce_tree(
                    c, t, mpi.MPI_SUM, bucket_bytes=4096, overlap=True,
                    tier_window=tw),
                mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False)
            return jax.jit(wrapped).lower(tree).as_text()

        assert lower(4) != lower(None)
        # widen-only: a window shallower than the overlap depth is a
        # no-op
        assert lower(1) == lower(None)


# ---------------------------------------------------------------------------
# Registry guard + N-axis communicator
# ---------------------------------------------------------------------------


class TestRegistryGuard:
    def test_tier_program_problems_empty(self):
        from mpi4torch_tpu.analyze.registry import tier_program_problems
        assert tier_program_problems() == []

    def test_standing_problems_still_empty(self):
        from mpi4torch_tpu.analyze.registry import standing_problems
        assert standing_problems() == []


class TestCommFromMeshND:
    def _mesh3(self):
        return Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                    ("pod", "host", "chip"))

    def test_three_axis_comm_allreduce_fwd_bwd(self):
        from mpi4torch_tpu.ops.spmd import (HierMeshBackend,
                                            TierStackBackend)

        mesh = self._mesh3()
        c = mpi.comm_from_mesh(mesh, ("pod", "host", "chip"))
        assert isinstance(c._backend(), TierStackBackend)
        assert not isinstance(c._backend(), HierMeshBackend)
        assert c._backend().size == 8
        x = jnp.arange(48, dtype=jnp.float32)

        def run(fn):
            wrapped = shard_map(fn, mesh=mesh, in_specs=P(),
                                out_specs=P(), check_vma=False)
            return jax.jit(wrapped)(x)

        out = run(lambda v: c.Allreduce(v, mpi.MPI_SUM))
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(x) * 8, rtol=1e-6)
        g = run(lambda v: jax.grad(
            lambda t: jnp.vdot(c.Allreduce(t, mpi.MPI_SUM), t))(v))
        # d/dt vdot(AR(t), t) = AR(t) + AR(t) = 2 * 8 * t for equal
        # per-rank operands
        np.testing.assert_allclose(np.asarray(g),
                                   np.asarray(x) * 16, rtol=1e-6)

    def test_three_axis_det_mode_lowers_grouped_chain(self):
        mesh = self._mesh3()
        c = mpi.comm_from_mesh(mesh, ("pod", "host", "chip"))
        x = jnp.arange(64, dtype=jnp.float32)
        wrapped = shard_map(lambda v: c.Allreduce(v, mpi.MPI_SUM),
                            mesh=mesh, in_specs=P(), out_specs=P(),
                            check_vma=False)
        with config.deterministic_mode(True):
            txt = jax.jit(wrapped).lower(x).as_text()
        got = analyze.tier_wire_table(txt, (2, 2, 2))
        assert len(got) == 3 and all(w > 0 for w in got)

    def test_two_axis_tuple_still_builds_hier(self):
        from mpi4torch_tpu.ops.spmd import HierMeshBackend

        mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                    ("g", "l"))
        c = mpi.comm_from_mesh(mesh, ("g", "l"))
        assert isinstance(c._backend(), HierMeshBackend)

    def test_error_paths(self):
        mesh = self._mesh3()
        with pytest.raises(mpi.CommError, match="two or more"):
            mpi.comm_from_mesh(mesh, ("pod",))
        with pytest.raises(mpi.CommError, match="not in mesh"):
            mpi.comm_from_mesh(mesh, ("pod", "rack"))

"""Fused bucketed collectives (mpi4torch_tpu.fuse, ISSUE 2).

Four claims are pinned here:

1. **Launch census** — a 100-leaf fp32 pytree Allreduce lowers to exactly
   ONE reduce-scatter + all-gather pair under SPMD when it fits one
   bucket, and to exactly ``ceil(total_bytes / bucket_bytes)`` pairs when
   it does not (vs one all_reduce per leaf unfused).
2. **Parity** — the fused path is bit-identical to the per-leaf path on
   the eager backend (same ascending-rank fold, concat changes nothing
   per element), including the Isend/Irecv overlap pipeline, and matches
   it to fp tolerance on the SPMD mesh.
3. **AD transparency** — gradients through fused (and fused+compressed)
   buckets equal the per-leaf gradients; the backward program is itself
   bucketed (census counts double, not per-leaf).
4. **DP lock-step** — ``all_average_tree``'s fused mean keeps gradients
   bitwise identical across ranks (the regression test of the
   single-post-fuse-scale change in parallel/dp.py).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import mpi4torch_tpu as mpi
from mpi4torch_tpu import fuse
from mpi4torch_tpu._compat import shard_map
from mpi4torch_tpu.fuse.bucketing import bucket_layout, flatten_buckets

NR = 4
comm = mpi.COMM_WORLD

COLLECTIVES = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
               "collective_permute")


def census(fn, *args):
    """Collective-op census of ``fn`` lowered in a shard_map over a fresh
    NR-device mesh (the test_hlo.py pattern)."""
    mesh = Mesh(np.asarray(jax.devices()[:NR]), ("w",))
    c = mpi.comm_from_mesh(mesh, "w")
    wrapped = shard_map(lambda *a: fn(c, *a), mesh=mesh, in_specs=P(),
                        out_specs=P(), check_vma=False)
    txt = jax.jit(wrapped).lower(*args).as_text()
    return {k: txt.count(f"stablehlo.{k}") for k in COLLECTIVES}


def tree100():
    # 100 fp32 leaves, 6400 B total — far under one 4 MiB bucket.
    return {f"p{i}": jnp.full((16,), float(i + 1), jnp.float32)
            for i in range(100)}


def mixed_tree(scale=1.0):
    return {
        "a": jnp.arange(7, dtype=jnp.float32) * scale,
        "b": [jnp.ones((3, 5), jnp.float64) * 2.0 * scale,
              jnp.arange(4, dtype=jnp.int32)],
        "c": jnp.linspace(0.0, 1.0, 9, dtype=jnp.float64) * scale,
        "d": jnp.float32(scale),
    }


# ---------------------------------------------------------------------------
# Bucketing layout
# ---------------------------------------------------------------------------


class TestBucketing:
    def test_roundtrip_identity(self):
        t = mixed_tree(3.0)
        buckets, layout = flatten_buckets(t, 1 << 22)
        back = fuse.unflatten_buckets(buckets, layout)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            t, back)

    def test_buckets_are_dtype_homogeneous(self):
        buckets, layout = flatten_buckets(mixed_tree(), 1 << 22)
        for b, dt in zip(buckets, layout.bucket_dtypes):
            assert b.dtype == dt
        # f32 leaves (a, d), f64 leaves (b0, c), i32 leaf (b1) — three
        # dtype classes, three buckets at this size.
        assert layout.num_buckets == 3

    def test_layout_cached_per_structure(self):
        t = tree100()
        l1 = bucket_layout(t, 1 << 22)
        l2 = bucket_layout(jax.tree.map(lambda x: x * 2.0, t), 1 << 22)
        assert l1 is l2          # lru_cache hit: same structure+avals
        l3 = bucket_layout(t, 1 << 20)
        assert l3 is not l1      # different bucket size, different plan

    def test_bucket_bytes_respected_and_oversize_leaf_isolated(self):
        t = {"small": [jnp.ones((64,), jnp.float32) for _ in range(8)],
             "big": jnp.ones((1024,), jnp.float32)}
        layout = bucket_layout(t, 1024)      # 256 B leaves, 4 KiB big leaf
        sizes = layout.bucket_sizes
        # 8 small leaves -> 4 elem/bucket... 64*4B=256B, 4 per 1 KiB
        # bucket -> 2 buckets of 256 elems; the big leaf overflows any
        # bucket and sits alone in its own.
        assert 1024 in sizes
        for sz, dt in zip(sizes, layout.bucket_dtypes):
            if sz != 1024:
                assert sz * jnp.dtype(dt).itemsize <= 1024


# ---------------------------------------------------------------------------
# HLO census: launches
# ---------------------------------------------------------------------------


class TestFusedCensus:
    def test_100_leaves_one_collective_pair(self):
        # The ISSUE 2 acceptance bar: <= 4 MiB of fp32 leaves -> exactly
        # one fused ring reduce-scatter + all-gather pair, nothing else.
        got = census(lambda c, t: c.Allreduce_tree(t, mpi.MPI_SUM),
                     tree100())
        assert got == {"all_reduce": 0, "all_gather": 1,
                       "reduce_scatter": 1, "all_to_all": 0,
                       "collective_permute": 0}

    def test_unfused_baseline_is_per_leaf(self):
        got = census(
            lambda c, t: jax.tree.map(
                lambda p: c.Allreduce(p, mpi.MPI_SUM), t),
            tree100())
        assert got["all_reduce"] == 100

    def test_bucket_count_matches_ceil_bound(self):
        # 100 leaves x 64 B; bucket_bytes=1024 packs exactly 16 leaves
        # per bucket -> ceil(6400/1024) = 7 pairs.
        t = tree100()
        total = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(t))
        bb = 1024
        expect = math.ceil(total / bb)
        got = census(
            lambda c, tt: c.Allreduce_tree(tt, mpi.MPI_SUM,
                                           bucket_bytes=bb), t)
        assert got["reduce_scatter"] == got["all_gather"] == expect

    def test_fusion_scope_zero_disables(self):
        def f(c, t):
            with mpi.config.fusion_scope(0):
                return c.Allreduce_tree(t, mpi.MPI_SUM)

        got = census(f, tree100())
        assert got["all_reduce"] == 100
        assert got["reduce_scatter"] == 0

    def test_fusion_scope_sets_bucket_size(self):
        def f(c, t):
            with mpi.config.fusion_scope(1024):
                return c.Allreduce_tree(t, mpi.MPI_SUM)

        got = census(f, tree100())
        assert got["reduce_scatter"] == 7
        # and the default is restored outside the scope
        assert mpi.config.default_bucket_bytes() \
            == mpi.config.DEFAULT_BUCKET_BYTES

    def test_backward_is_bucketed_too(self):
        # AD transparency at the launch level: fwd+bwd of one fused
        # bucket is two pairs, not 100 + 100 per-leaf collectives.
        def f(c, t):
            def loss(tt):
                y = c.Allreduce_tree(tt, mpi.MPI_SUM)
                return sum(jnp.vdot(v, v) for v in jax.tree.leaves(y))
            return jax.grad(loss)(t)

        got = census(f, tree100())
        assert got["all_reduce"] == 0
        assert got["reduce_scatter"] == 2
        assert got["all_gather"] == 2

    def test_compressed_buckets_ship_int8(self):
        mesh = Mesh(np.asarray(jax.devices()[:NR]), ("w",))
        c = mpi.comm_from_mesh(mesh, "w")
        t = {f"p{i}": jnp.ones((64,), jnp.float32) for i in range(10)}

        def f(tt):
            return c.Allreduce_tree(tt, mpi.MPI_SUM, compression="q8")

        txt = jax.jit(shard_map(f, mesh=mesh, in_specs=P(), out_specs=P(),
                                check_vma=False)).lower(t).as_text()
        import re
        assert re.search(r"collective_permute.*xi8>", txt), \
            "fused q8 bucket did not ride the int8 ring"
        assert txt.count("stablehlo.all_reduce") == 0

    def test_zero3_regather_is_one_allgather_per_bucket(self):
        from mpi4torch_tpu.parallel import zero

        t = {f"w{i}": jnp.ones((8, 3), jnp.float64) for i in range(12)}

        def f(c, tt):
            shards = zero.zero3_shard_params(c, tt)
            return zero.zero3_params(c, shards, tt)

        got = census(f, t)
        assert got["all_gather"] == 1
        assert got["all_reduce"] == 0

    def test_zero_grad_shard_is_one_reduce_scatter_per_bucket(self):
        def f(c, tt):
            return fuse.fused_reduce_scatter_tree(c, tt, mpi.MPI_SUM,
                                                  mean=True)

        got = census(f, {f"g{i}": jnp.ones((10,), jnp.float64)
                         for i in range(12)})
        assert got["reduce_scatter"] == 1
        assert got["all_reduce"] == got["all_gather"] == 0


# ---------------------------------------------------------------------------
# Value / gradient parity
# ---------------------------------------------------------------------------


def _perleaf_allreduce(c, t, **kw):
    return jax.tree.map(lambda p: c.Allreduce(p, mpi.MPI_SUM, **kw), t)


class TestParity:
    def test_eager_fused_bitwise_equals_perleaf(self):
        def body():
            t = mixed_tree(float(comm.rank + 1))
            fused = comm.Allreduce_tree(t, mpi.MPI_SUM)
            ref = _perleaf_allreduce(comm, t)
            return jax.tree.map(np.asarray, (fused, ref))

        for fused, ref in mpi.run_ranks(body, NR):
            jax.tree.map(np.testing.assert_array_equal, fused, ref)

    def test_eager_overlap_pipeline_bitwise_equals_perleaf(self):
        def body():
            t = {"a": jnp.arange(13, dtype=jnp.float64) * (comm.rank + 1),
                 "b": jnp.ones((5, 3), jnp.float64) * (comm.rank - 1.5)}
            fused = fuse.fused_allreduce_tree(comm, t, mpi.MPI_SUM,
                                              overlap=True)
            ref = _perleaf_allreduce(comm, t)
            return jax.tree.map(np.asarray, (fused, ref))

        for fused, ref in mpi.run_ranks(body, NR):
            jax.tree.map(np.testing.assert_array_equal, fused, ref)

    def test_eager_overlap_pipeline_multibucket_and_grads(self):
        # Several buckets in flight (bucket_bytes forces 4 buckets of 2
        # leaves); values and gradients must both match the per-leaf
        # path bitwise.
        def body():
            t = {f"p{i}": jnp.arange(8, dtype=jnp.float64) + comm.rank + i
                 for i in range(8)}

            def loss_fused(tt):
                y = fuse.fused_allreduce_tree(comm, tt, mpi.MPI_SUM,
                                              bucket_bytes=128,
                                              overlap=True)
                return sum(jnp.vdot(v, v) for v in jax.tree.leaves(y))

            def loss_ref(tt):
                y = _perleaf_allreduce(comm, tt)
                return sum(jnp.vdot(v, v) for v in jax.tree.leaves(y))

            vf, gf = jax.value_and_grad(loss_fused)(t)
            vr, gr = jax.value_and_grad(loss_ref)(t)
            return np.asarray(vf), np.asarray(vr), \
                jax.tree.map(np.asarray, (gf, gr))

        for vf, vr, (gf, gr) in mpi.run_ranks(body, NR):
            np.testing.assert_array_equal(vf, vr)
            jax.tree.map(np.testing.assert_array_equal, gf, gr)

    def test_spmd_fused_matches_eager_oracle(self):
        data = {"a": np.linspace(-2.0, 3.0, 17),
                "c": np.sin(np.arange(33, dtype=np.float64))}

        def eager_body():
            t = jax.tree.map(lambda x: jnp.asarray(x) * (comm.rank + 1),
                             data)
            return jax.tree.map(np.asarray,
                                comm.Allreduce_tree(t, mpi.MPI_SUM))

        oracle = mpi.run_ranks(eager_body, NR)[0]

        def spmd_body():
            r = jnp.asarray(comm.rank + 0)
            t = jax.tree.map(lambda x: jnp.asarray(x) * (r + 1.0), data)
            return comm.Allreduce_tree(t, mpi.MPI_SUM)

        out = mpi.run_spmd(spmd_body, nranks=NR)()
        for rank in range(NR):
            jax.tree.map(
                lambda o, s: np.testing.assert_allclose(
                    o, np.asarray(s)[rank], rtol=1e-12, atol=1e-12),
                oracle, out)

    def test_spmd_deterministic_fused_bitwise_matches_eager(self):
        data = np.sin(np.arange(40, dtype=np.float32)).reshape(8, 5)

        def eager_body():
            t = {"x": jnp.asarray(data) * (comm.rank + 1)}
            return np.asarray(comm.Allreduce_tree(t, mpi.MPI_SUM)["x"])

        oracle = mpi.run_ranks(eager_body, NR)[0]

        def spmd_body():
            r = jnp.asarray(comm.rank + 0)
            t = {"x": jnp.asarray(data) * (r + 1.0).astype(jnp.float32)}
            return comm.Allreduce_tree(t, mpi.MPI_SUM)["x"]

        with mpi.config.deterministic_mode(True):
            out = np.asarray(mpi.run_spmd(spmd_body, nranks=NR)())
        for rank in range(NR):
            np.testing.assert_array_equal(out[rank], oracle)

    def test_spmd_fused_grads_match_perleaf(self):
        def body():
            r = jnp.asarray(comm.rank + 0)
            t = {"a": jnp.arange(7.0) * (r + 1.0),
                 "b": jnp.ones((3, 5)) * (r + 2.0)}

            def loss(fn, tt):
                y = fn(tt)
                return sum(jnp.vdot(v, v) for v in jax.tree.leaves(y))

            gf = jax.grad(lambda tt: loss(
                lambda u: comm.Allreduce_tree(u, mpi.MPI_SUM), tt))(t)
            gr = jax.grad(lambda tt: loss(
                lambda u: _perleaf_allreduce(comm, u), tt))(t)
            return gf, gr

        gf, gr = mpi.run_spmd(body, nranks=NR)()
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-12, atol=1e-12),
            gf, gr)

    def test_nonsum_op_fused(self):
        def body():
            t = {"a": jnp.asarray([comm.rank, -comm.rank], jnp.float64),
                 "b": jnp.full((3,), float(comm.rank), jnp.float64)}
            got = comm.Allreduce_tree(t, mpi.MPI_MAX)
            ref = jax.tree.map(
                lambda p: comm.Allreduce(p, mpi.MPI_MAX), t)
            return jax.tree.map(np.asarray, (got, ref))

        for got, ref in mpi.run_ranks(body, NR):
            jax.tree.map(np.testing.assert_array_equal, got, ref)

    def test_mean_is_single_postfuse_scale(self):
        # mean=True equals per-leaf Allreduce / size bitwise in eager.
        def body():
            t = mixed_tree(float(comm.rank + 1))
            t = {"a": t["a"], "c": t["c"]}     # float leaves only
            got = comm.Allreduce_tree(t, mpi.MPI_SUM, mean=True)
            ref = jax.tree.map(
                lambda p: comm.Allreduce(p, mpi.MPI_SUM) / comm.size, t)
            return jax.tree.map(np.asarray, (got, ref))

        for got, ref in mpi.run_ranks(body, NR):
            jax.tree.map(np.testing.assert_array_equal, got, ref)

    def test_mean_with_nonsum_raises(self):
        with pytest.raises(mpi.CommError, match="mean"):
            comm.Allreduce_tree({"a": jnp.ones(3)}, mpi.MPI_MAX, mean=True)

    def test_eager_overlap_with_codec_or_nonsum_raises(self):
        # An explicit overlap=True must never silently degrade to the
        # blocking rendezvous path: the pipeline is exact-SUM-only.
        def body():
            t = {"a": jnp.ones(4)}
            got = []
            for kwargs in ({"compression": "q8"}, {}):
                try:
                    comm.Allreduce_tree(
                        t, mpi.MPI_MAX if not kwargs else mpi.MPI_SUM,
                        overlap=True, **kwargs)
                    got.append("no error")
                except mpi.CommError as e:
                    got.append("pipeline" in str(e))
            return got

        assert all(all(r) for r in mpi.run_ranks(body, NR))

    def test_stale_shard_tree_raises(self):
        # flatten_shard_rows must reject a shard tree that does not
        # belong to the template (the old per-leaf tree.map raised too).
        from mpi4torch_tpu.parallel import zero

        def body():
            t = {"w": jnp.ones((6,)), "b": jnp.ones((3,))}
            shards = zero.zero3_shard_params(comm, t)
            stale = {"w": shards["w"]}              # leaf removed
            try:
                zero.zero3_params(comm, stale, t)
            except ValueError as e:
                return "structure" in str(e)
            return False

        assert all(mpi.run_ranks(body, NR))


# ---------------------------------------------------------------------------
# Compression interaction
# ---------------------------------------------------------------------------


class TestCompressedBuckets:
    def test_fused_q8_grads_close_to_exact(self):
        # Gradient correctness through fused + compressed buckets: the
        # adjoint is a compressed bucketed collective; on rank-uniform
        # values q8's block scaling is tight.
        def body():
            t = {"a": jnp.full((32,), 2.0 + comm.rank, jnp.float32),
                 "b": jnp.full((16,), -1.0 - comm.rank, jnp.float32)}

            def loss(tt):
                y = comm.Allreduce_tree(tt, mpi.MPI_SUM, compression="q8")
                return sum(jnp.sum(v) for v in jax.tree.leaves(y))

            return jax.tree.map(np.asarray, jax.grad(loss)(t))

        for g in mpi.run_ranks(body, NR):
            # d(sum of AR(x)) / dx = size on every slot, through the
            # quantized wire (scales are exact powers-free but tight on
            # constants).
            jax.tree.map(
                lambda a: np.testing.assert_allclose(a, float(NR),
                                                     rtol=1e-2), g)

    def test_scope_default_degrades_int_leaves(self):
        def body():
            t = mixed_tree(float(comm.rank + 1))    # has an int32 leaf
            with mpi.config.compression_scope("q8"):
                got = comm.Allreduce_tree(t, mpi.MPI_SUM)
            ref = _perleaf_allreduce(comm, t, compression=False)
            # int leaf must be exact; float leaves carry q8 error
            np.testing.assert_array_equal(np.asarray(got["b"][1]),
                                          np.asarray(ref["b"][1]))
            np.testing.assert_allclose(np.asarray(got["a"]),
                                       np.asarray(ref["a"]), rtol=0.05,
                                       atol=0.05)
            return True

        assert all(mpi.run_ranks(body, NR))

    def test_explicit_codec_on_int_leaf_raises(self):
        def body():
            t = {"i": jnp.arange(4, dtype=jnp.int32)}
            try:
                comm.Allreduce_tree(t, mpi.MPI_SUM, compression="q8")
            except ValueError as e:
                return "requires a floating tensor" in str(e)
            return False

        assert all(mpi.run_ranks(body, NR))

    def test_explicit_false_overrides_scope_in_buckets(self):
        def body():
            t = {"a": jnp.full((8,), 1.0 + comm.rank, jnp.float64)}
            with mpi.config.compression_scope("q8"):
                got = comm.Allreduce_tree(t, mpi.MPI_SUM,
                                          compression=False)
            ref = _perleaf_allreduce(comm, t, compression=False)
            return jax.tree.map(np.asarray, (got, ref))

        for got, ref in mpi.run_ranks(body, NR):
            jax.tree.map(np.testing.assert_array_equal, got, ref)


# ---------------------------------------------------------------------------
# DP lock-step regression (parallel/dp.py single post-fuse scale)
# ---------------------------------------------------------------------------


class TestDPLockstep:
    def test_all_average_tree_bitwise_lockstep_across_ranks(self):
        from mpi4torch_tpu.parallel import all_average_tree

        def body():
            rng = np.random.default_rng(100 + comm.rank)
            t = {"w": jnp.asarray(rng.standard_normal((11, 3))),
                 "b": jnp.asarray(rng.standard_normal(7))}
            return jax.tree.map(np.asarray, all_average_tree(comm, t))

        outs = mpi.run_ranks(body, NR)
        for other in outs[1:]:
            jax.tree.map(np.testing.assert_array_equal, outs[0], other)

    def test_dp_grads_bitwise_lockstep_across_ranks(self):
        from mpi4torch_tpu.parallel import dp_value_and_grad

        rng = np.random.default_rng(7)
        X = jnp.asarray(rng.standard_normal((8 * NR, 3)))
        y = jnp.asarray(rng.standard_normal(8 * NR))
        w0 = jnp.asarray(rng.standard_normal(3))

        def local_loss(w, batch):
            xb, yb = batch
            return jnp.mean((xb @ w - yb) ** 2)

        def body():
            r = comm.rank
            batch = (X[r * 8:(r + 1) * 8], y[r * 8:(r + 1) * 8])
            val, grad = dp_value_and_grad(comm, local_loss)(w0, batch)
            return np.asarray(val), np.asarray(grad)

        outs = mpi.run_ranks(body, NR)
        for val, grad in outs[1:]:
            np.testing.assert_array_equal(val, outs[0][0])
            np.testing.assert_array_equal(grad, outs[0][1])


# ---------------------------------------------------------------------------
# Fused ZeRO building blocks
# ---------------------------------------------------------------------------


class TestZeroFused:
    def test_fused_reduce_scatter_tree_matches_perleaf(self):
        def body():
            rng = np.random.default_rng(comm.rank)
            t = {"w": jnp.asarray(rng.standard_normal((5, 3))),
                 "b": jnp.asarray(rng.standard_normal(9))}
            got = fuse.fused_reduce_scatter_tree(comm, t, mpi.MPI_SUM,
                                                 mean=True)

            def per_leaf(g):
                flat = jnp.asarray(g).reshape(-1)
                per = -(-flat.shape[0] // comm.size)
                padded = jnp.pad(flat,
                                 (0, per * comm.size - flat.shape[0]))
                return comm.Reduce_scatter(padded, mpi.MPI_SUM, 0) \
                    / comm.size

            ref = jax.tree.map(per_leaf, t)
            return jax.tree.map(np.asarray, (got, ref))

        for got, ref in mpi.run_ranks(body, NR):
            jax.tree.map(np.testing.assert_array_equal, got, ref)

    def test_fused_allgather_tree_roundtrip(self):
        from mpi4torch_tpu.parallel import zero

        def body():
            t = {"w": jnp.arange(13, dtype=jnp.float64).reshape(1, 13),
                 "b": jnp.linspace(-1.0, 1.0, 6)}
            shards = zero.zero3_shard_params(comm, t)
            back = zero.zero3_params(comm, shards, t)
            return jax.tree.map(np.asarray, (t, back))

        for t, back in mpi.run_ranks(body, NR):
            jax.tree.map(np.testing.assert_array_equal, t, back)

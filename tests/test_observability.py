"""Named-op observability (SURVEY.md §5 tracing).

The reference's only observability surface is its autograd node names
(e.g. ``MPIAllreduceSumBackward``, csrc/extension.cpp:256-258) showing up
in torch's profiler.  Here every facade op runs under a
``jax.named_scope`` and every SPMD *collective* adjoint under an explicit
``...Backward`` scope, so lowered programs (and hence JAX profiler
traces) carry the spans.  The p2p adjoints are the exception: their
reverse-direction permute comes from XLA's built-in transpose of
``ppermute`` and carries the forward scope's transpose metadata instead
of a dedicated span.  Asserted on the lowered StableHLO text."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm


def _lowered_text(fn, *args):
    # debug_info keeps the loc()/name-stack metadata the profiler uses.
    from mpi4torch_tpu._compat import lowered_text
    return lowered_text(jax.jit(fn).lower(*args), debug_info=True)


class TestNamedScopes:
    def test_forward_and_backward_spans_in_spmd_program(self):
        def prog(x):
            def loss(v):
                y = comm.Allreduce(v, mpi.MPI_SUM)
                z = comm.Allgather(y, 0)
                return jnp.sum(z * z)
            return jax.value_and_grad(loss)(x)

        def wrapped(x):
            return mpi.run_spmd(prog, nranks=4, jit=False)(x)

        import re

        txt = _lowered_text(wrapped, jnp.ones(8))
        # \b-terminated: "mpi4torch.Allreduce\b" cannot be satisfied by the
        # Backward span's substring, so forward-scope removal is caught.
        for span in ("mpi4torch\\.Allreduce\\b", "mpi4torch\\.Allgather\\b",
                     "mpi4torch\\.AllreduceBackward\\b",
                     "mpi4torch\\.AllgatherBackward\\b"):
            assert re.search(span, txt), f"missing span {span}"

    def test_p2p_spans(self):
        def prog(x):
            h = comm.Isend(x, (comm.rank + 1) % comm.size, 0)
            buf = mpi.JoinDummies(jnp.zeros_like(x), [h.dummy])
            y = comm.Recv(buf, (comm.rank - 1) % comm.size, 0)
            ret = comm.Wait(mpi.JoinDummiesHandle(h, [y]))
            return mpi.JoinDummies(x + y, [ret])

        def wrapped(x):
            return mpi.run_spmd(prog, nranks=4, jit=False)(x)

        txt = _lowered_text(wrapped, jnp.ones(4))
        for span in ("mpi4torch.Isend", "mpi4torch.Recv", "mpi4torch.Wait"):
            assert span in txt, f"missing span {span}"

    def test_scopes_transparent_to_eager_semantics(self):
        # The scopes must not change any value/grad (eager backend runs
        # them as plain context managers).
        def body():
            x = jnp.full(3, float(comm.rank) + 1.0)
            y = comm.Allreduce(x, mpi.MPI_SUM)
            g = jax.grad(
                lambda v: jnp.sum(comm.Allreduce(v, mpi.MPI_SUM)))(x)
            return np.asarray(g), np.asarray(y)

        outs = mpi.run_ranks(body, 3)
        for g, y in outs:
            np.testing.assert_array_equal(y, np.full(3, 6.0))
            np.testing.assert_array_equal(g, np.full(3, 3.0))


class TestProfilerTrace:
    def test_trace_captures_op_spans(self, tmp_path):
        # The capture wrapper writes a profile dir; the named-scope
        # discipline it documents is asserted on HLO elsewhere in this
        # file.
        import os

        from mpi4torch_tpu.utils import profiler_trace

        logdir = str(tmp_path / "trace")

        def prog(x):
            return comm.Allreduce(x, mpi.MPI_SUM)

        step = mpi.run_spmd(prog, nranks=2)
        x = jnp.ones(8)
        step(x)                       # compile outside the trace window
        with profiler_trace(logdir):
            jax.block_until_ready(step(x))
        found = []
        for root, _dirs, files in os.walk(logdir):
            found += [f for f in files if f.endswith(".xplane.pb")]
        assert found, f"no xplane files under {logdir}"

    def test_exception_safe(self, tmp_path):
        from mpi4torch_tpu.utils import profiler_trace

        with pytest.raises(RuntimeError, match="boom"):
            with profiler_trace(str(tmp_path / "t")):
                raise RuntimeError("boom")
        # A new trace can start after the failed one (stop_trace ran).
        with profiler_trace(str(tmp_path / "t2")):
            pass

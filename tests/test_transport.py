"""Transport-runtime tests (mpi4torch_tpu.transport; ISSUE 16).

Tier-1 keeps the CHEAP cells: bitwise thread-vs-process parity on a
(3,) world, the worker-pool reuse regression (session-scoped pool,
PID stability, respawn only after a kill), real-SIGKILL/SIGTERM
attribution through the fault-matrix chokepoints, function shipping,
and the registry-sync guard.  The full parity matrix, the (8,)
worlds, and the cross-matrix process reruns live in ``make
transport-smoke`` and the ``slow``-marked classes below.
"""

import os
import pickle
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm
from mpi4torch_tpu import transport
from mpi4torch_tpu.runtime import CommError, RankFailedError
from mpi4torch_tpu.transport import _ship
from mpi4torch_tpu.transport.pool import shared_pool


def _plain_body():
    # NB: a local def, not a module-level function — the test module is
    # not importable inside a worker process, so bodies must travel by
    # value (the documented _ship contract for closures).
    def _plain(rank):
        x = jnp.sin(jnp.arange(64, dtype=jnp.float32)) * (rank + 1)
        return np.asarray(comm.Allreduce(x, mpi.MPI_SUM)), os.getpid()
    return _plain


class TestRegistry:
    def test_both_backends_registered(self):
        assert transport.available_transports() == ["process", "thread"]

    def test_registry_matches_tested_backends(self):
        from mpi4torch_tpu.analyze.registry import transport_problems
        assert transport_problems() == []

    def test_shadowing_refused(self):
        class Impostor(transport.Transport):
            name = "thread"

            def run_ranks(self, *a, **k):
                raise AssertionError

        with pytest.raises(ValueError, match="already registered"):
            transport.register_transport(Impostor)

    def test_unknown_backend_raises(self):
        with pytest.raises(ValueError, match="unknown transport"):
            transport.get_transport("smoke-signals")
        with pytest.raises(ValueError, match="comm_transport"):
            mpi.config.set_comm_transport("smoke-signals")

    @pytest.mark.parametrize("backend", ["thread", "process"])
    def test_size_zero_world_rejected_on_both_backends(self, backend):
        # The thread backend gets this from World.__init__; the process
        # backend has no parent-side World, so its run_ranks entry must
        # enforce the same contract (a size-0 run once returned []).
        with pytest.raises(ValueError, match="World size"):
            mpi.run_ranks(lambda rank: rank, 0, backend=backend)


class TestProcessParity:
    def test_plain_allreduce_bitwise_and_really_multiprocess(self):
        got = mpi.run_ranks(_plain_body(), 3, backend="process")
        oracle = mpi.run_ranks(_plain_body(), 3, backend="thread")
        launcher = os.getpid()
        pids = set()
        for rank in range(3):
            np.testing.assert_array_equal(got[rank][0], oracle[rank][0])
            assert got[rank][1] != launcher
            assert oracle[rank][1] == launcher
            pids.add(got[rank][1])
        assert len(pids) == 3, "ranks shared a worker process"

    def test_transport_scope_sets_default(self):
        with mpi.config.transport_scope("process"):
            assert mpi.config.comm_transport() == "process"
            got = mpi.run_ranks(_plain_body(), 3)
        assert mpi.config.comm_transport() == "thread"
        assert all(got[r][1] != os.getpid() for r in range(3))

    def test_p2p_over_the_wire(self):
        def body(rank):
            if rank == 0:
                comm.Send(jnp.arange(8, dtype=jnp.float32) * 7,
                          dest=1, tag=3)
                return None
            buf = jnp.zeros(8, jnp.float32)
            return np.asarray(comm.Recv(buf, source=0, tag=3))

        got = mpi.run_ranks(body, 2, backend="process")
        np.testing.assert_array_equal(
            got[1], np.arange(8, dtype=np.float32) * 7)


class TestWorkerPoolReuse:
    def test_pool_is_reused_and_pids_stable(self):
        a = mpi.run_ranks(_plain_body(), 3, backend="process")
        before = shared_pool().spawned_total
        b = mpi.run_ranks(_plain_body(), 3, backend="process")
        after = shared_pool().spawned_total
        assert after == before, "fault-free rerun respawned workers"
        assert {r[1] for r in a} == {r[1] for r in b}, \
            "worker PIDs changed across fault-free runs"

    def test_respawn_only_after_kill(self):
        from mpi4torch_tpu.resilience.matrix import run_cell

        mpi.run_ranks(_plain_body(), 3, backend="process")   # pool warm
        before = shared_pool().spawned_total
        rec = run_cell("rank_death", "plain", nranks=3,
                       backend="process")
        assert rec["status"] == "ok", rec["detail"]
        mpi.run_ranks(_plain_body(), 3, backend="process")   # forces the prune
        after = shared_pool().spawned_total
        assert after == before + 1, \
            f"one SIGKILL must cost exactly one respawn " \
            f"({before} -> {after})"


class TestRealSignals:
    def test_rank_death_is_a_real_sigkill_and_attributed(self):
        from mpi4torch_tpu.resilience.matrix import run_cell

        pids_before = set(shared_pool().pids())
        rec = run_cell("rank_death", "plain", nranks=3,
                       backend="process")
        assert rec["status"] == "ok", rec["detail"]
        assert rec["backend"] == "process"
        assert "rank_death" in rec["fired"]
        assert "rank [1]" in rec["detail"] or "rank(s) [1]" \
            in rec["detail"], rec["detail"]
        # the kill was REAL: a worker process from the leased set is gone
        mpi.run_ranks(_plain_body(), 3, backend="process")
        assert pids_before - set(shared_pool().pids()), \
            "no worker process actually died"

    def test_preempt_cell_over_process_backend(self):
        from mpi4torch_tpu.resilience.matrix import run_cell

        rec = run_cell("preempt", "plain", nranks=3, backend="process")
        assert rec["status"] == "ok", rec["detail"]
        assert "preempt" in rec["fired"]

    def test_real_sigterm_lands_on_the_preemption_board(self):
        def body(rank):
            if rank == 1:
                os.kill(os.getpid(), signal.SIGTERM)
            x = jnp.ones(8, jnp.float32) * (rank + 1)
            return np.asarray(comm.Allreduce(x, mpi.MPI_SUM))

        try:
            got = mpi.run_ranks(body, 3, backend="process")
            for r in range(3):
                np.testing.assert_array_equal(
                    got[r], 6.0 * np.ones(8, np.float32))
            from mpi4torch_tpu.resilience import pending_preemptions
            board = transport.external_preemptions()
            assert board.get(1) == 64, board     # default grace
            assert pending_preemptions().get(1) == 64
        finally:
            transport.clear_external_preemption(1)
        assert 1 not in transport.external_preemptions()

    def test_postmortem_ships_from_the_dead_worker(self):
        from mpi4torch_tpu import obs
        from mpi4torch_tpu.resilience import FaultSpec, fault_scope

        spec = FaultSpec("rank_death", rank=1, op="Allreduce", index=0)

        def body(rank):
            x = jnp.ones(8, jnp.float32)
            return comm.Allreduce(x, mpi.MPI_SUM)

        with obs.trace() as t:
            with fault_scope([spec]):
                with pytest.raises(RankFailedError):
                    mpi.run_ranks(body, 3, timeout=30.0,
                                  backend="process")
        pms = t.postmortems
        assert len(pms) == 1, [p.get("error") for p in pms]
        pm = pms[0]
        assert tuple(pm["failed_ranks"]) == (1,)
        # survivors AND the dying rank's own local note all merged into
        # one postmortem, and the survivors' flight-recorder tails
        # crossed the wire (rank 1 died before completing an event, so
        # its tail can legitimately be empty — thread semantics)
        assert sorted(pm["observer_ranks"]) == [0, 1, 2], pm
        assert {0, 2} <= set(pm["tails"])


class TestFunctionShipping:
    def test_closure_roundtrip(self):
        base = 17

        def fn(rank, scale=3):
            return (rank + base) * scale

        out = _ship.loads(_ship.dumps(fn))
        assert out(2) == fn(2) == 57
        assert out(0, scale=1) == 17

    def test_module_and_importable_travel_by_reference(self):
        blob = _ship.dumps({"np": np, "fn": np.arange})
        back = _ship.loads(blob)
        assert back["np"] is np and back["fn"] is np.arange

    def test_comm_world_self_restores(self):
        back = _ship.loads(_ship.dumps(comm))
        assert back is comm

    def test_error_types_pickle_with_attribution(self):
        from mpi4torch_tpu.runtime import (CollectiveMismatchError,
                                           DeadlockError)

        e = RankFailedError("rank 1 died", ranks=(1,))
        e2 = pickle.loads(pickle.dumps(e))
        assert type(e2) is RankFailedError and set(e2.ranks) == {1}
        d = DeadlockError("deadlock", arrived=(0, 1), missing=(2,))
        d2 = pickle.loads(pickle.dumps(d))
        assert set(d2.arrived) == {0, 1} and set(d2.missing) == {2}
        m = CollectiveMismatchError("sig mismatch at op 3")
        m2 = pickle.loads(pickle.dumps(m))
        assert type(m2) is CollectiveMismatchError
        assert "sig mismatch at op 3" in str(m2)
        assert isinstance(d2, CommError)


class TestObsOverTheWire:
    def test_events_from_every_worker_reach_the_parent(self):
        from mpi4torch_tpu import obs

        with obs.trace() as t:
            mpi.run_ranks(_plain_body(), 3, backend="process")
        ranks = {e.rank for e in t.events if not e.bookkeeping}
        assert ranks == {0, 1, 2}
        seqs = [e.seq for e in t.events]
        assert seqs == sorted(seqs), "absorbed events lost seq order"


@pytest.mark.slow
class TestCrossMatrixProcessReruns:
    """Satellite 2 heavyweights: the elastic matrix's rank_death and
    preempt cells, and one chaos cell, rerun over REAL worker
    processes via transport_scope — zero per-subsystem hooks."""

    @pytest.mark.parametrize("kind", ["rank_death", "preempt"])
    def test_elastic_shrink_cells(self, kind):
        from mpi4torch_tpu.elastic.matrix import run_cell

        with mpi.config.transport_scope("process"):
            rec = run_cell(kind, "plain", "shrink")
        assert rec["status"] == "ok", rec["detail"]
        assert kind in rec["fired"]

    def test_chaos_slow_rank_cell(self):
        from mpi4torch_tpu.resilience.chaos import run_chaos_cell

        with mpi.config.transport_scope("process"):
            rec = run_chaos_cell("slow_rank", "plain")
        assert rec["status"] == "ok", rec["detail"]

"""ViT family: non-causal flash attention inside a full model.

Oracle discipline matches the other families: the model forward must
equal a naive dense-softmax re-implementation exactly (the attention
dispatch may pick any path — jnp blockwise here on the CPU harness —
and none may drift from dense attention), DP training must stay in
lock-step and match the single-process global-batch trajectory."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm
from mpi4torch_tpu.models import vit as V
from mpi4torch_tpu.models.transformer import _layer_norm

CFG = V.ViTConfig(image_hw=8, patch=4, d_model=16, n_heads=2,
                  n_layers=2, d_ff=32, num_classes=5)


def images_labels(n, cfg=CFG, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal(
        (n, cfg.image_hw, cfg.image_hw, cfg.channels)), jnp.float64)
    y = jnp.asarray(rng.integers(0, cfg.num_classes, n), jnp.int32)
    return x, y


def naive_forward(cfg, params, images):
    """Dense-softmax reference, structurally independent of the model's
    attention dispatch."""
    x = V.patchify(cfg, images) @ params["patch_proj"] + params["pos"]
    b, s, d = x.shape
    hd = d // cfg.n_heads
    for blk in params["blocks"]:
        y = _layer_norm(x, blk["ln1"])
        qkv = y @ blk["wqkv"]
        q, k, v = (qkv[..., i * d:(i + 1) * d].reshape(
            b, s, cfg.n_heads, hd) for i in range(3))
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(hd, x.dtype))
        probs = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        x = x + att.reshape(b, s, d) @ blk["wo"]
        y = _layer_norm(x, blk["ln2"])
        x = x + jax.nn.gelu(y @ blk["w1"]) @ blk["w2"]
    x = _layer_norm(x, params["ln_f"])
    return jnp.mean(x, axis=1) @ params["head"]


class TestForward:
    def test_matches_naive_dense_oracle(self):
        params = V.init_vit(jax.random.PRNGKey(0), CFG, dtype=jnp.float64)
        x, _ = images_labels(3)
        got = V.forward(CFG, params, x)
        want = naive_forward(CFG, params, x)
        assert got.shape == (3, CFG.num_classes)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-10, atol=1e-12)

    def test_grads_match_naive_oracle(self):
        params = V.init_vit(jax.random.PRNGKey(1), CFG, dtype=jnp.float64)
        x, y = images_labels(2, seed=3)

        def loss(fwd):
            def f(p):
                logp = jax.nn.log_softmax(fwd(CFG, p, x), axis=-1)
                return -jnp.mean(jnp.take_along_axis(
                    logp, y[:, None], axis=-1))
            return f

        g1 = jax.grad(loss(V.forward))(params)
        g2 = jax.grad(loss(naive_forward))(params)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-8, atol=1e-10), g1, g2)

    def test_patchify_raster_order(self):
        cfg = V.ViTConfig(image_hw=4, patch=2, d_model=8, n_heads=1,
                          n_layers=1, d_ff=8, num_classes=2, channels=1)
        img = jnp.arange(16.0).reshape(1, 4, 4, 1)
        p = V.patchify(cfg, img)
        # Patch (0,0) holds rows 0-1 x cols 0-1 of the image.
        np.testing.assert_array_equal(np.asarray(p[0, 0]), [0, 1, 4, 5])
        np.testing.assert_array_equal(np.asarray(p[0, 3]), [10, 11, 14, 15])

    def test_config_validation(self):
        with pytest.raises(ValueError, match="not divisible by patch"):
            V.ViTConfig(image_hw=9, patch=4, d_model=16, n_heads=2,
                        n_layers=1, d_ff=16, num_classes=2)
        with pytest.raises(ValueError, match="not divisible by n_heads"):
            V.ViTConfig(image_hw=8, patch=4, d_model=15, n_heads=2,
                        n_layers=1, d_ff=16, num_classes=2)


class TestDP:
    @pytest.mark.parametrize("nranks", [2, 4])
    def test_lockstep_matches_single_process(self, nranks):
        params0 = V.init_vit(jax.random.PRNGKey(2), CFG, dtype=jnp.float64)
        x, y = images_labels(2 * nranks, seed=5)
        bl = 2

        # Single-process oracle on the global batch.
        ref_p = params0
        for _ in range(2):
            loss, grads = jax.value_and_grad(
                lambda p: V.local_loss(CFG, p, (x, y)))(ref_p)
            ref_p = jax.tree.map(lambda p, g: p - 0.1 * g, ref_p, grads)

        def body():
            p = params0
            r = comm.rank
            batch = (x[r * bl:(r + 1) * bl], y[r * bl:(r + 1) * bl])
            for _ in range(2):
                loss, p = V.dp_grad_train_step(comm, CFG, p, batch, lr=0.1)
            return loss, p["head"]

        outs = mpi.run_ranks(body, nranks)
        h0 = np.asarray(outs[0][1])
        assert all(np.array_equal(h0, np.asarray(h)) for _, h in outs[1:])
        # Mean-of-local-means == global mean only with equal shards (they
        # are); the distributed trajectory then matches single-process to
        # reassociation noise.
        np.testing.assert_allclose(h0, np.asarray(ref_p["head"]),
                                   rtol=1e-9, atol=1e-11)


class TestPatchParallel:
    def test_sp_forward_matches_single_process(self):
        # Non-causal ring attention over patch shards (eager backend):
        # 4 ranks each hold n_patches/4 contiguous patches; logits must
        # equal the single-process forward exactly (ring merges are the
        # same online-softmax algebra, f64 here).
        cfg = V.ViTConfig(image_hw=8, patch=2, d_model=16, n_heads=2,
                          n_layers=2, d_ff=32, num_classes=5)
        params = V.init_vit(jax.random.PRNGKey(4), cfg, dtype=jnp.float64)
        x, _ = images_labels(2, cfg, seed=9)
        want = V.forward(cfg, params, x)
        patches = V.patchify(cfg, x)
        sl = cfg.n_patches // 4

        def body():
            r = comm.rank
            local = patches[:, r * sl:(r + 1) * sl]
            # patch_offset intentionally omitted: derived from the rank.
            return V.forward_patches(cfg, params, local, comm_sp=comm)

        outs = mpi.run_ranks(body, 4)
        for o in outs:
            np.testing.assert_allclose(np.asarray(o), np.asarray(want),
                                       rtol=1e-10, atol=1e-12)

    @pytest.mark.slow  # heavyweight compile/run; TPU-manual lane (tier-1 budget)
    def test_sp_grads_flow_and_match(self):
        cfg = V.ViTConfig(image_hw=4, patch=2, d_model=16, n_heads=2,
                          n_layers=1, d_ff=16, num_classes=3)
        params = V.init_vit(jax.random.PRNGKey(6), cfg, dtype=jnp.float64)
        x, _ = images_labels(2, cfg, seed=11)
        patches = V.patchify(cfg, x)
        sl = cfg.n_patches // 2

        def gl(fwd):
            return jax.grad(lambda p: jnp.sum(fwd(p) ** 2))(params)

        want = gl(lambda p: V.forward(cfg, p, x))

        def body():
            r = comm.rank
            local = patches[:, r * sl:(r + 1) * sl]
            # Per-rank backward seeds 1 on every rank; the replicated
            # logits make the sharded gradient = size x the oracle for
            # replicated params after the ring adjoint sums rank
            # contributions -- divide by size (doc/examples.rst:46-65
            # discipline).
            g = gl(lambda p: V.forward_patches(cfg, p, local,
                                               comm_sp=comm))
            return jax.tree.map(
                lambda a: comm.Allreduce(a, mpi.MPI_SUM) / comm.size, g)

        outs = mpi.run_ranks(body, 2)
        for g in outs:
            jax.tree.map(lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-8, atol=1e-10),
                g, want)

    def test_sp_forward_spmd_mesh_matches(self):
        # Same check through the SPMD mesh backend (the performance
        # path): ring transport lowers to collective_permute; the
        # rank-derived patch offset is a traced value here.
        cfg = V.ViTConfig(image_hw=8, patch=2, d_model=16, n_heads=2,
                          n_layers=1, d_ff=32, num_classes=4)
        params = V.init_vit(jax.random.PRNGKey(8), cfg, dtype=jnp.float32)
        x, _ = images_labels(2, cfg, seed=13)
        x = x.astype(jnp.float32)
        want = V.forward(cfg, params, x)
        patches = V.patchify(cfg, x)
        NR = 4
        sl = cfg.n_patches // NR

        def body(patches, params):
            c = mpi.COMM_WORLD
            local = jax.lax.dynamic_slice_in_dim(
                patches, jnp.asarray(c.rank) * sl, sl, 1)
            return V.forward_patches(cfg, params, local, comm_sp=c)

        out = mpi.run_spmd(body, nranks=NR)(patches, params)
        for r in range(NR):
            np.testing.assert_allclose(np.asarray(out)[r], np.asarray(want),
                                       rtol=2e-5, atol=2e-6)

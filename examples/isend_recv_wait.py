"""Nonblocking ring exchange with differentiable dependency tokens.

The TPU-native port of the reference's second example (reference:
examples/isend-recv-wait.py): each rank sends a value to its right
neighbor and receives from its left neighbor, with the
JoinDummies/JoinDummiesHandle token discipline encoding the orderings the
AD engine cannot see on its own (reference doc/basic_usage.rst:184-197).
The backward pass routes each gradient over the ring in the *reverse*
direction automatically.

Run:  python examples/isend_recv_wait.py [nranks]
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

if os.environ.get("MPI4TORCH_TPU_REAL_DEVICES") != "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

import mpi4torch_tpu as mpi

comm = mpi.COMM_WORLD


def main():
    def program(a):
        handle = comm.Isend(a, (comm.rank + 1) % comm.size, 0)
        recvbuffer = mpi.JoinDummies(jnp.empty_like(a), [handle.dummy])
        b = comm.Recv(recvbuffer, (comm.rank - 1 + comm.size) % comm.size, 0)
        wait_ret = comm.Wait(mpi.JoinDummiesHandle(handle, [b]))
        res = mpi.JoinDummies(a + b, [wait_ret])
        return res.sum(), res

    a = jnp.asarray([1.0 + comm.rank])
    (_, res), grad = jax.value_and_grad(program, has_aux=True)(a)
    print(f"rank {comm.rank}: res = {np.asarray(res)}, "
          f"a.grad = {np.asarray(grad)}")
    return np.asarray(res), np.asarray(grad)


if __name__ == "__main__":
    nranks = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    results = mpi.run_ranks(main, nranks)
    for r, (res, grad) in enumerate(results):
        left = (r - 1 + nranks) % nranks
        assert res[0] == (1.0 + r) + (1.0 + left)
        # a_r reaches its own output and the right neighbor's output
        assert grad[0] == 2.0
    print(f"OK: ring values and ring-routed gradients correct on "
          f"{nranks} ranks")

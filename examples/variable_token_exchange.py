"""Variable-length token exchange: butterfly p2p + ragged Alltoall on the
compiled mesh backend.

Two capabilities the reference exposes through raw MPI that this example
exercises TPU-natively under ONE compiled SPMD program:

1. **Arbitrary static p2p permutations** (reference: any dest/source
   rank, csrc/extension.cpp:1071-1157): a butterfly exchange
   ``dest = rank ^ 1`` — the classic recursive-doubling building block —
   written with the same Isend/JoinDummies/Recv/Wait token discipline as
   the ring example, lowering to exactly one ``collective_permute``.
2. **Per-rank-varying segment sizes on the dense collectives**
   (reference: MPI_Alltoallv-style varying ``numelem``,
   csrc/extension.cpp:947-979): every rank holds a *different* number of
   valid tokens (static per-rank counts over a capacity-padded buffer)
   and redistributes them into equal-ish contiguous spans via
   ``Alltoall(..., numelem=new_counts, current_numelem=old_counts)`` —
   the load-balancing step of an expert-parallel dispatch.

Differentiability is asserted end to end: the loss pulls gradients back
through the redistribution AND the butterfly (padding slots provably get
zero gradient).

Run:  python examples/variable_token_exchange.py [nranks]
      (nranks must be even: the ``rank ^ 1`` butterfly pairs ranks)
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

if os.environ.get("MPI4TORCH_TPU_REAL_DEVICES") != "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

import mpi4torch_tpu as mpi

comm = mpi.COMM_WORLD

D = 4  # token feature width


def balanced_counts(old):
    """Rebalance a lopsided partition into spans differing by <= 1."""
    total, n = sum(old), len(old)
    base, extra = divmod(total, n)
    return tuple(base + (1 if r < extra else 0) for r in range(n))


def exchange(x0, old_counts, new_counts, cap):
    """One compiled step: butterfly-mix each rank's valid tokens with its
    partner, then repartition the global token axis to ``new_counts``."""
    # Rank-stamped tokens: row i of rank r = (global token id, r, ...).
    offs = np.concatenate([[0], np.cumsum(old_counts)])
    gids = jnp.take(jnp.asarray(offs[:-1], jnp.float64),
                    jnp.asarray(comm.rank + 0)) + jnp.arange(cap)
    tokens = (gids[:, None] + jnp.zeros((cap, D))) * x0

    # 1. Butterfly: swap token blocks with partner rank ^ 1 (capacity-
    #    uniform on the wire; validity travels with the counts below).
    h = comm.Isend(tokens, comm.rank ^ 1, 0)
    mixed = comm.Recv(mpi.JoinDummies(jnp.empty_like(tokens), [h.dummy]),
                      comm.rank ^ 1, 0)
    mixed = mpi.JoinDummies(mixed, [comm.Wait(h)])
    # After the swap, rank r holds its PARTNER's tokens — and therefore
    # the partner's valid count.
    swapped = tuple(old_counts[r ^ 1] for r in range(len(old_counts)))

    # 2. Ragged repartition of the global token axis to the balanced
    #    spans (MPI_Alltoallv analogue; static count tuples, one program).
    spans = comm.Alltoall(mixed, 0, 0, new_counts,
                          current_numelem=swapped)
    return tokens, spans


def main():
    n = comm.size
    old = tuple(((3 * r + 1) % (n + 2)) + 1 for r in range(n))  # lopsided
    new = balanced_counts(old)
    cap = max(max(old), max(new))

    def fwd(x0):
        return exchange(x0, old, new, cap)

    tokens, spans = fwd(jnp.ones(()))

    # Gradient through butterfly + repartition: every VALID token in the
    # global axis contributes exactly once to sum(spans); padding never.
    g = jax.grad(lambda x0: fwd(x0)[1].sum())(jnp.ones(()))
    return tokens, spans, g


if __name__ == "__main__":
    nranks = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    if nranks % 2:
        sys.exit(f"nranks must be even (the rank ^ 1 butterfly pairs "
                 f"ranks); got {nranks}")
    tokens, spans, grads = mpi.run_spmd(main, nranks=nranks)()
    # Recompute the static metadata for the assertions.
    old = tuple(((3 * r + 1) % (nranks + 2)) + 1 for r in range(nranks))
    new = balanced_counts(old)
    offs = np.concatenate([[0], np.cumsum(new)])
    swapped_order = []   # global ids in post-butterfly axis order
    oo = np.concatenate([[0], np.cumsum(old)])
    for r in range(nranks):
        p = r ^ 1
        swapped_order.extend(range(oo[p], oo[p] + old[p]))
    for r in range(nranks):
        span = np.asarray(spans)[r, :new[r], 0]
        want = np.asarray(swapped_order[offs[r]:offs[r + 1]], float)
        np.testing.assert_array_equal(span, want)
        assert (np.asarray(spans)[r, new[r]:] == 0).all()
        # Per-rank gradient oracle: rank r's x0 feeds its own valid
        # tokens (ids oo[r]..oo[r]+old[r]-1), each reaching exactly one
        # valid span slot somewhere — so dL/dx0_r = D * sum(those ids),
        # delivered back through the adjoint repartition AND the reverse
        # butterfly.  Padding contributes exactly nothing.
        ids = range(oo[r], oo[r] + old[r])
        np.testing.assert_allclose(np.asarray(grads)[r], D * sum(ids))
    print(f"OK: {nranks} ranks, counts {old} -> {new}, "
          f"butterfly+ragged repartition verified; per-rank grads match "
          f"the token-id oracle")

"""ZeRO-1 and ZeRO-3: data-parallel training with sharded state.

Plain DP replicates Adam's two moment tensors on every rank — 2x the
parameter bytes of pure redundancy.  ZeRO stage 1 shards them: each
rank's un-reduced local gradients are ``Reduce_scatter``'d (the native
``psum_scatter`` under SPMD — half an allreduce on the wire), each rank
updates only its 1/N parameter shard, and an ``Allgather``
re-replicates the parameters.  Per-step wire cost equals ONE gradient
allreduce (its two halves), while optimizer HBM drops by the rank
count — and because element-wise optimizers act per-parameter, the
final parameters are EXACTLY the plain replicated-DP result, verified
here against a single-process oracle on every rank and leaf.

Stage 3 additionally shards the PARAMETERS between steps: each rank
persists only a 1/N flat shard, the forward gathers on use, and the
gradient comes back sharded through the Allgather ADJOINT (its
reduce-scatter) — no explicit DP reduction anywhere in the program.
Same oracle, same exactness, parameter + optimizer HBM both 1/N.

Run:  python examples/zero_sharded_optimizer.py [nranks]
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

if os.environ.get("MPI4TORCH_TPU_REAL_DEVICES") != "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np
import optax

import mpi4torch_tpu as mpi
from mpi4torch_tpu.parallel import (zero3_init, zero3_params, zero3_step,
                                    zero_init, zero_step)

N, D, STEPS, LR = 64, 8, 30, 1e-1


def make_problem():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((N, D)))
    w_true = jnp.asarray(rng.standard_normal((D,)))
    y = x @ w_true + 0.05 * jnp.asarray(rng.standard_normal((N,)))
    return x, y


def local_loss(p, xl, yl):
    return jnp.sum((yl - xl @ p["w"] - p["b"]) ** 2)


def main(nranks: int = 4):
    if N % nranks != 0:
        raise SystemExit(
            f"nranks must divide the dataset size {N}, got {nranks}")
    x, y = make_problem()
    params0 = {"w": jnp.zeros((D,)), "b": jnp.zeros(())}
    opt = optax.adam(LR)
    shard = N // nranks

    # Single-process oracle: Adam on the rank-mean loss.
    ref_p, ref_s = params0, opt.init(params0)
    for _ in range(STEPS):
        g = jax.grad(lambda p: local_loss(p, x, y) / nranks)(ref_p)
        u, ref_s = opt.update(g, ref_s, ref_p)
        ref_p = jax.tree.map(jnp.add, ref_p, u)

    def body():
        comm = mpi.COMM_WORLD
        xl = x[comm.rank * shard:(comm.rank + 1) * shard]
        yl = y[comm.rank * shard:(comm.rank + 1) * shard]
        params = params0
        state = zero_init(comm, opt, params)   # 1/N of the Adam moments
        for _ in range(STEPS):
            g = jax.grad(lambda p: local_loss(p, xl, yl))(params)
            params, state = zero_step(comm, opt, params, g, state)
        return params

    outs = mpi.run_ranks(body, nranks)
    for r, got in enumerate(outs):
        # Every leaf, every rank — "b" is the scalar leaf that exercises
        # the shard zero-padding path (() padded to nranks slots).
        for k in ("w", "b"):
            assert np.allclose(np.asarray(got[k]), np.asarray(ref_p[k]),
                               rtol=1e-9), \
                f"rank {r} leaf {k} diverged from oracle"
    print(f"{nranks} ranks, Adam state sharded 1/{nranks}: final params "
          f"match the replicated-DP oracle on every rank")

    # ZeRO-3: the same training run with the parameters themselves
    # sharded between steps — note there is NO collective in this loop
    # body besides the gather inside zero3_step (the reduction is its
    # adjoint).
    def body3():
        comm = mpi.COMM_WORLD
        xl = x[comm.rank * shard:(comm.rank + 1) * shard]
        yl = y[comm.rank * shard:(comm.rank + 1) * shard]
        p_shards, state = zero3_init(comm, opt, params0)
        for _ in range(STEPS):
            _, p_shards, state = zero3_step(
                comm, opt, p_shards, params0,
                lambda p: local_loss(p, xl, yl), state)
        return zero3_params(comm, p_shards, params0)

    outs3 = mpi.run_ranks(body3, nranks)
    for r, got in enumerate(outs3):
        for k in ("w", "b"):
            assert np.allclose(np.asarray(got[k]), np.asarray(ref_p[k]),
                               rtol=1e-9), \
                f"zero3: rank {r} leaf {k} diverged from oracle"
    print(f"ZeRO-3: params sharded 1/{nranks} between steps — same "
          f"oracle-exact result")
    print(f"w = {np.asarray(outs[0]['w']).round(3)}")
    return outs[0], ref_p


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 4)

"""Data-parallel polynomial regression with L-BFGS.

The TPU-native port of the reference's canonical example (reference:
examples/simple_linear_regression.py): each rank holds a chunk of the data;
the loss function contains exactly two communication calls —

  1. ``Allreduce(params, MPI_SUM) / size`` — averages the (replicated)
     parameters so every rank's optimizer instance stays arithmetically
     identical; its adjoint divides by size again, making the total
     gradients pure sums and the run rank-count-invariant (the subtlety
     documented at reference doc/examples.rst:46-65).
  2. ``Allreduce(localloss, MPI_SUM)`` — the global loss.

Run:  python examples/simple_linear_regression.py [nranks]
(the thread-SPMD launcher replaces ``mpirun -np N``)
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

if os.environ.get("MPI4TORCH_TPU_REAL_DEVICES") != "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

import mpi4torch_tpu as mpi
from mpi4torch_tpu.utils import LBFGS

comm = mpi.COMM_WORLD


def some_parametrized_function(inp, params):
    return (params[2] * inp + params[1]) * inp + params[0]


def main():
    rng = np.random.default_rng(42)

    num_points = 10000
    chunk_size = num_points // comm.size
    rest = num_points % comm.size
    if comm.rank < rest:
        chunk_size += 1
        offset = chunk_size * comm.rank
    else:
        offset = chunk_size * comm.rank + rest

    xinput = jnp.asarray(
        2.0 * rng.random(num_points)[offset:offset + chunk_size])

    gen_params = jnp.asarray([0.1, 1.0, -2.0])
    youtput = some_parametrized_function(xinput, gen_params)

    def lossfunction(params):
        # average initial params to bring all ranks on the same page
        params = comm.Allreduce(params, mpi.MPI_SUM) / comm.size

        # compute local loss
        localloss = jnp.sum(jnp.square(
            youtput - some_parametrized_function(xinput, params)))

        # sum up the loss among all ranks
        return comm.Allreduce(localloss, mpi.MPI_SUM)

    params = jnp.arange(3, dtype=jnp.float64)

    # L-BFGS needs only one outer step for so few parameters
    optimizer = LBFGS(max_iter=30)
    params, loss = optimizer.step(lossfunction, params)

    # only print output on rank 0
    if comm.rank == 0:
        print("Loss  : ", float(loss))
        print("Final parameters: ", np.asarray(params))
    return np.asarray(params), float(loss)


if __name__ == "__main__":
    nranks = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    results = mpi.run_ranks(main, nranks)
    params0, loss0 = results[0]
    assert all(np.array_equal(params0, p) for p, _ in results), \
        "ranks diverged"
    assert np.allclose(params0, [0.1, 1.0, -2.0], atol=1e-5), params0
    print(f"OK: {nranks} ranks converged identically to the generating "
          "parameters")

"""Expert-parallel MoE training over the differentiable Alltoall.

The EP demo completing the §2.5 strategy-example matrix: each rank owns
``n_experts/size`` experts and a shard of the tokens; ``moe_ffn``
dispatches tokens to their routed expert's rank over the differentiable
``Alltoall`` (the reference's per-rank-varying-count primitive is
exactly this token exchange, SURVEY.md §2.5 EP row), computes the local
experts, and combines the outputs back — with gradients riding the
reverse Alltoall.

The script trains a one-layer MoE regressor and checks, at every step,
that the distributed loss equals the single-device oracle
(``moe_ffn_dense``: identical routing/capacity semantics, all experts
local) on the full batch — token-for-token EP correctness while the
router itself is learning.

Run:  python examples/expert_parallel_moe.py [nranks]
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

if os.environ.get("MPI4TORCH_TPU_REAL_DEVICES") != "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

import mpi4torch_tpu as mpi
from mpi4torch_tpu.parallel import init_moe, moe_ffn, moe_ffn_dense

comm = mpi.COMM_WORLD

D, D_FF, T_LOCAL, N_EXP_PER_RANK = 8, 16, 16, 2
CAPACITY, N_STEPS, LR, AUX = 24, 25, 0.05, 0.01


def make_problem(size: int, seed=0):
    n_experts = N_EXP_PER_RANK * size
    params = init_moe(jax.random.PRNGKey(seed), n_experts, D, D_FF,
                       dtype=jnp.float64)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((size * T_LOCAL, D)))
    y = jnp.asarray(np.tanh(rng.standard_normal((size * T_LOCAL, D))))
    return params, x, y


def main():
    rank, size = int(comm.rank), comm.size
    params, x, y = make_problem(size)
    lo = rank * T_LOCAL
    xs, ys = x[lo:lo + T_LOCAL], y[lo:lo + T_LOCAL]

    def dense_loss(p):
        # The EP capacity contract is PER SOURCE RANK (each rank's token
        # shard routes into its own C slots per expert — tests/
        # test_moe.py), so the oracle applies the dense layer to each
        # shard independently and averages the per-shard aux losses.
        total = 0.0
        aux_sum = 0.0
        for r in range(size):
            xr = x[r * T_LOCAL:(r + 1) * T_LOCAL]
            yr = y[r * T_LOCAL:(r + 1) * T_LOCAL]
            out, aux = moe_ffn_dense(xr, p, CAPACITY)
            total = total + jnp.sum((out + xr - yr) ** 2)
            aux_sum = aux_sum + aux
        return total / x.shape[0] + AUX * aux_sum / size

    def ep_loss(p):
        # Token shard in, replicated global loss out: residual sums and
        # the shard-local aux are both Allreduce'd, mirroring the oracle.
        out, aux = moe_ffn(comm, xs, p, CAPACITY)
        local = jnp.sum((out + xs - ys) ** 2)
        total = comm.Allreduce(local, mpi.MPI_SUM) / x.shape[0]
        aux_mean = comm.Allreduce(aux, mpi.MPI_SUM) / size
        return total + AUX * aux_mean

    losses = []
    for step in range(N_STEPS):
        ref_l, ref_g = jax.value_and_grad(dense_loss)(params)
        l, g = jax.value_and_grad(ep_loss)(params)
        np.testing.assert_allclose(float(l), float(ref_l), rtol=1e-10,
                                   atol=1e-12)
        # Sum-over-ranks semantics: every rank seeds 1, so the program
        # differentiates size x loss — and expert leaves are sharded
        # inside moe_ffn, so each rank's grad covers only ITS experts'
        # slice (the gate, used by every rank, arrives complete).  The
        # uniform identity (same as the driver dryrun's): summing raw
        # grads over ranks gives size x the oracle gradient for EVERY
        # leaf, so one Allreduce + /size recovers the exact dense
        # gradient, replicated.
        g = jax.tree.map(
            lambda a: comm.Allreduce(a, mpi.MPI_SUM) / size, g)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-8, atol=1e-10),
            g, ref_g)
        params = jax.tree.map(lambda a, b: a - LR * b, params, g)
        losses.append(float(l))
    assert losses[-1] < 0.9 * losses[0], (losses[0], losses[-1])
    if rank == 0:
        print(f"rank 0: EP == dense oracle each step; loss "
              f"{losses[0]:.4f} -> {losses[-1]:.4f}")
    return losses


if __name__ == "__main__":
    nranks = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    outs = mpi.run_ranks(main, nranks)
    assert all(o == outs[0] for o in outs)
    print(f"OK: {nranks} ranks, loss {outs[0][0]:.4f} -> {outs[0][-1]:.4f}")

"""Train a tiny character LM data-parallel, then decode with a KV cache.

End-to-end inference demo for the flagship transformer: the model is
trained for a few steps with the reference's two-Allreduce DP recipe
(Allreduce parameter averaging + Allreduce'd loss — the adjoint keeps
every rank's optimizer in lock-step, reference doc/examples.rst:24-65)
on a memorizable token pattern, then text is generated two ways:

* ``models.transformer.generate`` — batched one-pass prefill + a single
  compiled ``lax.scan`` of KV-cache ``decode_step`` calls (the serving
  path: under GQA the cache holds only ``n_kv_heads`` heads, and
  ``attn_window`` bounds each step's attention reach);
* a repeated-full-forward greedy loop (the oracle).

Both must emit identical tokens — the same teacher-forcing-equivalence
property tests/test_transformer.py::TestDecoding asserts.

Run:  python examples/generate_kv_cache.py [nranks]
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

if os.environ.get("MPI4TORCH_TPU_REAL_DEVICES") != "1":
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

import mpi4torch_tpu as mpi
from mpi4torch_tpu.models import transformer as T
from mpi4torch_tpu.parallel import all_average_tree

CFG = T.TransformerConfig(vocab=16, d_model=32, n_heads=4, n_layers=2,
                          d_ff=64, max_seq=32, n_kv_heads=2, attn_window=8)
STEPS, BATCH, LR = 150, 8, 3e-2


def make_data(key):
    # A deterministic repeating pattern: next token = (tok + 1) % 8 — easy
    # to memorize, and verifiably learned when generation continues it.
    start = jax.random.randint(key, (BATCH, 1), 0, 8)
    ramp = jnp.arange(CFG.max_seq, dtype=jnp.int32)[None, :]
    return ((start + ramp) % 8).astype(jnp.int32)


def train(nranks: int):
    """DP training: each rank holds a batch shard; the two-Allreduce
    recipe keeps per-rank SGD trajectories bit-identical."""
    tokens = make_data(jax.random.PRNGKey(1))
    params0 = T.init_transformer(jax.random.PRNGKey(0), CFG,
                                 dtype=jnp.float64)
    shard = BATCH // nranks

    def body():
        comm = mpi.COMM_WORLD
        local = tokens[comm.rank * shard:(comm.rank + 1) * shard]
        params = params0

        def loss_fn(p):
            p = all_average_tree(comm, p) if comm.size > 1 else p
            loss = T.lm_loss(CFG, p, local)
            return comm.Allreduce(loss, mpi.MPI_SUM) / comm.size \
                if comm.size > 1 else loss

        for _ in range(STEPS):
            loss, grads = jax.value_and_grad(loss_fn)(params)
            params = jax.tree.map(lambda p, g: p - LR * g, params, grads)
        return float(loss), params

    results = mpi.run_ranks(body, nranks)
    loss, params = results[0]
    for other_loss, other in results[1:]:
        assert other_loss == loss, "DP ranks diverged"
    return loss, params


def main(nranks: int = 4):
    loss, params = train(nranks)
    print(f"trained {STEPS} steps on {nranks} ranks: loss {loss:.4f}")

    prompt = jnp.asarray([[3, 4, 5, 6]], jnp.int32)
    out = T.generate(CFG, params, prompt, n_new=12, dtype=jnp.float64)

    # Oracle: repeated full forwards.
    seq = prompt
    for _ in range(12):
        nxt = jnp.argmax(T.forward(CFG, params, seq)[:, -1], axis=-1)
        seq = jnp.concatenate([seq, nxt[:, None].astype(seq.dtype)], axis=1)
    assert (np.asarray(out) == np.asarray(seq)).all(), \
        "KV-cache decode diverged from the full-forward oracle"

    gen = np.asarray(out[0, 4:])
    want = (np.asarray(prompt[0, -1]) + 1 + np.arange(12)) % 8
    learned = (gen == want).mean()
    print(f"prompt {np.asarray(prompt[0])} -> generated {gen}")
    print(f"pattern continuation accuracy: {learned:.0%}")
    return gen, want


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 4)

"""Tensor-parallel MLP training: Megatron column->row sharding.

The TP demo completing the strategy-example matrix (DP:
simple_linear_regression / resnet_cifar_dp, p2p: isend_recv_wait, CP/SP:
ring_attention_longcontext, PP: pipeline_training, stencil:
halo_exchange_stencil).  The reference ships TP only as primitives —
its axis-aware Gather/Allgather/Scatter are the column/row-parallel glue
(SURVEY.md §2.5 TP row) — and this framework packages the pattern:

* ``w1`` column-sharded, ``w2`` row-sharded (``shard_axis``);
* one ``Allreduce`` forward per MLP (``tp_mlp``), its adjoint the one
  backward collective;
* per-rank grads are exact shard grads, so a plain SGD step per rank
  trains the sharded model in lock-step with the single-device oracle
  (asserted each step at near machine precision).

Run:  python examples/tensor_parallel_mlp.py [nranks]
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

if os.environ.get("MPI4TORCH_TPU_REAL_DEVICES") != "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

import mpi4torch_tpu as mpi
from mpi4torch_tpu.parallel import shard_axis, tp_mlp

comm = mpi.COMM_WORLD

D_IN, D_FF, B, N_STEPS, LR = 8, 32, 16, 15, 0.1


def make_problem(seed=0):
    rng = np.random.default_rng(seed)
    params = {
        "w1": jnp.asarray(rng.standard_normal((D_IN, D_FF)) / np.sqrt(D_IN)),
        "b1": jnp.zeros((D_FF,)),
        "w2": jnp.asarray(rng.standard_normal((D_FF, D_IN)) / np.sqrt(D_FF)),
        "b2": jnp.zeros((D_IN,)),
    }
    x = jnp.asarray(rng.standard_normal((B, D_IN)))
    y = jnp.asarray(np.tanh(rng.standard_normal((B, D_IN))))
    return params, x, y


def dense_loss(params, x, y):
    h = jax.nn.gelu(x @ params["w1"] + params["b1"])
    return jnp.mean((h @ params["w2"] + params["b2"] - y) ** 2)


def main():
    params, x, y = make_problem()

    # Single-device oracle trajectory.
    ref = params
    ref_losses = []
    for _ in range(N_STEPS):
        l, g = jax.value_and_grad(dense_loss)(ref, x, y)
        ref = jax.tree.map(lambda a, b: a - LR * b, ref, g)
        ref_losses.append(float(l))

    # Tensor-parallel run: every rank owns a feature shard of w1/b1/w2
    # and the replicated b2.
    local = {
        "w1": shard_axis(comm, params["w1"], 1),
        "b1": shard_axis(comm, params["b1"], 0),
        "w2": shard_axis(comm, params["w2"], 0),
        "b2": params["b2"],
    }

    def tp_loss(p):
        out = tp_mlp(comm, x, p["w1"], p["b1"], p["w2"], p["b2"])
        return jnp.mean((out - y) ** 2)

    losses = []
    n = comm.size
    for step in range(N_STEPS):
        l, g = jax.value_and_grad(tp_loss)(local)
        # Gradient semantics (the reference's "pure sums over ranks"
        # discipline, doc/examples.rst:46-65): every rank's backward
        # seeds 1, so the program differentiates n x loss.  Shard params
        # (w1/b1/w2) sit upstream of the row-parallel Allreduce, whose
        # adjoint sums the n identical cotangents -> their grads arrive
        # n x already; the replicated b2 sits after it, so each rank
        # holds only its replica's partial -> Allreduce completes the
        # sum.  One uniform LR/n then reproduces the single-device
        # trajectory exactly (asserted below every step).
        g = dict(g, b2=comm.Allreduce(g["b2"], mpi.MPI_SUM))
        local = jax.tree.map(lambda a, b: a - (LR / n) * b, local, g)
        losses.append(float(l))
        np.testing.assert_allclose(float(l), ref_losses[step],
                                   rtol=1e-10, atol=1e-12)

    # Final sharded params equal the oracle's corresponding shards —
    # every leaf: both feature shards, the sharded bias, and the
    # replicated bias.
    r = int(comm.rank)
    f_lo = r * (D_FF // n)
    sl = slice(f_lo, f_lo + D_FF // n)
    np.testing.assert_allclose(np.asarray(local["w1"]),
                               np.asarray(ref["w1"][:, sl]), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(local["b1"]),
                               np.asarray(ref["b1"][sl]), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(local["w2"]),
                               np.asarray(ref["w2"][sl, :]), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(local["b2"]),
                               np.asarray(ref["b2"]), rtol=1e-10)
    if r == 0:
        print(f"rank 0: TP trajectory matches the single-device oracle; "
              f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    return losses


if __name__ == "__main__":
    nranks = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    outs = mpi.run_ranks(main, nranks)
    assert all(o == outs[0] for o in outs)
    print(f"OK: {nranks} ranks, loss {outs[0][0]:.4f} -> {outs[0][-1]:.4f}")

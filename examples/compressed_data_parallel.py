"""Data-parallel training with compressed gradient sync.

The shipped linear-regression recipe (examples/simple_linear_regression.py)
with the gradient AllReduce riding the quantized wire
(doc/compression.md): three runs of the same SGD loop —

  1. exact fp32 gradient sync (the baseline),
  2. ``compression="q8_ef"`` — block-scaled int8 with an in-call
     error-feedback round (~2x fewer bytes on the wire, second-order
     error),
  3. single-round ``q8`` (~3.94x fewer bytes) with the residual carried
     ACROSS steps via ``compress.ef_init``/``ef_allreduce`` (EF-SGD).

All three converge to the same loss (the acceptance gate in
tests/test_compress.py requires the compressed runs within 2% of fp32);
the printout shows the final losses and the per-step gradient bytes each
variant puts on the wire.

Run:  python examples/compressed_data_parallel.py [nranks]
(the thread-SPMD launcher replaces ``mpirun -np N``; the identical loss
function runs compiled over a TPU mesh under ``mpi.run_spmd``)
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

if os.environ.get("MPI4TORCH_TPU_REAL_DEVICES") != "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

import mpi4torch_tpu as mpi
from mpi4torch_tpu.compress import ef_allreduce, ef_init, get_codec

comm = mpi.COMM_WORLD

NUM_POINTS = 512
STEPS = 150
LR = 0.1


def some_parametrized_function(inp, params):
    return (params[2] * inp + params[1]) * inp + params[0]


def _shard(rank, size):
    rng = np.random.default_rng(42)
    x = 2.0 * rng.random(NUM_POINTS)
    gen = np.asarray([0.1, 1.0, -2.0])
    y = some_parametrized_function(x, gen) \
        + 0.05 * rng.standard_normal(NUM_POINTS)
    n = NUM_POINTS // size
    lo = rank * n
    return jnp.asarray(x[lo:lo + n]), jnp.asarray(y[lo:lo + n])


def train(compression=False, stateful_ef=False):
    """One SGD run; returns (final global loss, params)."""
    xs, ys = _shard(comm.rank, comm.size)

    def local_loss(p):
        pred = some_parametrized_function(xs, p)
        return jnp.mean(jnp.square(ys - pred)) / comm.size

    params = jnp.zeros(3, jnp.float64)
    resid = ef_init(params)
    for _ in range(STEPS):
        g = jax.grad(local_loss)(params)
        if stateful_ef:
            # Residual carried across steps: single-round q8 wire, the
            # untransmitted error re-enters next step's gradient.
            g, resid = ef_allreduce(comm, g, resid, compression=compression)
        else:
            g = comm.Allreduce(g, mpi.MPI_SUM, compression=compression)
        params = params - LR * g
    return float(comm.Allreduce(local_loss(params), mpi.MPI_SUM)), params


def main():
    fp32_loss, fp32_params = train(compression=False)
    ef_loss, _ = train(compression="q8_ef")
    st_loss, _ = train(compression="q8", stateful_ef=True)

    if comm.rank == 0:
        # Wire accounting at a model-scale gradient (1 Mi f32 elements);
        # this example's 3-entry gradient is block-padding-dominated and
        # would misrepresent the asymptotic ratio.
        nelem = 1 << 20
        fp32_bytes = nelem * 4
        rows = [
            ("fp32 (exact)", fp32_loss, 1.0),
            ("q8_ef (in-call EF)", ef_loss,
             fp32_bytes / get_codec("q8_ef").wire_bytes((nelem,),
                                                        jnp.float32)),
            ("q8 + carried EF", st_loss,
             fp32_bytes / get_codec("q8").wire_bytes((nelem,),
                                                     jnp.float32)),
        ]
        print(f"{'gradient sync':<22} {'final loss':>12} "
              f"{'wire reduction':>15}")
        for name, loss, ratio in rows:
            print(f"{name:<22} {loss:>12.6f} {ratio:>14.2f}x")
        print("params (fp32 run):", np.asarray(fp32_params))
    return fp32_loss, ef_loss, st_loss


if __name__ == "__main__":
    nranks = int(sys.argv[1]) if len(sys.argv) > 1 else 2
    mpi.run_ranks(main, nranks)

"""Crash-safe data-parallel training with checkpoint/resume.

Composes the framework's two persistence layers on the DP recipe of the
canonical regression example (reference: examples/
simple_linear_regression.py — the reference itself has no training-state
checkpointing, SURVEY.md §5):

* ``utils.CheckpointManager`` — step-numbered orbax checkpoints of the
  full train state (params + SGD momentum + step), atomic on disk;
* resume: a fresh process discovers ``latest_step()`` and continues; the
  resumed run is bit-identical to an uninterrupted one (asserted below).

Run:  python examples/checkpoint_resume.py [nranks] [workdir]
"""

import os
import sys
import tempfile

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

if os.environ.get("MPI4TORCH_TPU_REAL_DEVICES") != "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

import mpi4torch_tpu as mpi
from mpi4torch_tpu.utils import CheckpointManager

comm = mpi.COMM_WORLD

N_STEPS = 8
CRASH_AFTER = 3          # simulated preemption point
LR, MOMENTUM = 0.002, 0.9


def make_data(rank: int, size: int):
    xs = jnp.linspace(0.0, 1.0, 64 * size)
    ys = 3.0 * xs + 0.5
    lo = rank * 64
    return xs[lo:lo + 64], ys[lo:lo + 64]


def loss_fn(params, x, y):
    params = comm.Allreduce(params, mpi.MPI_SUM) / comm.size
    pred = params[0] * x + params[1]
    local = jnp.sum((pred - y) ** 2)
    return comm.Allreduce(local, mpi.MPI_SUM)


def train_step(state, x, y):
    loss, g = jax.value_and_grad(loss_fn)(state["params"], x, y)
    vel = MOMENTUM * state["vel"] + g
    return {"params": state["params"] - LR * vel, "vel": vel,
            "step": state["step"] + 1}, loss


def init_state():
    return {"params": jnp.zeros(2), "vel": jnp.zeros(2),
            "step": jnp.asarray(0, jnp.int32)}


def run(workdir: str, stop_after=None):
    """Train, checkpointing every step; resume from the latest step if
    checkpoints exist.  Only rank 0 touches disk (the eager world is
    threads in ONE process; a multi-process launch would checkpoint
    collectively instead)."""
    rank = int(comm.rank)
    x, y = make_data(rank, comm.size)
    state = init_state()
    mgr = CheckpointManager(workdir, max_to_keep=2) if rank == 0 else None
    start = 0
    if rank == 0 and mgr.latest_step() is not None:
        start = int(mgr.latest_step()) + 1
        state = mgr.restore(mgr.latest_step(), template=state)
    # Every rank resumes from the same state: broadcast rank 0's restore.
    state = jax.tree.map(lambda a: comm.Bcast_(a, 0), state)
    start = int(comm.Bcast_(jnp.asarray(start), 0))

    losses = []
    for step in range(start, N_STEPS):
        state, loss = train_step(state, x, y)
        losses.append(float(loss))
        if rank == 0:
            mgr.save(step, state)
        if stop_after is not None and step + 1 - start >= stop_after:
            break
    if rank == 0:
        mgr.wait_until_finished()
        mgr.close()
    return state, losses


def main(workdir=None):
    rank = int(comm.rank)
    if workdir is None and len(sys.argv) > 2:
        workdir = sys.argv[2]
    cleanup = False
    if workdir is None and rank == 0:
        # One scratch dir per invocation, chosen once on rank 0 — rank 0
        # is the only rank that touches disk (see run()), so the other
        # rank threads can keep workdir=None.  Cleaned up below.
        workdir = tempfile.mkdtemp(prefix="mpi4torch_tpu_ckpt_")
        cleanup = True

    # Uninterrupted reference run (separate directory).
    ref_state, ref_losses = run(f"{workdir}_ref" if workdir else None)

    # "Preempted" run: train CRASH_AFTER steps, drop everything, resume.
    run(workdir, stop_after=CRASH_AFTER)
    state, tail = run(workdir)

    np.testing.assert_array_equal(np.asarray(state["params"]),
                                  np.asarray(ref_state["params"]))
    assert int(state["step"]) == N_STEPS
    if rank == 0:
        # tail is empty when the workdir already held a completed run
        # (the example re-invoked on a persistent directory).
        last = (f"final loss {tail[-1]:.6f}" if tail
                else "checkpointed run already complete")
        print(f"rank 0: resumed run matches uninterrupted run "
              f"bit-for-bit at step {N_STEPS}; {last}")
        if cleanup:
            import shutil

            shutil.rmtree(workdir, ignore_errors=True)
            shutil.rmtree(f"{workdir}_ref", ignore_errors=True)
    return np.asarray(state["params"])


if __name__ == "__main__":
    nranks = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    outs = mpi.run_ranks(main, nranks)
    assert all(np.array_equal(outs[0], o) for o in outs)
    print(f"OK: {nranks} ranks, params {outs[0]}")

"""Data-parallel ResNet-18 on CIFAR-10-shaped data (BASELINE.md config #4).

The classic DDP recipe over mpi4torch_tpu's differentiable Allreduce: each
rank computes a local backward on its batch shard, then every parameter
gradient is averaged with one ``Allreduce(g, MPI_SUM) / size`` — the
per-param-grad pattern the reference enables but leaves to the user
(reference: README.md:34-46).  The whole step (forward, backward, N
gradient Allreduces, SGD update) is ONE jitted XLA program per rank; under
the SPMD mesh backend the Allreduces lower to ``psum`` over ICI.

Data is synthetic CIFAR-10-shaped (32x32x3, 10 classes) so the example runs
hermetically; swap ``make_synthetic_cifar`` for real numpy CIFAR batches and
nothing else changes.

Run:  python examples/resnet_cifar_dp.py [nranks] [steps]
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

if os.environ.get("MPI4TORCH_TPU_REAL_DEVICES") != "1":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4torch_tpu as mpi
from mpi4torch_tpu.models import resnet as R

comm = mpi.COMM_WORLD

CFG = R.ResNetConfig(num_classes=10)
BATCH_PER_RANK = 8
IMAGE_HW = 32


def make_synthetic_cifar(seed, n, hw, num_classes):
    rng = np.random.default_rng(seed)
    images = rng.standard_normal((n, hw, hw, 3)).astype(np.float32)
    labels = rng.integers(0, num_classes, n).astype(np.int32)
    return jnp.asarray(images), jnp.asarray(labels)


def main(steps: int = 3, cfg: R.ResNetConfig = CFG, hw: int = IMAGE_HW,
         batch_per_rank: int = BATCH_PER_RANK):
    params, state = R.init_resnet(jax.random.PRNGKey(0), cfg)

    # Every rank holds the full (here: synthetic) dataset and derives
    # its shard through the input pipeline: one seeded epoch
    # permutation shared by construction (no coordination collective),
    # static per-step shapes, and the next shard's host->device copy
    # prefetched behind the current step's compute.
    from mpi4torch_tpu.utils import prefetch_to_device, shard_batches_comm

    images, labels = make_synthetic_cifar(
        7, comm.size * batch_per_rank, hw, cfg.num_classes)
    data = (np.asarray(images), np.asarray(labels))

    def epochs():
        # One global batch per epoch: each epoch re-visits the same
        # example set under a fresh (seed, epoch) permutation, so the
        # global loss descends like plain repeated-batch GD while the
        # pipeline's reshuffle + rank partition are genuinely exercised.
        for epoch in range(steps):
            yield from shard_batches_comm(data, batch_per_rank, comm,
                                          seed=7, epoch=epoch)

    losses = []
    for batch in prefetch_to_device(epochs()):
        loss, params, state = R.dp_grad_train_step(
            comm, cfg, params, state, batch, lr=0.05)
        losses.append(float(loss))

    if comm.rank == 0:
        for i, l in enumerate(losses):
            print(f"step {i}: global loss {l:.4f}")
    head_w = np.asarray(params["head"]["w"])
    return losses, head_w


if __name__ == "__main__":
    nranks = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    results = mpi.run_ranks(lambda: main(steps), nranks)
    losses0, head0 = results[0]
    assert all(np.array_equal(head0, h) for _, h in results), "ranks diverged"
    assert losses0[-1] < losses0[0], losses0
    print(f"OK: {nranks}-rank DP ResNet-18 stayed in lock-step and the loss "
          f"fell {losses0[0]:.4f} -> {losses0[-1]:.4f}")

"""Distributed 2D stencil PDE loss via differentiable halo exchange.

BASELINE.md parity config #5: a 5-point-Laplacian residual loss on a 2D
periodic grid, row-partitioned across ranks.  Each evaluation exchanges
one-row halos with both neighbors over the differentiable Isend/Irecv/Wait
ring (:func:`mpi4torch_tpu.parallel.halo_exchange` — under the SPMD mesh
backend each matched send/recv pair lowers to one ``collective_permute``
riding the ICI torus), applies the stencil locally, and Allreduces the
squared residual.  Gradient descent on the field then drives
``lap(u) = g``: boundary-row gradients physically travel the reverse ring
(reference: csrc/extension.cpp:1159-1218 — the backward of a p2p pipeline
is the mirror-image pipeline).

The run is rank-count invariant up to floating-point summation order: the
globally-reduced loss/line-search scalars make N ranks follow the
single-rank trajectory (tests/test_examples.py asserts the solved fields
agree to 1e-8; the Allreduce groups partial sums differently, so low bits
may differ).

Run:  python examples/halo_exchange_stencil.py [nranks] [steps]
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

if os.environ.get("MPI4TORCH_TPU_REAL_DEVICES") != "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

import mpi4torch_tpu as mpi
from mpi4torch_tpu.parallel import halo_exchange
from mpi4torch_tpu.utils import LBFGS

comm = mpi.COMM_WORLD

GRID_N = 32  # global rows (divisible by any nranks used here)
GRID_M = 16  # columns


def source_term(n=GRID_N, m=GRID_M):
    """A smooth zero-mean RHS g with periodic structure."""
    i = jnp.arange(n)[:, None]
    j = jnp.arange(m)[None, :]
    return (jnp.sin(2 * jnp.pi * i / n) * jnp.cos(2 * jnp.pi * j / m)
            + 0.5 * jnp.sin(4 * jnp.pi * (i / n + j / m)))


def local_laplacian(u_local):
    """5-point periodic Laplacian of this rank's row block; the row
    neighbors come from the halo exchange, the column neighbors from a
    local roll."""
    padded = halo_exchange(comm, u_local, halo=1, axis=0)
    up, center, down = padded[:-2], padded[1:-1], padded[2:]
    left = jnp.roll(u_local, 1, axis=1)
    right = jnp.roll(u_local, -1, axis=1)
    return up + down + left + right - 4.0 * center


def residual_loss(u_local, g_local):
    res = local_laplacian(u_local) - g_local
    return comm.Allreduce(jnp.sum(res * res), mpi.MPI_SUM)


def main(steps: int = 80):
    """Solve ``lap(u) = g`` by L-BFGS on the distributed residual loss
    (the reference example's optimizer loop, scaled from 3 parameters to a
    whole field — examples/simple_linear_regression.py:42-53)."""
    if GRID_N % comm.size != 0:
        raise ValueError(
            f"GRID_N={GRID_N} rows must divide evenly over {comm.size} "
            "ranks (an uneven split would silently solve a truncated grid)")
    rows = GRID_N // comm.size
    start = jnp.asarray(comm.rank) * rows
    g_local = jax.lax.dynamic_slice_in_dim(source_term(), start, rows, 0)
    u = jnp.zeros((rows, GRID_M), jnp.float64)

    loss0 = float(residual_loss(u, g_local))
    # comm: u is domain-decomposed (each rank owns its row block), so the
    # line-search scalars must be global reductions to stay in lock-step.
    opt = LBFGS(max_iter=steps, comm=comm)
    u, loss = opt.step(lambda v: residual_loss(v, g_local), u)
    losses = [loss0, float(loss)]

    if comm.rank == 0:
        print(f"residual^2: {losses[0]:.6f} -> {losses[-1]:.3e} "
              f"(<= {steps} L-BFGS iters on {comm.size} rank(s))")
    return losses, np.asarray(u)


if __name__ == "__main__":
    nranks = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 80
    results = mpi.run_ranks(lambda: main(steps), nranks)
    losses0 = results[0][0]
    full = np.concatenate([u for _, u in results], axis=0)
    assert losses0[-1] < 1e-2 * losses0[0], losses0[-1]
    # The solution of lap(u)=g is unique only up to a constant on a
    # periodic domain; the zero-init gradient flow keeps the mean at 0.
    assert abs(full.mean()) < 1e-8
    print(f"OK: {nranks}-rank stencil converged, grid reassembled "
          f"{full.shape}, mean {full.mean():.2e}")

"""Long-context attention via ring (CP) and Ulysses (SP) parallelism.

The SURVEY.md §2.5 sequence-parallel demo: a sequence too long to attend
on one device is sharded across ranks; two strategies compute exact dense
attention over the full context from the reference's own primitive set:

* **ring** — K/V blocks circulate the differentiable Isend/Irecv ring
  (one ``collective_permute`` per hop under SPMD), merged by online
  softmax; per-rank memory is O(seq/ranks).  The per-block compute is the
  fused Pallas kernel on eligible TPU shapes.
* **ulysses** — two ``Alltoall`` calls reshuffle sequence<->head shards
  around fully-local per-head attention (the reference's
  ``Alltoall(gatheraxis != scatteraxis)`` is exactly this exchange,
  csrc/extension.cpp:917-987).

Both match the single-device oracle in values AND gradients — gradients
travel the reverse ring / inverse reshuffle.  Attention is causal, as in
a decoder.

Run:  python examples/ring_attention_longcontext.py [nranks] [seq_per_rank]
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

if os.environ.get("MPI4TORCH_TPU_REAL_DEVICES") != "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

import mpi4torch_tpu as mpi
from mpi4torch_tpu.parallel import (
    dense_attention,
    ring_attention,
    ulysses_attention,
)

comm = mpi.COMM_WORLD

BATCH, HEADS, HEAD_DIM = 2, 4, 16


def make_qkv(seq_total, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((BATCH, seq_total, HEADS, HEAD_DIM)))
        for _ in range(3))


def main(seq_per_rank: int = 16, attn: str = "ring"):
    """Each rank attends its sequence shard against the FULL context;
    returns (local output, local dq) for reassembly by the caller."""
    seq_total = comm.size * seq_per_rank
    q, k, v = make_qkv(seq_total)
    r = jnp.asarray(comm.rank)
    ql, kl, vl = (
        jax.lax.dynamic_slice_in_dim(t, r * seq_per_rank, seq_per_rank, 1)
        for t in (q, k, v))

    fn = ring_attention if attn == "ring" else ulysses_attention

    def f(ql):
        out = fn(comm, ql, kl, vl, causal=True)
        return jnp.sum(out ** 2), out

    (loss, out), dq = jax.value_and_grad(f, has_aux=True)(ql)
    return np.asarray(out), np.asarray(dq)


if __name__ == "__main__":
    nranks = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    seq_per_rank = int(sys.argv[2]) if len(sys.argv) > 2 else 16

    # Single-device oracle over the full context.
    q, k, v = make_qkv(nranks * seq_per_rank)
    ref_out = dense_attention(q, k, v, causal=True)
    ref_dq = jax.grad(
        lambda q: jnp.sum(dense_attention(q, k, v, causal=True) ** 2))(q)

    for attn in ("ring", "ulysses"):
        if attn == "ulysses" and HEADS % nranks != 0:
            print(f"skip ulysses: {HEADS} heads not divisible by {nranks}")
            continue
        results = mpi.run_ranks(lambda: main(seq_per_rank, attn), nranks)
        out = np.concatenate([o for o, _ in results], axis=1)
        dq = np.concatenate([g for _, g in results], axis=1)
        np.testing.assert_allclose(out, np.asarray(ref_out), rtol=1e-9,
                                   atol=1e-11)
        np.testing.assert_allclose(dq, np.asarray(ref_dq), rtol=1e-9,
                                   atol=1e-11)
        print(f"OK: {attn} attention on {nranks} ranks x {seq_per_rank} "
              f"tokens == dense oracle over {nranks * seq_per_rank} tokens "
              "(values + gradients)")

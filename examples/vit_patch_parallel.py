"""ViT with DP training plus patch-parallel inference.

Two phases over the communicator surface:

1. **DP training** — each rank trains the ViT on its batch shard with
   the classic per-gradient `Allreduce(g, MPI_SUM)/size` recipe
   (`models.vit.dp_grad_train_step`), through the deterministic input
   pipeline (`utils.shard_batches_comm` + `prefetch_to_device`).
2. **Patch-parallel inference** — the trained model classifies a batch
   with its PATCH axis sharded over the same ranks: each block's
   attention runs as NON-causal ring attention (every query attends
   every key through circulating KV shards — context parallelism
   without a causal cut), and the result must match the single-process
   forward exactly.

Run:  python examples/vit_patch_parallel.py [nranks] [steps]
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

if os.environ.get("MPI4TORCH_TPU_REAL_DEVICES") != "1":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import mpi4torch_tpu as mpi
from mpi4torch_tpu import COMM_WORLD as comm
from mpi4torch_tpu.models import vit as V
from mpi4torch_tpu.utils import prefetch_to_device, shard_batches_comm

CFG = V.ViTConfig(image_hw=16, patch=4, d_model=32, n_heads=4,
                  n_layers=2, d_ff=64, num_classes=10)


def synthetic_images(seed, n, cfg):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal(
        (n, cfg.image_hw, cfg.image_hw, cfg.channels)).astype(np.float32)
    y = rng.integers(0, cfg.num_classes, n).astype(np.int32)
    return x, y


def main(steps: int = 3, cfg: V.ViTConfig = CFG, batch_per_rank: int = 2):
    params = V.init_vit(jax.random.PRNGKey(0), cfg)
    data = synthetic_images(7, comm.size * batch_per_rank, cfg)

    def epochs():
        for epoch in range(steps):
            yield from shard_batches_comm(data, batch_per_rank, comm,
                                          seed=7, epoch=epoch)

    losses = []
    for batch in prefetch_to_device(epochs()):
        loss, params = V.dp_grad_train_step(comm, cfg, params, batch,
                                            lr=0.05)
        losses.append(float(loss))

    # Phase 2: classify with the patch axis sharded over the ranks.
    if cfg.n_patches % comm.size != 0:
        raise ValueError(
            f"patch parallelism needs the {cfg.n_patches} patches to "
            f"split evenly over {comm.size} ranks — run with a divisor "
            "rank count (ring attention's equal-shard layout)")
    images = jnp.asarray(data[0][:batch_per_rank])
    patches = V.patchify(cfg, images)
    sl = cfg.n_patches // comm.size
    local = patches[:, comm.rank * sl:(comm.rank + 1) * sl]
    sharded_logits = V.forward_patches(cfg, params, local, comm_sp=comm)
    single_logits = V.forward(cfg, params, images)

    if comm.rank == 0:
        for i, l in enumerate(losses):
            print(f"step {i}: global loss {l:.4f}")
    return (losses, np.asarray(params["head"]),
            np.asarray(sharded_logits), np.asarray(single_logits))


if __name__ == "__main__":
    nranks = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    results = mpi.run_ranks(lambda: main(steps), nranks)
    losses0, head0, shard0, single0 = results[0]
    assert all(np.array_equal(head0, h) for _, h, _, _ in results), \
        "ranks diverged"
    assert losses0[-1] < losses0[0], losses0
    for _, _, sh, si in results:
        np.testing.assert_allclose(sh, si, rtol=1e-5, atol=1e-6)
    print(f"OK: {nranks}-rank DP ViT trained in lock-step "
          f"({losses0[0]:.3f} -> {losses0[-1]:.3f}) and patch-parallel "
          f"ring-attention inference matched the single-process forward")
